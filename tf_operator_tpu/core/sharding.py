"""Sharded active-active control plane: lease-claimed job shards.

One leader owning every job (`core/leaderelection.py`) caps control-plane
capacity at a single process; worker pools (PR 5) scale within it but not
across it. This module shards job OWNERSHIP across N operator replicas:

- the job key space is split into a fixed ring of `--shards` shards by a
  consistent hash of the job's `namespace/name` (the queue-item identity —
  stable across job incarnations, known before any read, and identical on
  every replica);
- each shard is guarded by its own coordination.k8s.io Lease
  (`<lease-name>-shard-<i>`), claimed/renewed/stolen through the same
  `ClusterLeaseLock` OCC idiom the global election uses — two replicas can
  NEVER both hold a shard, so per-job exactly-once degrades to the
  single-leader argument shard by shard;
- replica membership is itself lease-based: every replica renews a
  `<lease-name>-member-<identity>` Lease and lists the member prefix, so
  all replicas converge on the same sorted live-member ranking and
  therefore the same target assignment (`shard % members == my_rank`)
  with no configuration of peer addresses;
- handoff is claim -> resync (the manager re-enqueues every job of the
  claimed shard and resets its expectations: a fresh owner has none of
  its predecessor's in-memory ledger, exactly like a cold-started
  process), drain-before-release on graceful rebalance (stop admitting
  the shard's keys, wait out in-flight syncs, then release so the next
  owner wins the lease immediately), and expiry-steal on crash (a dead
  replica stops renewing member + shard leases at once; survivors
  recompute targets and steal once the shard lease has sat unchanged a
  full duration on THEIR clock — the skew-safe observation rule).

Fleet-scale extensions (docs/design/sharded_control_plane.md):

- **Namespace-affinity rings** (`--shard-affinity namespace`): placement
  rendezvous-hashes the NAMESPACE first so one tenant's jobs co-locate
  on one replica's warm shard-scoped watch caches, with
  `--shard-affinity-spread` as the deterministic fallback toward the
  uniform per-key spread for tenants that outgrow one shard.
- **Live shard-count resize**: a config Lease (`<lock>-config`) carries
  (epoch, shards); replicas observing a newer epoch drain-and-release
  everything they own (the same drain-before-release protocol as a
  rebalance), adopt the new ring (epoch-qualified lease names so rings
  never contend), advertise the adoption on their member lease, and
  first-claim new-ring shards only once EVERY live member has adopted —
  the barrier that makes "no job synced by two replicas" hold across
  the migration. Published via `/debugz/resize` or SIGHUP +
  `--shards-file`; a resize is a per-shard claim resync, not a redeploy.

Single-replica default (`--shards 1`) builds none of this: the manager
keeps the PR 5 global `is_leader` gate and issues zero lease traffic, so
every seeded chaos/crash/stall tier replays byte-identical fault logs
and span sequences (the same capability-gating contract as parallel
fan-out, sync workers, and write coalescing).
"""

from __future__ import annotations

import functools
import hashlib
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..cluster.base import Conflict, NotFound
from .leaderelection import ClusterLeaseLock, _pod_namespace

log = logging.getLogger(__name__)

# A member lease that has not changed for this many lease durations on the
# observer's clock is garbage-collected (best-effort): dead replicas must
# not grow the member list forever, but the GC bound stays well past the
# liveness bound so a slow renewer is never deleted while still counted.
_MEMBER_GC_DURATIONS = 4.0

# shard_for_key placement modes. "uniform" is the PR 8 behavior (sha256
# of ns/name, byte-identical); "namespace" rendezvous-hashes the
# NAMESPACE first so one tenant's jobs co-locate on one replica's warm
# caches, falling back toward the uniform spread as --shard-affinity-
# spread grows (the lever for a tenant that outgrows one shard).
AFFINITY_UNIFORM = "uniform"
AFFINITY_NAMESPACE = "namespace"
AFFINITY_MODES = (AFFINITY_UNIFORM, AFFINITY_NAMESPACE)

# Labels stamped on shard-member leases so membership discovery can be a
# label-selected LIST (server-side on HTTP backends) instead of a scan of
# every lease in the namespace — at 10k jobs the heartbeat leases alone
# outnumber members 1000:1 (docs/design/sharded_control_plane.md).
LABEL_SHARD_MEMBER = "training.tpu/shard-member"
# Ring epoch the member has ADOPTED — the live-resize barrier: a replica
# first-claims new-ring shards only once every live member advertises the
# new epoch (all old-ring ownership provably released).
LABEL_RING_EPOCH = "training.tpu/ring-epoch"


def _uniform_hash(namespace: str, name: str) -> int:
    digest = hashlib.sha256(f"{namespace}/{name}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


@functools.lru_cache(maxsize=8192)
def _ranked_shards(namespace: str, shards: int) -> Tuple[int, ...]:
    """Rendezvous (highest-random-weight) ranking of the ring for one
    namespace: shard s scores sha256("ns@s"); the namespace's home is the
    top scorer. Rendezvous, not modulo, so a ring RESIZE moves a
    namespace only when a newly added shard out-scores its old home —
    minimal migration, which is what makes live resize cheap. Cached per
    (namespace, shards): the gate consults placement on every enqueue
    and pop."""
    scores = [
        (int.from_bytes(
            hashlib.sha256(f"{namespace}@{s}".encode()).digest()[:8], "big"),
         s)
        for s in range(shards)
    ]
    return tuple(s for _, s in sorted(scores, key=lambda p: (-p[0], p[1])))


def shard_for_key(namespace: str, name: str, shards: int,
                  affinity: str = AFFINITY_UNIFORM,
                  affinity_spread: int = 1) -> int:
    """Consistent shard id for one job key. Hashes the `namespace/name`
    queue-item identity (NOT the uid: the gate must place a key before
    any read, and a delete+recreate keeping its shard avoids a gratuitous
    ownership migration mid-churn). SHA-256 like every other seeded
    decision in this repo — identical placement on every replica, every
    run, every platform.

    affinity="namespace" biases placement so one tenant co-locates: the
    namespace's top `affinity_spread` rendezvous shards are the
    candidates and the uniform key hash picks among them. spread=1 (the
    default) puts the whole tenant on one shard — one replica's watch
    cache stays warm for it; spread=S degrades to the uniform per-key
    spread, the fallback for a tenant that outgrows a shard. Placement
    stays a pure function of (key, shards, config): every replica agrees
    with zero coordination, the same determinism contract as the ring
    itself."""
    if shards <= 1:
        return 0
    if affinity != AFFINITY_NAMESPACE:
        return _uniform_hash(namespace, name) % shards
    spread = min(max(int(affinity_spread), 1), shards)
    candidates = _ranked_shards(namespace, shards)[:spread]
    if spread == 1:
        return candidates[0]
    return candidates[_uniform_hash(namespace, name) % spread]


def shard_lease_name(lease_name: str, shard: int) -> str:
    return f"{lease_name}-shard-{shard}"


def ring_shard_lease_name(lease_name: str, epoch: int, shard: int) -> str:
    """Per-shard lease name, qualified by ring epoch once a live resize
    has happened: epoch 0 keeps the PR 8 names (`<lock>-shard-<i>`), so
    an unresized fleet is byte-identical; later epochs get
    `<lock>-r<epoch>-shard-<i>` so an old ring's leases and a new ring's
    can NEVER contend — the resize barrier, not lease OCC, is what keeps
    the rings exclusive."""
    if epoch <= 0:
        return shard_lease_name(lease_name, shard)
    return f"{lease_name}-r{epoch}-shard-{shard}"


def member_lease_prefix(lease_name: str) -> str:
    return f"{lease_name}-member-"


def config_lease_name(lease_name: str) -> str:
    return f"{lease_name}-config"


def read_ring_config(cluster, namespace: str,
                     lease_name: str) -> Optional[Tuple[int, int]]:
    """Read the ring-config lease: (epoch, shards) or None when no resize
    was ever published (epoch 0, the boot --shards ring). The config
    rides a Lease — the one object kind the coordinator already has RBAC
    and seams for — with `spec.holderIdentity = "shards=N"` and
    `spec.leaseTransitions` as the monotonically increasing epoch."""
    try:
        lease = cluster.get_lease(namespace, config_lease_name(lease_name))
    except NotFound:
        return None
    return _parse_ring_config(lease)


def _parse_ring_config(lease: dict) -> Optional[Tuple[int, int]]:
    spec = lease.get("spec") or {}
    holder = str(spec.get("holderIdentity") or "")
    if not holder.startswith("shards="):
        return None
    try:
        shards = int(holder.partition("=")[2])
        epoch = int(spec.get("leaseTransitions") or 0)
    except (TypeError, ValueError):
        return None
    if shards < 1 or epoch < 1:
        return None
    return epoch, shards


def publish_ring_resize(cluster, namespace: str, lease_name: str,
                        shards: int) -> int:
    """Publish a new ring size (the `/debugz/resize` verb and SIGHUP
    reload both land here): bump the config lease's epoch and record the
    target shard count. Every replica's next coordinator tick observes
    it and runs the drain-based migration. OCC via the lease's
    resourceVersion: two racing admins get one Conflict instead of two
    epochs. IDEMPOTENT on the target: re-publishing the count the config
    already carries returns the existing epoch without a bump — a SIGHUP
    with an unchanged --shards-file (routine config-reload convention)
    must not force a fleet-wide drain-and-reclaim for zero ring change.
    Returns the effective epoch."""
    if shards < 1:
        raise ValueError("shards must be >= 1")
    name = config_lease_name(lease_name)
    try:
        lease = cluster.get_lease(namespace, name)
    except NotFound:
        lease = None
    if lease is not None:
        current = _parse_ring_config(lease)
        if current is not None and current[1] == shards:
            return current[0]
    if lease is None:
        epoch = 1
        cluster.create_lease({
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"namespace": namespace, "name": name},
            "spec": {
                "holderIdentity": f"shards={shards}",
                "leaseTransitions": epoch,
            },
        })
        return epoch
    spec = lease.setdefault("spec", {})
    try:
        epoch = int(spec.get("leaseTransitions") or 0) + 1
    except (TypeError, ValueError):
        epoch = 1
    spec["holderIdentity"] = f"shards={shards}"
    spec["leaseTransitions"] = epoch
    cluster.update_lease(lease)
    return epoch


def resync_shard_jobs(controller, cluster, kind: str,
                      namespace: Optional[str], shard: int,
                      shards: int,
                      shard_of: Optional[Callable[[str, str], int]] = None,
                      ) -> int:
    """The claim half of the handoff protocol, single-sourced for the
    operator manager, the shard failover harness, and the flap-storm
    regression (three hand-rolled copies would silently drift as the
    protocol grows steps): reset the shard's pod/service expectations —
    a fresh owner has none of its predecessor's in-memory ledger, and
    waiting on OUR stale ledger from a previous stint would wedge each
    job for the expectation-expiry window — and re-enqueue every job of
    the shard (the cold-start resync_once idiom, shard-scoped). Returns
    the number of jobs covered.

    `shard_of` overrides the placement function (the coordinator's live
    ring view — shard count AND affinity mode); the plain `shards` int
    keeps the uniform-hash behavior for legacy callers."""
    if shard_of is None:
        shard_of = lambda ns, name: shard_for_key(ns, name, shards)  # noqa: E731
    count = 0
    for job in cluster.list_jobs(kind, namespace):
        meta = job.get("metadata", {}) or {}
        ns = meta.get("namespace", "default")
        name = meta.get("name", "")
        if shard_of(ns, name) != shard:
            continue
        key = f"{ns}/{name}"
        controller.expectations.delete_expectations(key, "pods")
        controller.expectations.delete_expectations(key, "services")
        controller._enqueue(ns, name)
        count += 1
    return count


class ShardCoordinator:
    """One replica's view of the shard ring: claims its target shards,
    renews what it holds, drains and releases what the membership says
    belongs elsewhere, and steals expired leases of dead owners.

    Driven by `tick()` from the manager's shard loop (or a test harness),
    never from a watch thread: every tick is a bounded number of lease
    reads plus one write per owned/target shard — all against the RAW
    cluster seam (no accounting, no throttle), so shard coordination is
    invisible to the per-job apiserver write attribution.

    `on_claim(shard, cause)` / `on_release(shard, cause)` fire from the
    tick thread AFTER the lease state changed; the manager uses them for
    the claim-resync handoff and the handoff metrics. Gating reads
    (`allows`, `owns_any`) are lock-protected and cheap — they run on
    every worker pop."""

    def __init__(
        self,
        cluster,
        shards: int,
        identity: str,
        namespace: Optional[str] = None,
        lease_name: str = "tf-operator-tpu-lock",
        duration: float = 15.0,
        clock=time.time,
        mono=None,
        on_claim: Optional[Callable[[int, str], None]] = None,
        on_release: Optional[Callable[[int, str], None]] = None,
        drain_check: Optional[Callable[[int], bool]] = None,
        drain_timeout: float = 30.0,
        affinity: str = AFFINITY_UNIFORM,
        affinity_spread: int = 1,
    ):
        if shards < 1:
            raise ValueError("shards must be >= 1")
        if affinity not in AFFINITY_MODES:
            raise ValueError(f"unknown shard affinity {affinity!r}")
        self.cluster = cluster
        self.shards = shards
        self.identity = identity
        self.namespace = namespace or _pod_namespace()
        self.lease_name = lease_name
        self.duration = duration
        # Placement mode (must be configured identically on every replica,
        # like --shards itself): see shard_for_key.
        self.affinity = affinity
        self.affinity_spread = affinity_spread
        self._clock = clock
        # Same monotonic-clock split as ClusterLeaseLock: liveness timers
        # must not move with NTP steps; fake-clock tests inject one clock
        # for both.
        self._mono = mono if mono is not None else (
            time.monotonic if clock is time.time else clock
        )
        self.on_claim = on_claim
        self.on_release = on_release
        # drain_check(shard) -> True when no sync of that shard's jobs is
        # in flight. None = always drained (single-threaded harnesses).
        self.drain_check = drain_check
        self.drain_timeout = drain_timeout
        # Live-resize state: the adopted ring epoch (0 = the boot ring,
        # legacy lease names) and, while a published resize is migrating,
        # the (epoch, shards) target. Mutated only on the tick thread.
        self.ring_epoch = 0
        self._resize_target: Optional[Tuple[int, int]] = None
        self._locks = self._build_locks()
        # Member lease labels: the selector that keeps membership listing
        # O(members) instead of O(all leases), plus the adopted ring
        # epoch — the resize barrier peers wait on.
        self._member_labels = {
            LABEL_SHARD_MEMBER: lease_name,
            LABEL_RING_EPOCH: "0",
        }
        self._member_lock = ClusterLeaseLock(
            cluster, namespace=self.namespace,
            name=f"{lease_name}-member-{identity}",
            clock=clock, mono=self._mono,
            labels=self._member_labels,
        )
        self._lock = threading.Lock()
        self._owned: Set[int] = set()
        self._draining: Set[int] = set()
        # Shards owned but still WARMING: the claim hooks (watch-cache
        # prime + claim resync) have not finished. The sync gate
        # (allows) excludes them — a worker syncing a just-claimed key
        # against a cache whose shard slice is still priming would read
        # an incomplete world — while the enqueue filter (admits) takes
        # them, so the claim resync's own enqueues are not dropped; the
        # post-pop gate re-checks and hands back until the warm-up
        # completes (a bounded sub-second window).
        self._warming: Set[int] = set()
        self._drain_since: Dict[int, float] = {}
        # Member-liveness observation: lease name -> (renew_raw, local
        # time the value last CHANGED). Liveness is "changed within one
        # duration on MY clock" — never a remote-timestamp comparison.
        self._member_obs: Dict[str, Tuple[str, float]] = {}
        self._live_members: List[str] = [identity]
        # Ring epoch each live member advertises (member-lease label);
        # refreshed by _compute_members, read by the resize claim barrier.
        self._member_epochs: Dict[str, int] = {identity: 0}
        # Last observed holder per shard (observability/debugz; advisory).
        self._holders: Dict[int, Optional[str]] = {}

    def _build_locks(self) -> List[ClusterLeaseLock]:
        return [
            ClusterLeaseLock(
                self.cluster, namespace=self.namespace,
                name=ring_shard_lease_name(self.lease_name, self.ring_epoch, i),
                clock=self._clock, mono=self._mono,
            )
            for i in range(self.shards)
        ]

    # ------------------------------------------------------------- gating
    def shard_of(self, namespace: str, name: str) -> int:
        return shard_for_key(namespace, name, self.shards,
                             self.affinity, self.affinity_spread)

    def admits(self, namespace: str, name: str) -> bool:
        """The ENQUEUE filter: this replica holds the job's shard and is
        not draining it. Warming shards (claim hooks still running) are
        admitted — the claim resync enqueues THROUGH this filter, and
        dropping its keys would lose the handoff."""
        shard = self.shard_of(namespace, name)
        with self._lock:
            return shard in self._owned and shard not in self._draining

    def allows(self, namespace: str, name: str) -> bool:
        """The per-key SYNC gate (the post-pop re-check, PR 5 rule, per
        key): admits AND the shard has finished warming — a sync must
        never run against a claim whose watch-cache prime is still in
        flight (it would read the primed-resource store as authoritative
        while the shard's slice is incomplete)."""
        shard = self.shard_of(namespace, name)
        with self._lock:
            return (shard in self._owned and shard not in self._draining
                    and shard not in self._warming)

    def owns(self, shard: int) -> bool:
        with self._lock:
            return shard in self._owned

    def owns_any(self) -> bool:
        with self._lock:
            return bool(self._owned - self._draining)

    def owned_shards(self) -> List[int]:
        with self._lock:
            return sorted(self._owned)

    def serving_shards(self) -> List[int]:
        """Owned AND admitting work (draining shards excluded) — the set
        the owned_shards gauge reports: a replica mid-rebalance still
        HOLDS the draining lease but is no longer serving its keys."""
        with self._lock:
            return sorted(self._owned - self._draining)

    def snapshot(self) -> dict:
        """Shard map for /debugz: per-shard holder (last observed),
        target owner under the current membership, and this replica's
        owned/draining view."""
        with self._lock:
            members = list(self._live_members)
            owned = sorted(self._owned)
            draining = sorted(self._draining)
            warming = sorted(self._warming)
            holders = dict(self._holders)
            member_epochs = dict(self._member_epochs)
            resize_target = self._resize_target
        targets = {
            s: members[s % len(members)] if members else None
            for s in range(self.shards)
        }
        return {
            "identity": self.identity,
            "shards": self.shards,
            "ring_epoch": self.ring_epoch,
            "affinity": self.affinity,
            "affinity_spread": self.affinity_spread,
            # Non-None while a published resize is mid-migration here:
            # (target epoch, target shard count). The member_epochs map
            # shows who the claim barrier is still waiting on.
            "resize_target": list(resize_target) if resize_target else None,
            "member_epochs": member_epochs,
            "members": members,
            "owned": owned,
            "draining": draining,
            "warming": warming,
            "holders": {str(s): holders.get(s) for s in range(self.shards)},
            "targets": {str(s): targets[s] for s in range(self.shards)},
        }

    # ------------------------------------------------------------ protocol
    def _renew_membership(self) -> None:
        """Keep our member lease fresh. A failed renew is survivable for
        the same renew-deadline window the shard locks grant; persistent
        failure lets peers rank us dead and drain toward the remainder —
        the safe direction."""
        try:
            self._member_lock.try_acquire(self.identity, self.duration)
        except Exception:  # noqa: BLE001 — a tick must never die here
            log.warning("member lease renew failed", exc_info=True)

    def _compute_members(self) -> List[str]:
        """Sorted live-member identities from the member-lease prefix.
        Every replica lists the same objects and applies the same
        observation rule, so rankings converge within one tick of any
        membership change. The LIST is label-selected (the
        LABEL_SHARD_MEMBER stamp every member lease carries) so it stays
        O(members) however many heartbeat/job leases share the namespace;
        the prefix remains a second, client-side filter. Also refreshes
        each live member's advertised ring epoch (the resize barrier)."""
        local = self._mono()
        prefix = member_lease_prefix(self.lease_name)
        try:
            try:
                leases = self.cluster.list_leases(
                    self.namespace, name_prefix=prefix,
                    labels={LABEL_SHARD_MEMBER: self.lease_name},
                )
            except TypeError:
                # Backend predating the labels parameter: prefix-only
                # (full-collection scan — correct, just not cheap).
                leases = self.cluster.list_leases(
                    self.namespace, name_prefix=prefix)
        except Exception:  # noqa: BLE001 — keep the last view on a blip
            log.warning("member lease list failed", exc_info=True)
            with self._lock:
                return list(self._live_members)
        live: Set[str] = {self.identity}
        epochs: Dict[str, int] = {self.identity: self.ring_epoch}
        seen_names: Set[str] = set()
        for lease in leases:
            meta = lease.get("metadata") or {}
            name = meta.get("name", "")
            ident = name[len(prefix):]
            if not ident:
                continue
            seen_names.add(name)
            spec = lease.get("spec") or {}
            renew_raw = str(spec.get("renewTime"))
            try:
                held = float(spec.get("leaseDurationSeconds"))
            except (TypeError, ValueError):
                held = self.duration
            with self._lock:
                prev = self._member_obs.get(name)
                if prev is None or prev[0] != renew_raw:
                    self._member_obs[name] = (renew_raw, local)
                    observed_at = local
                else:
                    observed_at = prev[1]
            if ident == self.identity or local < observed_at + held:
                live.add(ident)
                if ident != self.identity:
                    try:
                        epochs[ident] = int(
                            (meta.get("labels") or {}).get(
                                LABEL_RING_EPOCH, 0))
                    except (TypeError, ValueError):
                        epochs[ident] = 0
            elif local >= observed_at + held * _MEMBER_GC_DURATIONS:
                # Long-dead member: GC its lease so the roster doesn't
                # accrete one object per replica ever started. Best
                # effort — a racing peer's delete wins harmlessly.
                try:
                    self.cluster.delete_lease(self.namespace, name)
                except Exception:  # noqa: BLE001
                    pass
        with self._lock:
            for name in list(self._member_obs):
                if name not in seen_names:
                    self._member_obs.pop(name, None)
            self._live_members = sorted(live)
            self._member_epochs = epochs
            return list(self._live_members)

    def _targets(self, members: List[str]) -> Set[int]:
        """This replica's target shards under the given membership:
        `shard % len(members) == rank(identity)`. Deterministic and
        identical on every replica with the same view, so a stable
        membership yields a stable, non-overlapping assignment."""
        if self.identity not in members:
            return set()
        rank = members.index(self.identity)
        return {s for s in range(self.shards) if s % len(members) == rank}

    def _drained(self, shard: int) -> bool:
        if self.drain_check is None:
            return True
        try:
            return bool(self.drain_check(shard))
        except Exception:  # noqa: BLE001 — a broken check must not wedge
            log.warning("drain check failed; treating as drained", exc_info=True)
            return True

    def _check_ring_config(self) -> None:
        """Observe the published ring config; a NEWER epoch than ours
        starts the resize migration (drain everything, adopt, re-claim).
        One lease GET per tick — bounded, and invisible to per-job write
        attribution like all coordination traffic."""
        try:
            cfg = read_ring_config(self.cluster, self.namespace,
                                   self.lease_name)
        except Exception:  # noqa: BLE001 — a config blip must not kill ticks
            log.warning("ring config read failed", exc_info=True)
            return
        if cfg is None:
            return
        epoch, shards = cfg
        if epoch <= self.ring_epoch or self._resize_target == cfg:
            return
        log.info(
            "ring resize published: epoch %d -> %d, shards %d -> %d; "
            "draining all owned shards (%s)",
            self.ring_epoch, epoch, self.shards, shards, self.identity,
        )
        self._resize_target = cfg

    def _adopt_ring(self) -> None:
        """All old-ring ownership released: switch to the target ring and
        advertise the adoption on the member lease. First-claims on the
        new ring stay barred until EVERY live member advertises the same
        epoch (_claims_allowed) — released-by-all is what makes the two
        rings' disjoint lease names safe."""
        epoch, shards = self._resize_target
        old_epoch, old_shards = self.ring_epoch, self.shards
        with self._lock:
            self.ring_epoch = epoch
            self.shards = shards
            self._resize_target = None
            self._holders = {}
            self._member_epochs[self.identity] = epoch
        self._locks = self._build_locks()
        self._member_labels[LABEL_RING_EPOCH] = str(epoch)
        log.info(
            "ring adopted by %s: epoch %d (%d shards) -> epoch %d (%d shards)",
            self.identity, old_epoch, old_shards, epoch, shards,
        )

    def _claims_allowed(self) -> bool:
        """The resize barrier: new-ring FIRST-claims (renewals of shards
        already held are never barred) require every live member to have
        adopted our ring epoch — a laggard still advertising the old
        epoch may still hold old-ring leases over the same keys. A
        freshly booted epoch-0 replica trips this for at most one tick
        (it adopts on its first)."""
        with self._lock:
            epochs = dict(self._member_epochs)
            members = list(self._live_members)
        return all(epochs.get(m, 0) == self.ring_epoch for m in members)

    def tick(self) -> None:
        """One coordination round: observe the ring config (live resize),
        renew membership, recompute targets, then per shard acquire/
        renew/observe/drain as the assignment dictates. Cheap and
        bounded; the manager runs it every duration/3 like the elect
        loop."""
        self._check_ring_config()
        if self._resize_target is not None:
            with self._lock:
                still_owned = bool(self._owned)
            if not still_owned:
                self._adopt_ring()
        resizing = self._resize_target is not None
        self._renew_membership()
        members = self._compute_members()
        # Mid-resize every owned shard drains (targets empty); after
        # adoption, targets come from the new ring but first-claims wait
        # on the all-members-adopted barrier.
        targets = set() if resizing else self._targets(members)
        claims_ok = resizing or self._claims_allowed()
        for shard in range(self.shards):
            lock = self._locks[shard]
            with self._lock:
                mine = shard in self._owned
                draining = shard in self._draining
            if shard in targets and (mine or claims_ok):
                if draining:
                    # Re-targeted to us mid-drain (membership flapped
                    # back): cancel the drain and keep serving — but the
                    # drain window DROPPED this shard's enqueues (watch
                    # events, post-pop hand-backs hit the allows() gate),
                    # and since ownership never changed hands, no peer's
                    # claim resync covers the gap. Fire our own:
                    # cause="reclaim" runs the same expectation-reset +
                    # re-enqueue handoff a real claim runs.
                    with self._lock:
                        self._draining.discard(shard)
                        self._drain_since.pop(shard, None)
                        self._warming.add(shard)
                    try:
                        self._notify(self.on_claim, shard, "reclaim")
                    finally:
                        with self._lock:
                            self._warming.discard(shard)
                self._try_claim(shard, lock, mine)
            elif mine:
                self._drain_and_release(
                    shard, lock, cause="resize" if resizing else "rebalance")
            else:
                # Foreign shard (or a target we may not first-claim yet —
                # the resize barrier): observe only, so the expiry timer
                # is already armed if a membership change later targets
                # it here (steal latency = one tick, not one extra
                # duration), and /debugz can show the full holder map.
                self._holders[shard] = lock.observe()

    def _try_claim(self, shard: int, lock: ClusterLeaseLock, mine: bool) -> None:
        try:
            got = lock.try_acquire(self.identity, self.duration)
        except Exception:  # noqa: BLE001 — abdicate the shard, not the tick
            log.warning("shard %d claim round raised", shard, exc_info=True)
            got = False
        self._holders[shard] = self.identity if got else lock.last_holder_seen
        if got and not mine:
            # Fresh claim: free/released lease = "claim"; a lease whose
            # last holder was a (now-expired) peer = "steal". The shard
            # WARMS until the claim hooks (cache prime + resync) finish:
            # owned (deltas apply, enqueues admitted) but not yet synced.
            cause = (
                "steal"
                if lock.last_holder_seen not in (None, "", self.identity)
                else "claim"
            )
            with self._lock:
                self._owned.add(shard)
                self._warming.add(shard)
            log.info("shard %d %sed by %s", shard, cause, self.identity)
            try:
                self._notify(self.on_claim, shard, cause)
            finally:
                with self._lock:
                    self._warming.discard(shard)
        elif not got and mine:
            # Lost a held shard (stolen, or renew errors past the
            # deadline): gate off IMMEDIATELY — the new holder's claim
            # resync re-enqueues everything, so dropping our queue's
            # copies is safe, while syncing beside the new owner is not.
            with self._lock:
                self._owned.discard(shard)
                self._draining.discard(shard)
                self._warming.discard(shard)
                self._drain_since.pop(shard, None)
            log.warning("shard %d lost by %s", shard, self.identity)
            self._notify(self.on_release, shard, "lost")

    def _drain_and_release(self, shard: int, lock: ClusterLeaseLock,
                           cause: str = "rebalance") -> None:
        """Graceful rebalance (or resize migration — same drain protocol,
        cause="resize"): the membership re-assigned a shard we hold.
        Gate its keys off (allows() excludes draining shards), keep
        RENEWING while in-flight syncs finish — releasing mid-sync would
        let the next owner start beside us — then release so the target
        owner wins the very next tick instead of waiting out expiry."""
        with self._lock:
            if shard not in self._draining:
                self._draining.add(shard)
                self._drain_since[shard] = self._mono()
            started = self._drain_since[shard]
        if not self._drained(shard):
            if self._mono() < started + self.drain_timeout:
                try:
                    if not lock.try_acquire(self.identity, self.duration):
                        # Stolen out from under the drain: same as lost.
                        self._try_claim_lost(shard)
                    return
                except Exception:  # noqa: BLE001
                    log.warning("shard %d drain renew raised", shard,
                                exc_info=True)
                    return
            log.warning(
                "shard %d drain timed out after %.1fs; releasing anyway",
                shard, self.drain_timeout,
            )
        lock.release(self.identity)
        with self._lock:
            self._owned.discard(shard)
            self._draining.discard(shard)
            self._drain_since.pop(shard, None)
        self._holders[shard] = None
        log.info("shard %d released by %s (%s)", shard, self.identity, cause)
        self._notify(self.on_release, shard, cause)

    def _try_claim_lost(self, shard: int) -> None:
        with self._lock:
            self._owned.discard(shard)
            self._draining.discard(shard)
            self._warming.discard(shard)
            self._drain_since.pop(shard, None)
        self._notify(self.on_release, shard, "lost")

    def _notify(self, hook, shard: int, cause: str) -> None:
        if hook is None:
            return
        try:
            hook(shard, cause)
        except Exception:  # noqa: BLE001 — observer errors never stall claims
            log.warning("shard hook failed for shard %d", shard, exc_info=True)

    def request_resize(self, shards: int) -> int:
        """Publish a new ring size through the config lease; every
        replica (this one included) observes it on its next tick and
        runs the drain-based migration. Returns the published epoch."""
        return publish_ring_resize(
            self.cluster, self.namespace, self.lease_name, shards)

    # ----------------------------------------------------------- lifecycle
    def shutdown(self, sleep=time.sleep) -> None:
        """Clean exit: drain and release every owned shard (standbys win
        the next tick, like the global lock's ReleaseOnCancel) and delete
        our member lease so peers re-rank without waiting out liveness.
        EVERY step tolerates apiserver failure — a crashing replica must
        never wedge its own shutdown on a lease it can no longer write."""
        with self._lock:
            owned = sorted(self._owned)
            self._draining.update(owned)
        for shard in owned:
            deadline = self._mono() + self.drain_timeout
            while not self._drained(shard) and self._mono() < deadline:
                sleep(0.05)
            try:
                self._locks[shard].release(self.identity)
            except Exception:  # noqa: BLE001
                log.debug("shard %d release failed at shutdown", shard,
                          exc_info=True)
            self._notify(self.on_release, shard, "shutdown")
        with self._lock:
            self._owned.clear()
            self._draining.clear()
            self._drain_since.clear()
        try:
            self.cluster.delete_lease(
                self.namespace, f"{self.lease_name}-member-{self.identity}"
            )
        except (NotFound, Conflict):
            pass
        except Exception:  # noqa: BLE001
            log.debug("member lease delete failed at shutdown", exc_info=True)
