"""Sharded active-active control plane: lease-claimed job shards.

One leader owning every job (`core/leaderelection.py`) caps control-plane
capacity at a single process; worker pools (PR 5) scale within it but not
across it. This module shards job OWNERSHIP across N operator replicas:

- the job key space is split into a fixed ring of `--shards` shards by a
  consistent hash of the job's `namespace/name` (the queue-item identity —
  stable across job incarnations, known before any read, and identical on
  every replica);
- each shard is guarded by its own coordination.k8s.io Lease
  (`<lease-name>-shard-<i>`), claimed/renewed/stolen through the same
  `ClusterLeaseLock` OCC idiom the global election uses — two replicas can
  NEVER both hold a shard, so per-job exactly-once degrades to the
  single-leader argument shard by shard;
- replica membership is itself lease-based: every replica renews a
  `<lease-name>-member-<identity>` Lease and lists the member prefix, so
  all replicas converge on the same sorted live-member ranking and
  therefore the same target assignment (`shard % members == my_rank`)
  with no configuration of peer addresses;
- handoff is claim -> resync (the manager re-enqueues every job of the
  claimed shard and resets its expectations: a fresh owner has none of
  its predecessor's in-memory ledger, exactly like a cold-started
  process), drain-before-release on graceful rebalance (stop admitting
  the shard's keys, wait out in-flight syncs, then release so the next
  owner wins the lease immediately), and expiry-steal on crash (a dead
  replica stops renewing member + shard leases at once; survivors
  recompute targets and steal once the shard lease has sat unchanged a
  full duration on THEIR clock — the skew-safe observation rule).

Single-replica default (`--shards 1`) builds none of this: the manager
keeps the PR 5 global `is_leader` gate and issues zero lease traffic, so
every seeded chaos/crash/stall tier replays byte-identical fault logs
and span sequences (the same capability-gating contract as parallel
fan-out, sync workers, and write coalescing).
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..cluster.base import Conflict, NotFound
from .leaderelection import ClusterLeaseLock, _pod_namespace

log = logging.getLogger(__name__)

# A member lease that has not changed for this many lease durations on the
# observer's clock is garbage-collected (best-effort): dead replicas must
# not grow the member list forever, but the GC bound stays well past the
# liveness bound so a slow renewer is never deleted while still counted.
_MEMBER_GC_DURATIONS = 4.0


def shard_for_key(namespace: str, name: str, shards: int) -> int:
    """Consistent shard id for one job key. Hashes the `namespace/name`
    queue-item identity (NOT the uid: the gate must place a key before
    any read, and a delete+recreate keeping its shard avoids a gratuitous
    ownership migration mid-churn). SHA-256 like every other seeded
    decision in this repo — identical placement on every replica, every
    run, every platform."""
    if shards <= 1:
        return 0
    digest = hashlib.sha256(f"{namespace}/{name}".encode()).digest()
    return int.from_bytes(digest[:8], "big") % shards


def shard_lease_name(lease_name: str, shard: int) -> str:
    return f"{lease_name}-shard-{shard}"


def member_lease_prefix(lease_name: str) -> str:
    return f"{lease_name}-member-"


def resync_shard_jobs(controller, cluster, kind: str,
                      namespace: Optional[str], shard: int,
                      shards: int) -> int:
    """The claim half of the handoff protocol, single-sourced for the
    operator manager, the shard failover harness, and the flap-storm
    regression (three hand-rolled copies would silently drift as the
    protocol grows steps): reset the shard's pod/service expectations —
    a fresh owner has none of its predecessor's in-memory ledger, and
    waiting on OUR stale ledger from a previous stint would wedge each
    job for the expectation-expiry window — and re-enqueue every job of
    the shard (the cold-start resync_once idiom, shard-scoped). Returns
    the number of jobs covered."""
    count = 0
    for job in cluster.list_jobs(kind, namespace):
        meta = job.get("metadata", {}) or {}
        ns = meta.get("namespace", "default")
        name = meta.get("name", "")
        if shard_for_key(ns, name, shards) != shard:
            continue
        key = f"{ns}/{name}"
        controller.expectations.delete_expectations(key, "pods")
        controller.expectations.delete_expectations(key, "services")
        controller._enqueue(ns, name)
        count += 1
    return count


class ShardCoordinator:
    """One replica's view of the shard ring: claims its target shards,
    renews what it holds, drains and releases what the membership says
    belongs elsewhere, and steals expired leases of dead owners.

    Driven by `tick()` from the manager's shard loop (or a test harness),
    never from a watch thread: every tick is a bounded number of lease
    reads plus one write per owned/target shard — all against the RAW
    cluster seam (no accounting, no throttle), so shard coordination is
    invisible to the per-job apiserver write attribution.

    `on_claim(shard, cause)` / `on_release(shard, cause)` fire from the
    tick thread AFTER the lease state changed; the manager uses them for
    the claim-resync handoff and the handoff metrics. Gating reads
    (`allows`, `owns_any`) are lock-protected and cheap — they run on
    every worker pop."""

    def __init__(
        self,
        cluster,
        shards: int,
        identity: str,
        namespace: Optional[str] = None,
        lease_name: str = "tf-operator-tpu-lock",
        duration: float = 15.0,
        clock=time.time,
        mono=None,
        on_claim: Optional[Callable[[int, str], None]] = None,
        on_release: Optional[Callable[[int, str], None]] = None,
        drain_check: Optional[Callable[[int], bool]] = None,
        drain_timeout: float = 30.0,
    ):
        if shards < 1:
            raise ValueError("shards must be >= 1")
        self.cluster = cluster
        self.shards = shards
        self.identity = identity
        self.namespace = namespace or _pod_namespace()
        self.lease_name = lease_name
        self.duration = duration
        self._clock = clock
        # Same monotonic-clock split as ClusterLeaseLock: liveness timers
        # must not move with NTP steps; fake-clock tests inject one clock
        # for both.
        self._mono = mono if mono is not None else (
            time.monotonic if clock is time.time else clock
        )
        self.on_claim = on_claim
        self.on_release = on_release
        # drain_check(shard) -> True when no sync of that shard's jobs is
        # in flight. None = always drained (single-threaded harnesses).
        self.drain_check = drain_check
        self.drain_timeout = drain_timeout
        self._locks = [
            ClusterLeaseLock(
                cluster, namespace=self.namespace,
                name=shard_lease_name(lease_name, i),
                clock=clock, mono=self._mono,
            )
            for i in range(shards)
        ]
        self._member_lock = ClusterLeaseLock(
            cluster, namespace=self.namespace,
            name=f"{lease_name}-member-{identity}",
            clock=clock, mono=self._mono,
        )
        self._lock = threading.Lock()
        self._owned: Set[int] = set()
        self._draining: Set[int] = set()
        self._drain_since: Dict[int, float] = {}
        # Member-liveness observation: lease name -> (renew_raw, local
        # time the value last CHANGED). Liveness is "changed within one
        # duration on MY clock" — never a remote-timestamp comparison.
        self._member_obs: Dict[str, Tuple[str, float]] = {}
        self._live_members: List[str] = [identity]
        # Last observed holder per shard (observability/debugz; advisory).
        self._holders: Dict[int, Optional[str]] = {}

    # ------------------------------------------------------------- gating
    def shard_of(self, namespace: str, name: str) -> int:
        return shard_for_key(namespace, name, self.shards)

    def allows(self, namespace: str, name: str) -> bool:
        """The per-key sync gate: this replica holds the job's shard and
        is not draining it. Checked at enqueue AND re-checked after the
        blocking queue pop (the PR 5 post-pop rule, per key)."""
        shard = self.shard_of(namespace, name)
        with self._lock:
            return shard in self._owned and shard not in self._draining

    def owns(self, shard: int) -> bool:
        with self._lock:
            return shard in self._owned

    def owns_any(self) -> bool:
        with self._lock:
            return bool(self._owned - self._draining)

    def owned_shards(self) -> List[int]:
        with self._lock:
            return sorted(self._owned)

    def serving_shards(self) -> List[int]:
        """Owned AND admitting work (draining shards excluded) — the set
        the owned_shards gauge reports: a replica mid-rebalance still
        HOLDS the draining lease but is no longer serving its keys."""
        with self._lock:
            return sorted(self._owned - self._draining)

    def snapshot(self) -> dict:
        """Shard map for /debugz: per-shard holder (last observed),
        target owner under the current membership, and this replica's
        owned/draining view."""
        with self._lock:
            members = list(self._live_members)
            owned = sorted(self._owned)
            draining = sorted(self._draining)
            holders = dict(self._holders)
        targets = {
            s: members[s % len(members)] if members else None
            for s in range(self.shards)
        }
        return {
            "identity": self.identity,
            "shards": self.shards,
            "members": members,
            "owned": owned,
            "draining": draining,
            "holders": {str(s): holders.get(s) for s in range(self.shards)},
            "targets": {str(s): targets[s] for s in range(self.shards)},
        }

    # ------------------------------------------------------------ protocol
    def _renew_membership(self) -> None:
        """Keep our member lease fresh. A failed renew is survivable for
        the same renew-deadline window the shard locks grant; persistent
        failure lets peers rank us dead and drain toward the remainder —
        the safe direction."""
        try:
            self._member_lock.try_acquire(self.identity, self.duration)
        except Exception:  # noqa: BLE001 — a tick must never die here
            log.warning("member lease renew failed", exc_info=True)

    def _compute_members(self) -> List[str]:
        """Sorted live-member identities from the member-lease prefix.
        Every replica lists the same objects and applies the same
        observation rule, so rankings converge within one tick of any
        membership change."""
        local = self._mono()
        prefix = member_lease_prefix(self.lease_name)
        try:
            leases = self.cluster.list_leases(self.namespace, name_prefix=prefix)
        except Exception:  # noqa: BLE001 — keep the last view on a blip
            log.warning("member lease list failed", exc_info=True)
            with self._lock:
                return list(self._live_members)
        live: Set[str] = {self.identity}
        seen_names: Set[str] = set()
        for lease in leases:
            meta = lease.get("metadata") or {}
            name = meta.get("name", "")
            ident = name[len(prefix):]
            if not ident:
                continue
            seen_names.add(name)
            spec = lease.get("spec") or {}
            renew_raw = str(spec.get("renewTime"))
            try:
                held = float(spec.get("leaseDurationSeconds"))
            except (TypeError, ValueError):
                held = self.duration
            with self._lock:
                prev = self._member_obs.get(name)
                if prev is None or prev[0] != renew_raw:
                    self._member_obs[name] = (renew_raw, local)
                    observed_at = local
                else:
                    observed_at = prev[1]
            if ident == self.identity or local < observed_at + held:
                live.add(ident)
            elif local >= observed_at + held * _MEMBER_GC_DURATIONS:
                # Long-dead member: GC its lease so the roster doesn't
                # accrete one object per replica ever started. Best
                # effort — a racing peer's delete wins harmlessly.
                try:
                    self.cluster.delete_lease(self.namespace, name)
                except Exception:  # noqa: BLE001
                    pass
        with self._lock:
            for name in list(self._member_obs):
                if name not in seen_names:
                    self._member_obs.pop(name, None)
            self._live_members = sorted(live)
            return list(self._live_members)

    def _targets(self, members: List[str]) -> Set[int]:
        """This replica's target shards under the given membership:
        `shard % len(members) == rank(identity)`. Deterministic and
        identical on every replica with the same view, so a stable
        membership yields a stable, non-overlapping assignment."""
        if self.identity not in members:
            return set()
        rank = members.index(self.identity)
        return {s for s in range(self.shards) if s % len(members) == rank}

    def _drained(self, shard: int) -> bool:
        if self.drain_check is None:
            return True
        try:
            return bool(self.drain_check(shard))
        except Exception:  # noqa: BLE001 — a broken check must not wedge
            log.warning("drain check failed; treating as drained", exc_info=True)
            return True

    def tick(self) -> None:
        """One coordination round: renew membership, recompute targets,
        then per shard acquire/renew/observe/drain as the assignment
        dictates. Cheap and bounded; the manager runs it every
        duration/3 like the elect loop."""
        self._renew_membership()
        members = self._compute_members()
        targets = self._targets(members)
        for shard in range(self.shards):
            lock = self._locks[shard]
            with self._lock:
                mine = shard in self._owned
                draining = shard in self._draining
            if shard in targets:
                if draining:
                    # Re-targeted to us mid-drain (membership flapped
                    # back): cancel the drain and keep serving — but the
                    # drain window DROPPED this shard's enqueues (watch
                    # events, post-pop hand-backs hit the allows() gate),
                    # and since ownership never changed hands, no peer's
                    # claim resync covers the gap. Fire our own:
                    # cause="reclaim" runs the same expectation-reset +
                    # re-enqueue handoff a real claim runs.
                    with self._lock:
                        self._draining.discard(shard)
                        self._drain_since.pop(shard, None)
                    self._notify(self.on_claim, shard, "reclaim")
                self._try_claim(shard, lock, mine)
            elif mine:
                self._drain_and_release(shard, lock)
            else:
                # Foreign shard: observe only, so the expiry timer is
                # already armed if a membership change later targets it
                # here (steal latency = one tick, not one extra
                # duration), and /debugz can show the full holder map.
                self._holders[shard] = lock.observe()

    def _try_claim(self, shard: int, lock: ClusterLeaseLock, mine: bool) -> None:
        try:
            got = lock.try_acquire(self.identity, self.duration)
        except Exception:  # noqa: BLE001 — abdicate the shard, not the tick
            log.warning("shard %d claim round raised", shard, exc_info=True)
            got = False
        self._holders[shard] = self.identity if got else lock.last_holder_seen
        if got and not mine:
            # Fresh claim: free/released lease = "claim"; a lease whose
            # last holder was a (now-expired) peer = "steal".
            cause = (
                "steal"
                if lock.last_holder_seen not in (None, "", self.identity)
                else "claim"
            )
            with self._lock:
                self._owned.add(shard)
            log.info("shard %d %sed by %s", shard, cause, self.identity)
            self._notify(self.on_claim, shard, cause)
        elif not got and mine:
            # Lost a held shard (stolen, or renew errors past the
            # deadline): gate off IMMEDIATELY — the new holder's claim
            # resync re-enqueues everything, so dropping our queue's
            # copies is safe, while syncing beside the new owner is not.
            with self._lock:
                self._owned.discard(shard)
                self._draining.discard(shard)
                self._drain_since.pop(shard, None)
            log.warning("shard %d lost by %s", shard, self.identity)
            self._notify(self.on_release, shard, "lost")

    def _drain_and_release(self, shard: int, lock: ClusterLeaseLock) -> None:
        """Graceful rebalance: the membership re-assigned a shard we
        hold. Gate its keys off (allows() excludes draining shards), keep
        RENEWING while in-flight syncs finish — releasing mid-sync would
        let the next owner start beside us — then release so the target
        owner wins the very next tick instead of waiting out expiry."""
        with self._lock:
            if shard not in self._draining:
                self._draining.add(shard)
                self._drain_since[shard] = self._mono()
            started = self._drain_since[shard]
        if not self._drained(shard):
            if self._mono() < started + self.drain_timeout:
                try:
                    if not lock.try_acquire(self.identity, self.duration):
                        # Stolen out from under the drain: same as lost.
                        self._try_claim_lost(shard)
                    return
                except Exception:  # noqa: BLE001
                    log.warning("shard %d drain renew raised", shard,
                                exc_info=True)
                    return
            log.warning(
                "shard %d drain timed out after %.1fs; releasing anyway",
                shard, self.drain_timeout,
            )
        lock.release(self.identity)
        with self._lock:
            self._owned.discard(shard)
            self._draining.discard(shard)
            self._drain_since.pop(shard, None)
        self._holders[shard] = None
        log.info("shard %d released by %s (rebalance)", shard, self.identity)
        self._notify(self.on_release, shard, "rebalance")

    def _try_claim_lost(self, shard: int) -> None:
        with self._lock:
            self._owned.discard(shard)
            self._draining.discard(shard)
            self._drain_since.pop(shard, None)
        self._notify(self.on_release, shard, "lost")

    def _notify(self, hook, shard: int, cause: str) -> None:
        if hook is None:
            return
        try:
            hook(shard, cause)
        except Exception:  # noqa: BLE001 — observer errors never stall claims
            log.warning("shard hook failed for shard %d", shard, exc_info=True)

    # ----------------------------------------------------------- lifecycle
    def shutdown(self, sleep=time.sleep) -> None:
        """Clean exit: drain and release every owned shard (standbys win
        the next tick, like the global lock's ReleaseOnCancel) and delete
        our member lease so peers re-rank without waiting out liveness.
        EVERY step tolerates apiserver failure — a crashing replica must
        never wedge its own shutdown on a lease it can no longer write."""
        with self._lock:
            owned = sorted(self._owned)
            self._draining.update(owned)
        for shard in owned:
            deadline = self._mono() + self.drain_timeout
            while not self._drained(shard) and self._mono() < deadline:
                sleep(0.05)
            try:
                self._locks[shard].release(self.identity)
            except Exception:  # noqa: BLE001
                log.debug("shard %d release failed at shutdown", shard,
                          exc_info=True)
            self._notify(self.on_release, shard, "shutdown")
        with self._lock:
            self._owned.clear()
            self._draining.clear()
            self._drain_since.clear()
        try:
            self.cluster.delete_lease(
                self.namespace, f"{self.lease_name}-member-{self.identity}"
            )
        except (NotFound, Conflict):
            pass
        except Exception:  # noqa: BLE001
            log.debug("member lease delete failed at shutdown", exc_info=True)
