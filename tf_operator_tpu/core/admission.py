"""Capacity-aware gang admission: quota'd queueing, priority preemption,
and bounded backfill (docs/design/gang_admission.md).

The reference operator fires PodGroups at Volcano and forgets them; the
gang unit here (per-slice PodGroups, the JOB_QUEUED condition) already
exists but admission was first-come and capacity-blind — under contention
jobs race, deadlock on partial gangs, or starve. This module is the
operator-side admission arbiter the Gavel line of work (arXiv:2008.09213)
argues for: a declared capacity pool, all-or-nothing job admission (a
job's pods stay UNBORN while it queues — no partial gang can ever exist),
per-tenant (namespace) quotas, priority bands from
``SchedulingPolicy.priorityClass``, preempt-lowest-priority-gang on
contention, and bounded backfill of small gangs into capacity gaps with
an aging bound so backfill can never starve the head-of-line gang.

Everything is deterministic given a deterministic call sequence and
clock: decisions are pure functions of (queue, pool, usage, seed) — the
DECISION PROCEDURE itself lives behind the policy seam in
core/policies.py (`policy.decide(PolicyState) -> Decisions`, selected
by --admission-policy: the default `priority` policy is the original
arbiter byte-for-byte; `gavel` adds heterogeneity-aware placement over
device-generation sub-pools; `drf` replaces hard quotas with weighted
work-conserving fairness). This class owns registration, decision
APPLICATION (in the policy's order), the preemption handshake, and the
audit ledgers — including the decision log, the byte-equality artifact
of the determinism contract. Seeded chaos/crash tiers replay
byte-identically with admission ON, and with the flag OFF (the default)
the engine never constructs this object at all and the PR 1–8 behavior
is untouched byte-for-byte.

Ordering rules of the DEFAULT policy, in one place:

- The wait queue is ordered by (band desc, seq asc): higher priority
  bands first, FIFO within a band. ``seq`` is a monotonic admission-
  controller sequence; a preempted gang re-enters at the HEAD of its
  band (seq below every current waiter of that band).
- The head-of-line is the first waiting gang whose own namespace quota
  would allow it (a tenant that exhausted its own quota must not hold
  the line against other tenants — its wait can only end with its own
  releases).
- A non-head gang may only be BACKFILLED: it must fit the free gap, its
  member count must not exceed ``backfill_max_members``, and the
  head-of-line must not have waited past ``aging_seconds`` — once the
  head ages past the bound, backfill stops until the head admits
  (starvation-freedom; audited from the admit log by
  testing/invariants.py).
- When the head does not fit, admitted gangs of STRICTLY lower band are
  preempted — lowest band first, most-recently-admitted first — until
  the head would fit. Victims are only MARKED here; the engine routes
  the teardown through the count-before-teardown disruption protocol
  and acknowledges with :meth:`note_preempted` once the counted write
  is durable, so the preemption lands in the budget-free
  ``disruptionCounts`` ledger exactly once.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from fractions import Fraction
from typing import Callable, Dict, List, Optional, Tuple

from .job_controller import parse_quantity
from .policies import (
    Admit,
    AdmissionPolicy,
    GangView,
    PolicyState,
    PREEMPT_CAUSE_CAPACITY,
    PREEMPT_CAUSE_PRIORITY,
    PREEMPT_CAUSE_THROUGHPUT,
    Preempt,
    build_policy,
    ratio_of,
)

# Priority bands for SchedulingPolicy.priorityClass. Scheduler-style
# class names map onto small integers; bare non-negative integers are
# accepted verbatim so clusters with numeric PriorityClass conventions
# can express finer ladders. Other legal PriorityClass names ride the
# DEFAULT band (never band 0 — an unrecognized name must not make a job
# globally preemptible); only un-nameable values (negative, non-DNS) are
# ValidationErrors at admission (api/defaulting.py).
PRIORITY_CLASSES = {
    "low": 0,
    "preemptible": 0,
    "best-effort": 0,
    "": 1,
    "default": 1,
    "normal": 1,
    "high": 2,
    "critical": 3,
}

# Preemption causes (the gang_preemptions_total{cause} label values):
# defined once in core/policies.py (the emitting side) and re-exported
# here, the historical import home — one source of truth, no drift.


import re as _re

# A legal Kubernetes PriorityClass name (DNS-1123 subdomain shape). Names
# outside the band vocabulary but inside this shape are legitimate
# cluster PriorityClasses the operator merely has no band opinion about —
# they ride the default band (and pass through to the PodGroup verbatim,
# exactly as before this layer existed). Anything outside the shape can
# never name a real PriorityClass and is a typed ValidationError.
_K8S_NAME_RE = _re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")


def parse_priority_class(value) -> int:
    """Band of a priorityClass: a known band name (case-insensitive), a
    bare non-negative integer, or any OTHER legal PriorityClass name —
    which maps to the default band (the operator ranks only its own band
    vocabulary; foreign class names are Volcano's business and must keep
    flowing through untouched). Raises ValueError only for values that
    could never name a PriorityClass: negatives (they would sort below
    every band and permanently starve the job) and non-DNS-shaped
    strings."""
    v = str(value or "").strip()
    band = PRIORITY_CLASSES.get(v.lower())
    if band is not None:
        return band
    if v.isdigit():
        return int(v)
    if _K8S_NAME_RE.match(v):
        return PRIORITY_CLASSES[""]
    raise ValueError(f"malformed priority class {value!r}")


def _parse_resource_entries(text):
    """The shared per-entry parse/validate of every resource-list flag:
    yields (name, qty) pairs. Quantities must be parse_quantity-legal
    and non-negative (zero is a legal bound; a negative pool or quota
    can never be satisfied and would silently wedge every tenant it
    applies to). Resource NAMES are free-form: unknown keys (device
    plugins, vendor resources) flow through verbatim, exactly like k8s
    extended resources."""
    for part in str(text or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, qty = part.partition("=")
        if not sep or not name.strip():
            raise ValueError(f"malformed resource entry {part!r} (want res=qty)")
        if parse_quantity(qty.strip()) < 0:  # raises on malformed quantities
            raise ValueError(
                f"resource entry {part!r}: quantity must be non-negative")
        yield name.strip(), qty.strip()


def parse_resource_list(text) -> Dict[str, str]:
    """Parse "res=qty[,res=qty...]" (the --capacity / quota flag syntax)
    into a resource dict; quantities stay validated strings. Empty
    input -> {}."""
    return dict(_parse_resource_entries(text))


def parse_quota_flag(text) -> Dict[str, Dict[str, str]]:
    """Parse one "--namespace-quota ns:res=qty[,res=qty...]" value."""
    ns, sep, resources = str(text or "").partition(":")
    if not sep or not ns.strip():
        raise ValueError(
            f"malformed quota {text!r} (want namespace:res=qty[,res=qty])"
        )
    return {ns.strip(): parse_resource_list(resources)}


def parse_capacity_flag(text) -> Tuple[Dict[str, str], Dict[str, Dict[str, str]]]:
    """Parse the extended --capacity syntax: plain "res=qty" entries
    declare the homogeneous pool exactly as before; "res@generation=qty"
    entries declare a device-GENERATION sub-pool (the gavel policy's
    placement unit — e.g. "pods@v5lite=8,pods@v6=8" is a 16-slot pool
    split across two chip generations). Returns (flat_entries,
    generations); the controller sums generation entries into the flat
    pool, so a generation-split pool bounds totals identically to its
    flat sum under generation-blind policies."""
    flat: Dict[str, str] = {}
    generations: Dict[str, Dict[str, str]] = {}
    for name, qty in _parse_resource_entries(text):
        resource, at, generation = name.partition("@")
        if at:
            if not resource or not generation:
                raise ValueError(
                    f"malformed generation entry {name}={qty} "
                    "(want res@generation=qty)"
                )
            bucket = generations.setdefault(generation, {})
            if resource in bucket:
                raise ValueError(
                    f"duplicate declaration of {resource!r} in "
                    f"generation {generation!r}"
                )
            bucket[resource] = qty
        else:
            flat[resource] = qty
    return flat, generations


def parse_tenant_weight(text) -> Dict[str, float]:
    """Parse one "--tenant-weight ns=w" value (the drf policy's weighted
    fairness knob). Weights must be positive finite numbers."""
    ns, sep, weight = str(text or "").partition("=")
    if not sep or not ns.strip():
        raise ValueError(f"malformed tenant weight {text!r} (want ns=weight)")
    try:
        value = float(weight.strip())
    except ValueError:
        raise ValueError(f"tenant weight {weight!r} is not a number")
    if not value > 0 or value != value or value == float("inf"):
        raise ValueError(f"tenant weight {weight!r} must be a positive "
                         "finite number")
    return {ns.strip(): value}


def gang_demand(groups: List[dict]) -> Dict[str, Fraction]:
    """Aggregate a job's gang groups (hooks.gang_groups output) into one
    admission demand: the summed minResources plus a synthetic ``pods``
    resource (the summed minMember) so a pool can be declared in plain
    pod slots even when templates carry no resource requests."""
    demand: Dict[str, Fraction] = {}
    members = Fraction(0)
    for group in groups:
        spec = group.get("spec") or {}
        members += int(spec.get("minMember") or 0)
        for name, qty in (spec.get("minResources") or {}).items():
            try:
                demand[name] = demand.get(name, Fraction(0)) + parse_quantity(qty)
            except (ValueError, ZeroDivisionError):
                continue  # malformed stored quantity: validation rejects new ones
    if members:
        demand["pods"] = demand.get("pods", Fraction(0)) + members
    return demand


def _parse_resources(resources) -> Dict[str, Fraction]:
    return {k: parse_quantity(v) for k, v in (resources or {}).items()}


@dataclass
class AdmitResult:
    """One try_admit verdict. ``newly_admitted``/``newly_queued`` fire
    exactly once per transition (the engine's event/span triggers);
    ``waited`` is the queue wait of a newly-admitted gang (the
    ``admission.queue`` span duration); ``blocked_on`` names the binding
    constraint of a queued gang (capacity | quota | order | priority)."""

    admitted: bool
    newly_admitted: bool = False
    newly_queued: bool = False
    waited: float = 0.0
    blocked_on: str = ""


@dataclass
class _Gang:
    key: str  # "<Kind>:<ns>/<name>" — the workqueue item identity
    kind: str
    namespace: str
    name: str
    uid: str
    band: int
    demand: Dict[str, Fraction]
    members: int
    seq: int
    enqueued_at: float
    # Victim preference within a band (higher = evicted sooner). The
    # engine ranks a multislice job's slices by slice index so the
    # coordinator slice (rank 0 — the worker-0 jax.distributed
    # coordinator every sibling depends on) is only ever chosen once no
    # other slice of any job in the band remains; flat jobs rank 0, so
    # with slice granularity off every ordering is byte-identical to
    # the rank-free arbiter.
    victim_rank: int = 0
    kick: Optional[Callable[[], None]] = None
    admitted_at: Optional[float] = None
    backfilled: bool = False
    blocked_on: str = ""
    # Per-generation normalized throughput from
    # schedulingPolicy.throughputRatios (empty = generation-
    # indifferent; absent generations ride 1.0 — policies.DEFAULT_RATIO).
    throughput_ratios: Dict[str, float] = field(default_factory=dict)
    # The generation sub-pool an ADMITTED gang was placed in (None on a
    # homogeneous pool, and while waiting).
    generation: Optional[str] = None
    # The demand the gate actually GRANTED at admit time (None while
    # waiting). The growth guard keeps ``demand`` pinned to this for
    # admitted gangs: an elastic grow that fits free headroom re-grants
    # in place, one that does not must re-queue through the gate — it may
    # never inflate usage past the pool by side effect of a spec refresh.
    admitted_demand: Optional[Dict[str, Fraction]] = None
    announced_admit: bool = False
    announced_queue: bool = False
    # Last blocked_on verdict the metric layer saw: the quota-denial
    # counter fires on the TRANSITION into "quota", not on every
    # fallback-requeue poll of a still-blocked gang (which would trip
    # the denial-rate alert forever for one patiently-waiting job).
    reported_block: str = ""


class AdmissionController:
    """The shared (one per operator process) admission arbiter. All
    state is in-memory by design — like expectations and the heartbeat
    observation cache, an operator restart rebuilds it from the cluster:
    jobs with live pods re-ADOPT their admission unconditionally
    (has_pods), jobs without re-queue, and any over-capacity left by the
    adoption resolves through the same preemption path a capacity
    revocation takes."""

    def __init__(
        self,
        capacity: Optional[Dict[str, str]] = None,
        quotas: Optional[Dict[str, Dict[str, str]]] = None,
        backfill_max_members: int = 8,
        aging_seconds: float = 300.0,
        clock=time.time,
        metrics=None,
        capacity_fn: Optional[Callable[[], Optional[Dict[str, str]]]] = None,
        slice_granular: bool = False,
        policy=None,
        generations: Optional[Dict[str, Dict[str, str]]] = None,
        generations_fn: Optional[Callable[[], Optional[Dict]]] = None,
        tenant_weights: Optional[Dict[str, float]] = None,
        seed: int = 0,
        decision_log_max: int = 4096,
    ):
        # Per-SLICE admission (--admission-slice-granularity, flagged
        # headroom for multislice jobs): the ENGINE reads this and
        # registers each slice of a multislice job as its own demand
        # under the key "<Kind>:<ns>/<name>#slice-<s>" — individually
        # admittable, preemptable (slice-local counted teardown) and
        # backfillable, so a capacity revocation evicts one slice, not
        # the job. The arbiter itself is key-agnostic; the flag lives
        # here so the engine and the manager share one source of truth.
        self.slice_granular = bool(slice_granular)
        # The pluggable decision procedure (core/policies.py): a policy
        # name ("priority"|"gavel"|"drf"), a policy instance, or None =
        # the default priority policy — the PR 9 arbiter byte-for-byte.
        if policy is None or isinstance(policy, str):
            self.policy: AdmissionPolicy = build_policy(policy or "priority")
        else:
            self.policy = policy
        # Explicit decision seed, threaded into every PolicyState: the
        # classical policies ignore it (they are deterministic without
        # it), but it makes the purity contract auditable — decisions
        # are a function of (queue, pool, usage, seed) and nothing else,
        # and a learned/randomized policy gets its entropy ONLY here.
        self.seed = int(seed)
        self.tenant_weights: Dict[str, float] = {
            ns: float(w) for ns, w in (tenant_weights or {}).items()
        }
        # Device-generation sub-pools (the gavel placement unit). The
        # flat declared pool is the element-wise sum of the generation
        # pools plus any generation-less entries, so generation-blind
        # policies see exactly the total they always did.
        self._declared_gens: Dict[str, Dict[str, Fraction]] = {
            gen: _parse_resources(res)
            for gen, res in (generations or {}).items()
        }
        declared = _parse_resources(capacity) if capacity else None
        if self._declared_gens:
            declared = dict(declared or {})
            for res_map in self._declared_gens.values():
                for name, qty in res_map.items():
                    declared[name] = declared.get(name, Fraction(0)) + qty
        self._declared = declared
        self._generations_fn = generations_fn
        self.quotas: Dict[str, Dict[str, Fraction]] = {
            ns: _parse_resources(res) for ns, res in (quotas or {}).items()
        }
        self.backfill_max_members = int(backfill_max_members)
        self.aging_seconds = float(aging_seconds)
        self.clock = clock
        if metrics is None:
            from ..metrics import METRICS

            metrics = METRICS
        self.metrics = metrics
        # Live capacity provider (the memory cluster's schedulable-
        # capacity model, through which the seeded capacity-revocation
        # fault arrives): the effective pool is the per-resource MIN of
        # the declared pool and whatever the provider reports.
        self._capacity_fn = capacity_fn
        self._lock = threading.Lock()
        self._seq = 0
        self._admitted: Dict[str, _Gang] = {}
        self._waiting: Dict[str, _Gang] = {}
        self._preempt: Dict[str, str] = {}  # key -> cause, engine-acknowledged
        self._kicks: List[Callable[[], None]] = []
        # Audit ledgers (testing/invariants.py): every admit with its
        # backfill verdict + the head-of-line wait at that instant, and
        # every acknowledged preemption (key, uid, cause) — exactly one
        # entry per physical preemption by construction (note_preempted
        # pops the pending marker first). BOUNDED rings (the Tracer
        # convention): a long-lived operator churning jobs must not grow
        # RSS forever, and /debugz snapshots copy these under the lock —
        # the invariants read the retained window, which is exactly the
        # recent history a test scenario produces.
        from collections import deque

        self.admit_log: "deque[dict]" = deque(maxlen=1024)
        self.preemption_ledger: "deque[tuple]" = deque(maxlen=512)
        # The determinism-audit artifact: one entry per pump that took
        # an action (admits/preempts, in applied order) — a pure record
        # of the policy's observable schedule. Same-seed runs over the
        # same call sequence must produce byte-equal logs
        # (decision_log_lines); bounded like the other rings, but with
        # the cap EXPLICIT (decision_log_max — the fleet-sim smoke run
        # alone accretes ~4.1k entries) and a dropped counter so an
        # auditor can tell a complete log from a truncated window (a
        # byte-equality check over a silently-rotated ring would pass
        # on two DIFFERENT histories that merely share a tail).
        self.decision_log_max = max(1, int(decision_log_max))
        self.decision_log: "deque[dict]" = deque(maxlen=self.decision_log_max)
        self.decision_log_dropped = 0
        self._pump_count = 0

    # --------------------------------------------------------- capacity
    def effective_capacity(self) -> Optional[Dict[str, Fraction]]:
        """None = unlimited. With both a declared pool and a live
        provider, a resource's bound is the smaller of the two (a
        revocation can only shrink the pool, never grow past --capacity)."""
        cap = dict(self._declared) if self._declared is not None else None
        if self._capacity_fn is not None:
            try:
                live = self._capacity_fn()
            except Exception:  # noqa: BLE001 — a flaky provider must not wedge admission
                live = None
            if live:
                parsed = _parse_resources(live)
                if cap is None:
                    cap = parsed
                else:
                    for name, qty in parsed.items():
                        cap[name] = min(cap.get(name, qty), qty)
        return cap

    def effective_generations(self) -> Dict[str, Dict[str, Fraction]]:
        """The device-generation sub-pools ({} = homogeneous). With a
        live provider (the memory cluster's schedulable_generations),
        a declared generation's bound is the per-resource MIN of the
        two — a generation-scoped revocation can only shrink its
        sub-pool, mirroring the flat rule."""
        gens = {g: dict(r) for g, r in self._declared_gens.items()}
        if self._generations_fn is not None:
            try:
                live = self._generations_fn()
            except Exception:  # noqa: BLE001 — a flaky provider must not wedge admission
                live = None
            for gen, resources in (live or {}).items():
                if gen not in gens:
                    continue
                parsed = _parse_resources(resources)
                bucket = gens[gen]
                for name, qty in parsed.items():
                    bucket[name] = min(bucket.get(name, qty), qty)
        return gens

    def _usage_locked(self, exclude=()) -> Dict[str, Fraction]:
        usage: Dict[str, Fraction] = {}
        for key, gang in self._admitted.items():
            if key in exclude:
                continue
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, Fraction(0)) + qty
        return usage

    def _ns_usage_locked(self, namespace: str, exclude=()) -> Dict[str, Fraction]:
        usage: Dict[str, Fraction] = {}
        for key, gang in self._admitted.items():
            if key in exclude or gang.namespace != namespace:
                continue
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, Fraction(0)) + qty
        return usage

    # ------------------------------------------------------------- pump
    # (Fit/quota predicates live in core/policies.py now — the seam owns
    # the decision procedure; this class owns registration, application,
    # and the audit ledgers.)
    def _waiting_order_locked(self) -> List[_Gang]:
        return sorted(self._waiting.values(), key=lambda g: (-g.band, g.seq))

    def _admit_locked(self, gang: _Gang, now: float, backfill: bool,
                      head_wait: Optional[float],
                      generation: Optional[str] = None) -> None:
        self._waiting.pop(gang.key, None)
        gang.admitted_at = now
        gang.backfilled = backfill
        gang.blocked_on = ""
        gang.announced_admit = False
        gang.generation = generation
        gang.admitted_demand = dict(gang.demand)
        self._admitted[gang.key] = gang
        entry = {
            "key": gang.key, "band": gang.band, "backfill": backfill,
            "head_wait_at_admit": head_wait,
            "wait": now - gang.enqueued_at,
        }
        if self._declared_gens:
            # Generation-pool bookkeeping rides the admit log only when
            # a generation pool exists, so the PR 9 entry shape (and
            # everything that string-compares it) is untouched on
            # homogeneous pools.
            entry["generation"] = generation
            entry["ratio"] = ratio_of(gang, generation)
            entry["best_ratio"] = max(
                ratio_of(gang, g) for g in sorted(self._declared_gens)
            )
            entry["members"] = gang.members
        self.admit_log.append(entry)
        self.metrics.observe_admission_wait(
            gang.namespace, gang.kind, max(0.0, now - gang.enqueued_at)
        )
        if gang.kick is not None:
            self._kicks.append(gang.kick)

    def _growth_fits_locked(self, gang: _Gang,
                            demand: Dict[str, Fraction]) -> bool:
        """Would re-granting ``demand`` to this ADMITTED gang (in place of
        its current charge) still fit the flat pool, its generation
        sub-pool, and its namespace quota? The growth guard's predicate:
        an elastic grow covered by free headroom is an in-place re-grant;
        one that is not must release and re-queue through the gate."""
        from .policies import fits as _fits

        exclude = {gang.key}
        if not _fits(demand, self._usage_locked(exclude),
                     self.effective_capacity()):
            return False
        quota = self.quotas.get(gang.namespace)
        if quota:
            used = self._ns_usage_locked(gang.namespace, exclude)
            if not all(
                used.get(name, Fraction(0)) + qty <= quota[name]
                for name, qty in demand.items()
                if name in quota
            ):
                return False
        gens = self.effective_generations()
        if gens and gang.generation in gens:
            gen_usage: Dict[str, Fraction] = {}
            for g in self._admitted.values():
                if g.key in exclude or g.generation != gang.generation:
                    continue
                for name, qty in g.demand.items():
                    gen_usage[name] = gen_usage.get(name, Fraction(0)) + qty
            if not _fits(demand, gen_usage, gens[gang.generation]):
                return False
        return True

    def _demote_to_queue_locked(self, gang: _Gang, now: float) -> None:
        """Release an admitted gang back to the wait queue (the growth
        guard's no-bypass path): head of its band with a fresh aging
        clock — it held capacity in good standing and must not lose its
        place to later arrivals for asking to grow."""
        self._admitted.pop(gang.key, None)
        gang.admitted_at = None
        gang.backfilled = False
        gang.announced_admit = False
        gang.announced_queue = False
        gang.reported_block = ""
        gang.admitted_demand = None
        gang.generation = None
        band_seqs = [
            g.seq for g in self._waiting.values() if g.band == gang.band
        ]
        gang.seq = (min(band_seqs) - 1) if band_seqs else gang.seq
        gang.enqueued_at = now
        self._waiting[gang.key] = gang

    def _mark_preempt_locked(self, gang: _Gang, cause: str) -> None:
        if gang.key in self._preempt:
            return
        self._preempt[gang.key] = cause
        if gang.kick is not None:
            self._kicks.append(gang.kick)

    def _adoption_generation_locked(self, gang: _Gang) -> Optional[str]:
        """Best-effort generation attribution for the has_pods adoption
        path (an operator restart must re-admit live pods wherever they
        physically run — leaving them generation-less would make every
        sub-pool look empty and let placement oversubscribe real chips).
        First-fit with room; when every sub-pool is full, the sorted-
        first generation takes the visible overcommit and the policies'
        generation-revocation sweep preempts to fit — the same path a
        flat adoption overcommit resolves through."""
        gens = self.effective_generations()
        if not gens:
            return None
        from .policies import fits as _fits

        usage: Dict[str, Dict[str, Fraction]] = {}
        for g in self._admitted.values():
            if g.generation is None:
                continue
            bucket = usage.setdefault(g.generation, {})
            for name, qty in g.demand.items():
                bucket[name] = bucket.get(name, Fraction(0)) + qty
        for name in sorted(gens):
            if _fits(gang.demand, usage.get(name, {}), gens[name]):
                return name
        return sorted(gens)[0]

    @staticmethod
    def _gang_view(gang: _Gang) -> GangView:
        return GangView(
            key=gang.key, namespace=gang.namespace, band=gang.band,
            seq=gang.seq, demand=gang.demand, members=gang.members,
            enqueued_at=gang.enqueued_at, victim_rank=gang.victim_rank,
            throughput_ratios=gang.throughput_ratios,
            generation=gang.generation,
        )

    def _policy_state_locked(self, now: float, cap) -> PolicyState:
        """The pure-function input (queue, pool, usage, seed) — an
        immutable view of everything a decision may legally depend on.
        No wall clock reaches the policy except ``now``, which is this
        controller's injected clock value, so fake-clock replays are
        exact."""
        return PolicyState(
            waiting=tuple(
                self._gang_view(g) for g in self._waiting_order_locked()
            ),
            admitted=tuple(
                self._gang_view(g) for g in self._admitted.values()
            ),
            pending_preempt=frozenset(self._preempt),
            capacity=cap,
            generations=self.effective_generations(),
            quotas=self.quotas,
            tenant_weights=self.tenant_weights,
            backfill_max_members=self.backfill_max_members,
            aging_seconds=self.aging_seconds,
            now=now,
            seed=self.seed,
        )

    def _pump_locked(self, now: float) -> None:
        """One pump = build the immutable PolicyState, ask the active
        policy for an ORDERED decision list (core/policies.py), and
        apply it verbatim: admits register capacity (admit-log entries,
        wait metrics, and requeue kicks land in list order — a policy's
        output order IS its observable schedule), preempts mark victims
        for the engine's counted teardown, and blocked verdicts land on
        whoever stays waiting. The default priority policy reproduces
        the PR 9 procedure byte-for-byte."""
        self._pump_count += 1
        pump_started = time.perf_counter()
        cap = self.effective_capacity()
        state = self._policy_state_locked(now, cap)
        decisions = self.policy.decide(state)
        applied: List[list] = []
        admitted_keys: set = set()
        for action in decisions.actions:
            if isinstance(action, Admit):
                gang = self._waiting.get(action.key)
                if gang is None:
                    continue  # raced away (released mid-decision impossible under the lock; defensive)
                self._admit_locked(
                    gang, now, backfill=action.backfill,
                    head_wait=action.head_wait,
                    generation=action.generation,
                )
                admitted_keys.add(action.key)
                applied.append(
                    ["admit", action.key, bool(action.backfill),
                     action.generation])
            elif isinstance(action, Preempt):
                gang = self._admitted.get(action.key)
                if gang is None:
                    continue
                if gang.key not in self._preempt:
                    applied.append(["preempt", action.key, action.cause])
                self._mark_preempt_locked(gang, action.cause)
        for key, verdict in decisions.blocked.items():
            if key in admitted_keys:
                continue  # actions win over a stale verdict (drf's re-sorted passes)
            gang = self._waiting.get(key)
            if gang is not None:
                gang.blocked_on = verdict
        if applied:
            if len(self.decision_log) >= self.decision_log_max:
                # The ring is about to rotate: count the eviction so the
                # determinism audit knows its window is truncated.
                self.decision_log_dropped += 1
            self.decision_log.append(
                {"pump": self._pump_count, "policy": self.policy.name,
                 "seed": self.seed, "actions": applied}
            )
        self._update_gauges_locked(cap)
        # Wall time (perf_counter), never the injected clock: under the
        # fleet simulator the virtual clock is frozen inside an event,
        # and the whole point of this histogram is the REAL per-pump
        # cost at fleet object counts.
        self.metrics.observe_admission_pump(
            time.perf_counter() - pump_started)

    def _update_gauges_locked(self, cap=None) -> None:
        depths: Dict[int, int] = {}
        for gang in self._waiting.values():
            depths[gang.band] = depths.get(gang.band, 0) + 1
        self.metrics.set_admission_queue_depths(
            {str(band): depth for band, depth in depths.items()}
        )
        self.metrics.set_gauge(
            "training_operator_admission_effective_throughput",
            self._effective_throughput_locked(),
        )
        self.metrics.set_admission_dominant_shares(
            self._dominant_shares_locked(cap)
        )

    def _effective_throughput_locked(self) -> float:
        """Fleet-wide effective throughput of the admitted set:
        Σ ratio(assigned generation) × members — the Gavel objective in
        normalized chip-equivalents. On a homogeneous pool every ratio
        is 1.0 and this is simply the admitted member count."""
        return float(sum(
            ratio_of(g, g.generation) * max(g.members, 1)
            for g in self._admitted.values()
        ))

    def _dominant_shares_locked(self, cap=None) -> Dict[str, float]:
        """Per-tenant dominant share: max over pool resources of
        usage/capacity (the DRF coordinate). Empty without a bounded
        pool — shares are undefined against infinity."""
        if cap is None:
            cap = self.effective_capacity()
        if not cap:
            return {}
        shares: Dict[str, float] = {}
        for ns in sorted({g.namespace for g in self._admitted.values()}):
            used = self._ns_usage_locked(ns)
            share = 0.0
            for resource, bound in cap.items():
                if bound <= 0:
                    continue
                share = max(share, float(used.get(resource, Fraction(0)) / bound))
            shares[ns] = round(share, 6)
        return shares

    def _drain_kicks_locked(self) -> List[Callable[[], None]]:
        kicks, self._kicks = self._kicks, []
        return kicks

    # -------------------------------------------------------- engine API
    def try_admit(
        self, *, key: str, kind: str, namespace: str, name: str, uid: str,
        priority_class: str = "", demand: Optional[Dict[str, Fraction]] = None,
        members: int = 0, has_pods: bool = False,
        kick: Optional[Callable[[], None]] = None,
        victim_rank: int = 0,
        throughput_ratios: Optional[Dict[str, float]] = None,
    ) -> AdmitResult:
        """One job's admission question, asked on every sync. Admitted
        jobs take a fast path (plus a pump so capacity revocations are
        noticed on the admitted side too); waiting jobs are (re)registered
        and the queue pumped. ``has_pods`` (live, non-terminating pods
        exist) is the adoption path: those pods were admitted by a prior
        operator incarnation and holding them "unborn" is impossible —
        admit unconditionally and let the revocation path resolve any
        over-commit."""
        try:
            band = parse_priority_class(priority_class)
        except ValueError:
            band = PRIORITY_CLASSES[""]  # stored pre-validation jobs: default band
        demand = dict(demand or {})
        with self._lock:
            now = self.clock()
            gang = self._admitted.get(key)
            if gang is not None and demand:
                # Growth guard (no-bypass rule): an elastic resize that
                # RAISES an admitted gang's demand is a fresh capacity
                # ask, not a bookkeeping refresh. Covered by free
                # headroom it re-grants in place (below, unchanged);
                # beyond headroom it must queue through the gate — while
                # the old world's pods still live (resize teardown in
                # flight) the gang stays admitted at its GRANTED demand
                # so the pool keeps charging what actually runs, and
                # once they are gone it re-queues at the head of its
                # band instead of inflating usage past the pool (which
                # would preempt an innocent victim via the revocation
                # sweep).
                granted = gang.admitted_demand
                grew = granted is not None and any(
                    qty > granted.get(name, Fraction(0))
                    for name, qty in demand.items()
                )
                if grew and not self._growth_fits_locked(gang, demand):
                    if has_pods:
                        demand = dict(granted)
                    else:
                        self._demote_to_queue_locked(gang, now)
                        gang = None
            if gang is not None:
                # Refresh demand (elastic resize changes it) and notice
                # revocations; a same-sync re-ask stays admitted.
                gang.demand = demand or gang.demand
                gang.admitted_demand = dict(gang.demand)
                gang.members = members or gang.members
                gang.uid = uid or gang.uid
                gang.kick = kick or gang.kick
                gang.victim_rank = victim_rank
                if throughput_ratios is not None:
                    # Full replace, including {} — deleting the map from
                    # the spec must clear the stored ratios, or gavel
                    # keeps placing on ratios the API object no longer
                    # declares.
                    gang.throughput_ratios = dict(throughput_ratios)
                self._pump_locked(now)
                newly = not gang.announced_admit
                gang.announced_admit = True
                waited = (
                    max(0.0, (gang.admitted_at or now) - gang.enqueued_at)
                    if newly else 0.0
                )
                kicks = self._drain_kicks_locked()
                result = AdmitResult(True, newly_admitted=newly, waited=waited)
            else:
                gang = self._waiting.get(key)
                if gang is None:
                    self._seq += 1
                    gang = _Gang(
                        key=key, kind=kind, namespace=namespace, name=name,
                        uid=uid, band=band, demand=demand, members=members,
                        seq=self._seq, enqueued_at=now,
                        victim_rank=victim_rank, kick=kick,
                        throughput_ratios=dict(throughput_ratios or {}),
                    )
                    self._waiting[key] = gang
                else:
                    gang.band = band
                    gang.demand = demand or gang.demand
                    gang.members = members or gang.members
                    gang.uid = uid or gang.uid
                    gang.kick = kick or gang.kick
                    gang.victim_rank = victim_rank
                    if throughput_ratios is not None:
                        gang.throughput_ratios = dict(throughput_ratios)
                if has_pods:
                    self._admit_locked(
                        gang, now, backfill=False, head_wait=None,
                        generation=self._adoption_generation_locked(gang),
                    )
                    gang.announced_admit = True
                    self._pump_locked(now)
                    kicks = self._drain_kicks_locked()
                    result = AdmitResult(True, newly_admitted=True)
                else:
                    self._pump_locked(now)
                    if key in self._admitted:
                        gang.announced_admit = True
                        result = AdmitResult(
                            True, newly_admitted=True,
                            waited=max(0.0, now - gang.enqueued_at),
                        )
                    else:
                        newly_queued = not gang.announced_queue
                        gang.announced_queue = True
                        if (
                            gang.blocked_on == "quota"
                            and gang.reported_block != "quota"
                        ):
                            self.metrics.quota_denial_inc(namespace)
                        gang.reported_block = gang.blocked_on
                        result = AdmitResult(
                            False, newly_queued=newly_queued,
                            blocked_on=gang.blocked_on or "capacity",
                        )
                    kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()
        return result

    def preemption_requested(self, key: str) -> Optional[str]:
        """The pending preemption cause for a job, if any — the engine's
        signal to run the counted teardown."""
        with self._lock:
            return self._preempt.get(key)

    def note_preempted(self, key: str, uid: str, cause: str = "") -> bool:
        """Engine acknowledgment that the preemption's COUNTED status
        write is durable (or that nothing was left to tear down): release
        the gang's capacity, re-queue it at the head of its band with a
        fresh aging clock, and record the exactly-once ledger entry.
        Idempotent: a second call for an already-acknowledged preemption
        is a no-op (returns False) — the crash-retry path re-enters here
        after a teardown resume without double-counting."""
        with self._lock:
            pending = self._preempt.pop(key, None)
            if pending is None:
                return False
            cause = cause or pending
            now = self.clock()
            gang = self._admitted.pop(key, None)
            if gang is not None:
                if cause == PREEMPT_CAUSE_THROUGHPUT:
                    # A gavel swap victim YIELDS its place: re-queueing
                    # at the head of its band (the priority/capacity
                    # contract) would let an equal-band victim overtake
                    # the very head it was evicted for and re-take the
                    # vacated generation — the swap would churn forever
                    # without the throughput gain that justified it.
                    # Tail re-queue puts it behind the head; it
                    # re-places work-conservingly on what remains.
                    self._seq += 1
                    gang.seq = self._seq
                else:
                    band_seqs = [
                        g.seq for g in self._waiting.values()
                        if g.band == gang.band
                    ]
                    gang.seq = (min(band_seqs) - 1) if band_seqs else gang.seq
                gang.enqueued_at = now
                gang.admitted_at = None
                gang.backfilled = False
                gang.announced_admit = False
                gang.announced_queue = False
                gang.reported_block = ""
                gang.generation = None  # re-placed fresh on re-admission
                self._waiting[gang.key] = gang
                self.preemption_ledger.append((key, uid, cause))
                self.metrics.gang_preemption_inc(cause, str(gang.band))
            self._pump_locked(now)
            kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()
        return True

    def release(self, key: str) -> None:
        """The job left the contention domain (terminal, suspended, or
        deleted): free its capacity/quota and admit whoever is next. A
        key this controller never saw is a no-op — release is called
        unconditionally from every cleanup path. Releases the key's
        per-slice sub-entries ("<key>#slice-<s>") along with it: the
        cleanup paths know only the job, and a leaked slice admission
        would pin its share of the tenant's quota forever. The sub-key
        sweep runs only under slice granularity — the only mode that
        can create them — so the job-granular arbiter keeps its O(1)
        release on every terminal/suspend/delete sync."""
        with self._lock:
            doomed = {key}
            if self.slice_granular:
                prefix = key + "#slice-"
                doomed |= {
                    k
                    for k in (
                        set(self._admitted) | set(self._waiting)
                        | set(self._preempt)
                    )
                    if k.startswith(prefix)
                }
            released = False
            for k in doomed:
                released |= self._admitted.pop(k, None) is not None
                released |= self._waiting.pop(k, None) is not None
                self._preempt.pop(k, None)
            if not released:
                return
            self._pump_locked(self.clock())
            kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()

    def release_stale_granularity(self, key: str, sliced: bool) -> None:
        """Granularity-transition hygiene (an elastic resize crossing the
        numSlices>1 boundary switches which admission gate a job uses):
        entering the SLICED gate drops a stale plain-key registration;
        entering the FLAT gate drops stale '#slice-' sub-entries.
        Without this, the old granularity's admissions double-charge the
        pool and the tenant quota for the job's whole remaining life,
        and a pending preemption against a stale key is never serviced.
        Fast no-op when nothing stale exists — the flat branch probes the
        O(1) '#slice-0' sentinel (sliced registrations always include
        slice 0) before paying the full key scan, so a fleet of
        single-slice jobs never scans the arbiter per sync."""
        with self._lock:
            if sliced:
                doomed = [key] if (
                    key in self._admitted or key in self._waiting
                    or key in self._preempt
                ) else []
            else:
                sentinel = f"{key}#slice-0"
                if not (
                    sentinel in self._admitted or sentinel in self._waiting
                    or sentinel in self._preempt
                ):
                    return
                prefix = key + "#slice-"
                doomed = [
                    k
                    for k in (
                        set(self._admitted) | set(self._waiting)
                        | set(self._preempt)
                    )
                    if k.startswith(prefix)
                ]
            if not doomed:
                return
            for k in doomed:
                self._admitted.pop(k, None)
                self._waiting.pop(k, None)
                self._preempt.pop(k, None)
            self._pump_locked(self.clock())
            kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()

    # ------------------------------------------------------ observability
    def is_admitted(self, key: str) -> bool:
        with self._lock:
            return key in self._admitted

    def effective_throughput(self) -> float:
        """Current fleet-wide effective throughput (Σ ratio × members
        over admitted gangs) — the admission_effective_throughput gauge
        value, exposed directly for the contention benchmark's
        time-integral."""
        with self._lock:
            return self._effective_throughput_locked()

    def dominant_shares(self) -> Dict[str, float]:
        """Per-tenant dominant shares (the admission_dominant_share
        gauge values) — the fairness coordinate the drf gate samples."""
        with self._lock:
            return self._dominant_shares_locked()

    def decision_log_lines(self) -> List[str]:
        """The decision log as canonical JSON lines — the byte-equality
        artifact of the determinism regression (same seed + same call
        sequence => identical lines, across runs and policies)."""
        import json

        with self._lock:
            entries = list(self.decision_log)
        return [
            json.dumps(entry, sort_keys=True, separators=(",", ":"))
            for entry in entries
        ]

    def snapshot(self) -> dict:
        """The /debugz admission dump: bands, queue positions, aging
        clocks, usage vs capacity/quotas, pending preemptions, the audit
        ledgers the invariants run over — and, since the policy seam:
        the active policy name + seed, the per-generation sub-pools with
        their usage, and the per-tenant dominant shares. All additive
        keys: the PR 9 shape (what the smoke JSON and older dashboards
        read) is unchanged."""
        with self._lock:
            now = self.clock()
            cap = self.effective_capacity()
            gens = self.effective_generations()
            gen_usage: Dict[str, Dict[str, Fraction]] = {}
            for g in self._admitted.values():
                if g.generation is None:
                    continue
                bucket = gen_usage.setdefault(g.generation, {})
                for name, qty in g.demand.items():
                    bucket[name] = bucket.get(name, Fraction(0)) + qty

            def fmt(resources):
                return {k: str(v) for k, v in (resources or {}).items()}

            out = {
                "policy": self.policy.name,
                "seed": self.seed,
                "capacity": fmt(cap) if cap is not None else None,
                "usage": fmt(self._usage_locked()),
                "quotas": {ns: fmt(q) for ns, q in self.quotas.items()},
                "namespace_usage": {
                    ns: fmt(self._ns_usage_locked(ns))
                    for ns in sorted(
                        {g.namespace for g in self._admitted.values()}
                    )
                },
                "aging_seconds": self.aging_seconds,
                "backfill_max_members": self.backfill_max_members,
                "admitted": [
                    {
                        "key": g.key, "band": g.band, "members": g.members,
                        "demand": fmt(g.demand), "backfilled": g.backfilled,
                        "admitted_demand": fmt(
                            g.admitted_demand
                            if g.admitted_demand is not None else g.demand
                        ),
                        "admitted_for": round(now - (g.admitted_at or now), 3),
                        **({"generation": g.generation} if gens else {}),
                    }
                    for g in sorted(
                        self._admitted.values(), key=lambda g: (-g.band, g.seq)
                    )
                ],
                "waiting": [
                    {
                        "key": g.key, "band": g.band, "position": i,
                        "members": g.members, "demand": fmt(g.demand),
                        "waited": round(now - g.enqueued_at, 3),
                        "blocked_on": g.blocked_on,
                    }
                    for i, g in enumerate(self._waiting_order_locked())
                ],
                "preempting": dict(self._preempt),
                "admit_log": list(self.admit_log),
                "preemption_ledger": [list(t) for t in self.preemption_ledger],
                "effective_throughput": self._effective_throughput_locked(),
                "dominant_shares": self._dominant_shares_locked(cap),
                # Additive since the explicit decision-log bound: how
                # big the audit ring is and how many entries it has
                # rotated out (0 = the log is the complete history).
                "decision_log_max": self.decision_log_max,
                "decision_log_dropped": self.decision_log_dropped,
            }
            if self.tenant_weights:
                out["tenant_weights"] = dict(sorted(
                    self.tenant_weights.items()))
            if gens:
                out["generations"] = {
                    gen: {
                        "capacity": fmt(gens[gen]),
                        "usage": fmt(gen_usage.get(gen, {})),
                    }
                    for gen in sorted(gens)
                }
            return out
