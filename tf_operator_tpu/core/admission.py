"""Capacity-aware gang admission: quota'd queueing, priority preemption,
and bounded backfill (docs/design/gang_admission.md).

The reference operator fires PodGroups at Volcano and forgets them; the
gang unit here (per-slice PodGroups, the JOB_QUEUED condition) already
exists but admission was first-come and capacity-blind — under contention
jobs race, deadlock on partial gangs, or starve. This module is the
operator-side admission arbiter the Gavel line of work (arXiv:2008.09213)
argues for: a declared capacity pool, all-or-nothing job admission (a
job's pods stay UNBORN while it queues — no partial gang can ever exist),
per-tenant (namespace) quotas, priority bands from
``SchedulingPolicy.priorityClass``, preempt-lowest-priority-gang on
contention, and bounded backfill of small gangs into capacity gaps with
an aging bound so backfill can never starve the head-of-line gang.

Everything is deterministic given a deterministic call sequence and
clock: decisions are pure functions of (registered gangs, capacity,
clock) — no randomness — so the seeded chaos/crash tiers replay
byte-identically with admission ON, and with the flag OFF (the default)
the engine never constructs this object at all and the PR 1–8 behavior
is untouched byte-for-byte.

Ordering rules, in one place:

- The wait queue is ordered by (band desc, seq asc): higher priority
  bands first, FIFO within a band. ``seq`` is a monotonic admission-
  controller sequence; a preempted gang re-enters at the HEAD of its
  band (seq below every current waiter of that band).
- The head-of-line is the first waiting gang whose own namespace quota
  would allow it (a tenant that exhausted its own quota must not hold
  the line against other tenants — its wait can only end with its own
  releases).
- A non-head gang may only be BACKFILLED: it must fit the free gap, its
  member count must not exceed ``backfill_max_members``, and the
  head-of-line must not have waited past ``aging_seconds`` — once the
  head ages past the bound, backfill stops until the head admits
  (starvation-freedom; audited from the admit log by
  testing/invariants.py).
- When the head does not fit, admitted gangs of STRICTLY lower band are
  preempted — lowest band first, most-recently-admitted first — until
  the head would fit. Victims are only MARKED here; the engine routes
  the teardown through the count-before-teardown disruption protocol
  and acknowledges with :meth:`note_preempted` once the counted write
  is durable, so the preemption lands in the budget-free
  ``disruptionCounts`` ledger exactly once.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from fractions import Fraction
from typing import Callable, Dict, List, Optional

from .job_controller import parse_quantity

# Priority bands for SchedulingPolicy.priorityClass. Scheduler-style
# class names map onto small integers; bare non-negative integers are
# accepted verbatim so clusters with numeric PriorityClass conventions
# can express finer ladders. Other legal PriorityClass names ride the
# DEFAULT band (never band 0 — an unrecognized name must not make a job
# globally preemptible); only un-nameable values (negative, non-DNS) are
# ValidationErrors at admission (api/defaulting.py).
PRIORITY_CLASSES = {
    "low": 0,
    "preemptible": 0,
    "best-effort": 0,
    "": 1,
    "default": 1,
    "normal": 1,
    "high": 2,
    "critical": 3,
}

# Preemption causes (the gang_preemptions_total{cause} label values).
PREEMPT_CAUSE_PRIORITY = "PriorityPreemption"
PREEMPT_CAUSE_CAPACITY = "CapacityRevoked"


import re as _re

# A legal Kubernetes PriorityClass name (DNS-1123 subdomain shape). Names
# outside the band vocabulary but inside this shape are legitimate
# cluster PriorityClasses the operator merely has no band opinion about —
# they ride the default band (and pass through to the PodGroup verbatim,
# exactly as before this layer existed). Anything outside the shape can
# never name a real PriorityClass and is a typed ValidationError.
_K8S_NAME_RE = _re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")


def parse_priority_class(value) -> int:
    """Band of a priorityClass: a known band name (case-insensitive), a
    bare non-negative integer, or any OTHER legal PriorityClass name —
    which maps to the default band (the operator ranks only its own band
    vocabulary; foreign class names are Volcano's business and must keep
    flowing through untouched). Raises ValueError only for values that
    could never name a PriorityClass: negatives (they would sort below
    every band and permanently starve the job) and non-DNS-shaped
    strings."""
    v = str(value or "").strip()
    band = PRIORITY_CLASSES.get(v.lower())
    if band is not None:
        return band
    if v.isdigit():
        return int(v)
    if _K8S_NAME_RE.match(v):
        return PRIORITY_CLASSES[""]
    raise ValueError(f"malformed priority class {value!r}")


def parse_resource_list(text) -> Dict[str, str]:
    """Parse "res=qty[,res=qty...]" (the --capacity / quota flag syntax)
    into a resource dict; quantities stay strings (parse_quantity-legal,
    validated here). Empty input -> {}."""
    out: Dict[str, str] = {}
    for part in str(text or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, qty = part.partition("=")
        if not sep or not name.strip():
            raise ValueError(f"malformed resource entry {part!r} (want res=qty)")
        parse_quantity(qty.strip())  # raises on malformed quantities
        out[name.strip()] = qty.strip()
    return out


def parse_quota_flag(text) -> Dict[str, Dict[str, str]]:
    """Parse one "--namespace-quota ns:res=qty[,res=qty...]" value."""
    ns, sep, resources = str(text or "").partition(":")
    if not sep or not ns.strip():
        raise ValueError(
            f"malformed quota {text!r} (want namespace:res=qty[,res=qty])"
        )
    return {ns.strip(): parse_resource_list(resources)}


def gang_demand(groups: List[dict]) -> Dict[str, Fraction]:
    """Aggregate a job's gang groups (hooks.gang_groups output) into one
    admission demand: the summed minResources plus a synthetic ``pods``
    resource (the summed minMember) so a pool can be declared in plain
    pod slots even when templates carry no resource requests."""
    demand: Dict[str, Fraction] = {}
    members = Fraction(0)
    for group in groups:
        spec = group.get("spec") or {}
        members += int(spec.get("minMember") or 0)
        for name, qty in (spec.get("minResources") or {}).items():
            try:
                demand[name] = demand.get(name, Fraction(0)) + parse_quantity(qty)
            except (ValueError, ZeroDivisionError):
                continue  # malformed stored quantity: validation rejects new ones
    if members:
        demand["pods"] = demand.get("pods", Fraction(0)) + members
    return demand


def _parse_resources(resources) -> Dict[str, Fraction]:
    return {k: parse_quantity(v) for k, v in (resources or {}).items()}


@dataclass
class AdmitResult:
    """One try_admit verdict. ``newly_admitted``/``newly_queued`` fire
    exactly once per transition (the engine's event/span triggers);
    ``waited`` is the queue wait of a newly-admitted gang (the
    ``admission.queue`` span duration); ``blocked_on`` names the binding
    constraint of a queued gang (capacity | quota | order | priority)."""

    admitted: bool
    newly_admitted: bool = False
    newly_queued: bool = False
    waited: float = 0.0
    blocked_on: str = ""


@dataclass
class _Gang:
    key: str  # "<Kind>:<ns>/<name>" — the workqueue item identity
    kind: str
    namespace: str
    name: str
    uid: str
    band: int
    demand: Dict[str, Fraction]
    members: int
    seq: int
    enqueued_at: float
    # Victim preference within a band (higher = evicted sooner). The
    # engine ranks a multislice job's slices by slice index so the
    # coordinator slice (rank 0 — the worker-0 jax.distributed
    # coordinator every sibling depends on) is only ever chosen once no
    # other slice of any job in the band remains; flat jobs rank 0, so
    # with slice granularity off every ordering is byte-identical to
    # the rank-free arbiter.
    victim_rank: int = 0
    kick: Optional[Callable[[], None]] = None
    admitted_at: Optional[float] = None
    backfilled: bool = False
    blocked_on: str = ""
    announced_admit: bool = False
    announced_queue: bool = False
    # Last blocked_on verdict the metric layer saw: the quota-denial
    # counter fires on the TRANSITION into "quota", not on every
    # fallback-requeue poll of a still-blocked gang (which would trip
    # the denial-rate alert forever for one patiently-waiting job).
    reported_block: str = ""


class AdmissionController:
    """The shared (one per operator process) admission arbiter. All
    state is in-memory by design — like expectations and the heartbeat
    observation cache, an operator restart rebuilds it from the cluster:
    jobs with live pods re-ADOPT their admission unconditionally
    (has_pods), jobs without re-queue, and any over-capacity left by the
    adoption resolves through the same preemption path a capacity
    revocation takes."""

    def __init__(
        self,
        capacity: Optional[Dict[str, str]] = None,
        quotas: Optional[Dict[str, Dict[str, str]]] = None,
        backfill_max_members: int = 8,
        aging_seconds: float = 300.0,
        clock=time.time,
        metrics=None,
        capacity_fn: Optional[Callable[[], Optional[Dict[str, str]]]] = None,
        slice_granular: bool = False,
    ):
        # Per-SLICE admission (--admission-slice-granularity, flagged
        # headroom for multislice jobs): the ENGINE reads this and
        # registers each slice of a multislice job as its own demand
        # under the key "<Kind>:<ns>/<name>#slice-<s>" — individually
        # admittable, preemptable (slice-local counted teardown) and
        # backfillable, so a capacity revocation evicts one slice, not
        # the job. The arbiter itself is key-agnostic; the flag lives
        # here so the engine and the manager share one source of truth.
        self.slice_granular = bool(slice_granular)
        self._declared = _parse_resources(capacity) if capacity else None
        self.quotas: Dict[str, Dict[str, Fraction]] = {
            ns: _parse_resources(res) for ns, res in (quotas or {}).items()
        }
        self.backfill_max_members = int(backfill_max_members)
        self.aging_seconds = float(aging_seconds)
        self.clock = clock
        if metrics is None:
            from ..metrics import METRICS

            metrics = METRICS
        self.metrics = metrics
        # Live capacity provider (the memory cluster's schedulable-
        # capacity model, through which the seeded capacity-revocation
        # fault arrives): the effective pool is the per-resource MIN of
        # the declared pool and whatever the provider reports.
        self._capacity_fn = capacity_fn
        self._lock = threading.Lock()
        self._seq = 0
        self._admitted: Dict[str, _Gang] = {}
        self._waiting: Dict[str, _Gang] = {}
        self._preempt: Dict[str, str] = {}  # key -> cause, engine-acknowledged
        self._kicks: List[Callable[[], None]] = []
        # Audit ledgers (testing/invariants.py): every admit with its
        # backfill verdict + the head-of-line wait at that instant, and
        # every acknowledged preemption (key, uid, cause) — exactly one
        # entry per physical preemption by construction (note_preempted
        # pops the pending marker first). BOUNDED rings (the Tracer
        # convention): a long-lived operator churning jobs must not grow
        # RSS forever, and /debugz snapshots copy these under the lock —
        # the invariants read the retained window, which is exactly the
        # recent history a test scenario produces.
        from collections import deque

        self.admit_log: "deque[dict]" = deque(maxlen=1024)
        self.preemption_ledger: "deque[tuple]" = deque(maxlen=512)

    # --------------------------------------------------------- capacity
    def effective_capacity(self) -> Optional[Dict[str, Fraction]]:
        """None = unlimited. With both a declared pool and a live
        provider, a resource's bound is the smaller of the two (a
        revocation can only shrink the pool, never grow past --capacity)."""
        cap = dict(self._declared) if self._declared is not None else None
        if self._capacity_fn is not None:
            try:
                live = self._capacity_fn()
            except Exception:  # noqa: BLE001 — a flaky provider must not wedge admission
                live = None
            if live:
                parsed = _parse_resources(live)
                if cap is None:
                    cap = parsed
                else:
                    for name, qty in parsed.items():
                        cap[name] = min(cap.get(name, qty), qty)
        return cap

    def _usage_locked(self, exclude=()) -> Dict[str, Fraction]:
        usage: Dict[str, Fraction] = {}
        for key, gang in self._admitted.items():
            if key in exclude:
                continue
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, Fraction(0)) + qty
        return usage

    def _ns_usage_locked(self, namespace: str, exclude=()) -> Dict[str, Fraction]:
        usage: Dict[str, Fraction] = {}
        for key, gang in self._admitted.items():
            if key in exclude or gang.namespace != namespace:
                continue
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, Fraction(0)) + qty
        return usage

    @staticmethod
    def _fits(demand, usage, cap) -> bool:
        """Resources absent from the pool are unconstrained (a pool
        declared in chips does not bound cpu)."""
        if cap is None:
            return True
        return all(
            usage.get(name, Fraction(0)) + qty <= cap[name]
            for name, qty in demand.items()
            if name in cap
        )

    def _quota_ok_locked(self, gang: _Gang, exclude=()) -> bool:
        quota = self.quotas.get(gang.namespace)
        if not quota:
            return True
        usage = self._ns_usage_locked(gang.namespace, exclude=exclude)
        return all(
            usage.get(name, Fraction(0)) + qty <= quota[name]
            for name, qty in gang.demand.items()
            if name in quota
        )

    # ------------------------------------------------------------- pump
    def _waiting_order_locked(self) -> List[_Gang]:
        return sorted(self._waiting.values(), key=lambda g: (-g.band, g.seq))

    def _admit_locked(self, gang: _Gang, now: float, backfill: bool,
                      head_wait: Optional[float]) -> None:
        self._waiting.pop(gang.key, None)
        gang.admitted_at = now
        gang.backfilled = backfill
        gang.blocked_on = ""
        gang.announced_admit = False
        self._admitted[gang.key] = gang
        self.admit_log.append({
            "key": gang.key, "band": gang.band, "backfill": backfill,
            "head_wait_at_admit": head_wait,
            "wait": now - gang.enqueued_at,
        })
        self.metrics.observe_admission_wait(
            gang.namespace, gang.kind, max(0.0, now - gang.enqueued_at)
        )
        if gang.kick is not None:
            self._kicks.append(gang.kick)

    def _mark_preempt_locked(self, gang: _Gang, cause: str) -> None:
        if gang.key in self._preempt:
            return
        self._preempt[gang.key] = cause
        if gang.kick is not None:
            self._kicks.append(gang.kick)

    def _pump_locked(self, now: float) -> None:
        """The decision procedure, run after every state change. Marks
        preemption victims, admits every currently-eligible waiter, and
        leaves a blocked_on verdict on the rest."""
        cap = self.effective_capacity()
        # Capacity revocation: the pool shrank under the admitted set —
        # preempt lowest-band (then most-recently-admitted) gangs until
        # what remains fits. Pending victims still count as usage until
        # the engine's counted teardown acknowledges them, so the check
        # excludes only gangs already marked.
        if cap is not None:
            victims_pool = sorted(
                (g for g in self._admitted.values() if g.key not in self._preempt),
                key=lambda g: (g.band, -g.victim_rank, -g.seq),
            )
            excluded = set(self._preempt)
            for victim in victims_pool:
                usage = self._usage_locked(exclude=excluded)
                if all(usage.get(r, Fraction(0)) <= cap[r] for r in cap):
                    break
                self._mark_preempt_locked(victim, PREEMPT_CAUSE_CAPACITY)
                excluded.add(victim.key)
        # Admission scan, priority order. Head-of-line = first waiter its
        # own quota allows; it admits as soon as it fits, schedules
        # preemption of strictly-lower bands when it doesn't, and bounds
        # backfill behind it by its age.
        # While preemptions are PENDING (marked but not yet acknowledged
        # by the engine's counted teardown), the capacity they will free
        # is spoken for — the head the arbiter is evicting FOR must get
        # it. Backfill is suppressed until the dust settles, or a victim
        # could slip right back into the gap its own eviction opened (and
        # the arbiter would evict it again: a preemption livelock).
        pending_preempt = bool(self._preempt)
        head: Optional[_Gang] = None
        head_wait = 0.0
        # Usage computed ONCE per pump and updated incrementally on each
        # admit (per-namespace views built lazily): the naive
        # recompute-per-waiter made every sync of every admitted job
        # O(admitted x waiters) inside this lock.
        usage = self._usage_locked()
        ns_usage: Dict[str, Dict[str, Fraction]] = {}

        def ns_usage_of(namespace: str) -> Dict[str, Fraction]:
            if namespace not in ns_usage:
                ns_usage[namespace] = self._ns_usage_locked(namespace)
            return ns_usage[namespace]

        def quota_ok(gang: _Gang) -> bool:
            quota = self.quotas.get(gang.namespace)
            if not quota:
                return True
            used = ns_usage_of(gang.namespace)
            return all(
                used.get(name, Fraction(0)) + qty <= quota[name]
                for name, qty in gang.demand.items()
                if name in quota
            )

        def charge(gang: _Gang) -> None:
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, Fraction(0)) + qty
            used = ns_usage_of(gang.namespace)
            for name, qty in gang.demand.items():
                used[name] = used.get(name, Fraction(0)) + qty

        for gang in self._waiting_order_locked():
            if not quota_ok(gang):
                gang.blocked_on = "quota"
                continue
            is_head = head is None
            if is_head:
                head = gang
                head_wait = now - gang.enqueued_at
            if self._fits(gang.demand, usage, cap):
                if is_head:
                    self._admit_locked(gang, now, backfill=False, head_wait=None)
                    charge(gang)
                    head = None  # the next eligible waiter takes the line
                elif (
                    not pending_preempt
                    and self.backfill_max_members > 0
                    and gang.members <= self.backfill_max_members
                    and head_wait < self.aging_seconds
                ):
                    self._admit_locked(gang, now, backfill=True,
                                       head_wait=head_wait)
                    charge(gang)
                else:
                    gang.blocked_on = "order"
                continue
            if is_head:
                # Priority preemption: strictly lower bands only — equal-
                # band contention waits its turn (FIFO within a band is
                # the fairness contract).
                candidates = sorted(
                    (g for g in self._admitted.values()
                     if g.band < gang.band and g.key not in self._preempt),
                    key=lambda g: (g.band, -g.victim_rank, -g.seq),
                )
                # Check-before-marking, INCLUDING the already-pending set:
                # a pump landing between a victim's mark and its
                # teardown-ack must see that the pending evictions alone
                # already satisfy the head — otherwise every intervening
                # pump would escalate one more innocent victim until the
                # whole lower band was condemned for a single head.
                freed: set = set(self._preempt)
                chosen: List[_Gang] = []
                satisfiable = self._fits(
                    gang.demand, self._usage_locked(exclude=freed), cap
                ) and self._quota_ok_locked(gang, exclude=freed)
                if not satisfiable:
                    for candidate in candidates:
                        chosen.append(candidate)
                        freed.add(candidate.key)
                        if self._fits(
                            gang.demand, self._usage_locked(exclude=freed), cap
                        ) and self._quota_ok_locked(gang, exclude=freed):
                            satisfiable = True
                            break
                if satisfiable:
                    for victim in chosen:
                        self._mark_preempt_locked(victim, PREEMPT_CAUSE_PRIORITY)
                    pending_preempt = True
                    gang.blocked_on = "priority"
                else:
                    gang.blocked_on = "capacity"
            else:
                gang.blocked_on = "capacity"
        self._update_gauges_locked()

    def _update_gauges_locked(self) -> None:
        depths: Dict[int, int] = {}
        for gang in self._waiting.values():
            depths[gang.band] = depths.get(gang.band, 0) + 1
        self.metrics.set_admission_queue_depths(
            {str(band): depth for band, depth in depths.items()}
        )

    def _drain_kicks_locked(self) -> List[Callable[[], None]]:
        kicks, self._kicks = self._kicks, []
        return kicks

    # -------------------------------------------------------- engine API
    def try_admit(
        self, *, key: str, kind: str, namespace: str, name: str, uid: str,
        priority_class: str = "", demand: Optional[Dict[str, Fraction]] = None,
        members: int = 0, has_pods: bool = False,
        kick: Optional[Callable[[], None]] = None,
        victim_rank: int = 0,
    ) -> AdmitResult:
        """One job's admission question, asked on every sync. Admitted
        jobs take a fast path (plus a pump so capacity revocations are
        noticed on the admitted side too); waiting jobs are (re)registered
        and the queue pumped. ``has_pods`` (live, non-terminating pods
        exist) is the adoption path: those pods were admitted by a prior
        operator incarnation and holding them "unborn" is impossible —
        admit unconditionally and let the revocation path resolve any
        over-commit."""
        try:
            band = parse_priority_class(priority_class)
        except ValueError:
            band = PRIORITY_CLASSES[""]  # stored pre-validation jobs: default band
        demand = dict(demand or {})
        with self._lock:
            now = self.clock()
            gang = self._admitted.get(key)
            if gang is not None:
                # Refresh demand (elastic resize changes it) and notice
                # revocations; a same-sync re-ask stays admitted.
                gang.demand = demand or gang.demand
                gang.members = members or gang.members
                gang.uid = uid or gang.uid
                gang.kick = kick or gang.kick
                gang.victim_rank = victim_rank
                self._pump_locked(now)
                newly = not gang.announced_admit
                gang.announced_admit = True
                waited = (
                    max(0.0, (gang.admitted_at or now) - gang.enqueued_at)
                    if newly else 0.0
                )
                kicks = self._drain_kicks_locked()
                result = AdmitResult(True, newly_admitted=newly, waited=waited)
            else:
                gang = self._waiting.get(key)
                if gang is None:
                    self._seq += 1
                    gang = _Gang(
                        key=key, kind=kind, namespace=namespace, name=name,
                        uid=uid, band=band, demand=demand, members=members,
                        seq=self._seq, enqueued_at=now,
                        victim_rank=victim_rank, kick=kick,
                    )
                    self._waiting[key] = gang
                else:
                    gang.band = band
                    gang.demand = demand or gang.demand
                    gang.members = members or gang.members
                    gang.uid = uid or gang.uid
                    gang.kick = kick or gang.kick
                    gang.victim_rank = victim_rank
                if has_pods:
                    self._admit_locked(gang, now, backfill=False, head_wait=None)
                    gang.announced_admit = True
                    self._pump_locked(now)
                    kicks = self._drain_kicks_locked()
                    result = AdmitResult(True, newly_admitted=True)
                else:
                    self._pump_locked(now)
                    if key in self._admitted:
                        gang.announced_admit = True
                        result = AdmitResult(
                            True, newly_admitted=True,
                            waited=max(0.0, now - gang.enqueued_at),
                        )
                    else:
                        newly_queued = not gang.announced_queue
                        gang.announced_queue = True
                        if (
                            gang.blocked_on == "quota"
                            and gang.reported_block != "quota"
                        ):
                            self.metrics.quota_denial_inc(namespace)
                        gang.reported_block = gang.blocked_on
                        result = AdmitResult(
                            False, newly_queued=newly_queued,
                            blocked_on=gang.blocked_on or "capacity",
                        )
                    kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()
        return result

    def preemption_requested(self, key: str) -> Optional[str]:
        """The pending preemption cause for a job, if any — the engine's
        signal to run the counted teardown."""
        with self._lock:
            return self._preempt.get(key)

    def note_preempted(self, key: str, uid: str, cause: str = "") -> bool:
        """Engine acknowledgment that the preemption's COUNTED status
        write is durable (or that nothing was left to tear down): release
        the gang's capacity, re-queue it at the head of its band with a
        fresh aging clock, and record the exactly-once ledger entry.
        Idempotent: a second call for an already-acknowledged preemption
        is a no-op (returns False) — the crash-retry path re-enters here
        after a teardown resume without double-counting."""
        with self._lock:
            pending = self._preempt.pop(key, None)
            if pending is None:
                return False
            cause = cause or pending
            now = self.clock()
            gang = self._admitted.pop(key, None)
            if gang is not None:
                band_seqs = [
                    g.seq for g in self._waiting.values() if g.band == gang.band
                ]
                gang.seq = (min(band_seqs) - 1) if band_seqs else gang.seq
                gang.enqueued_at = now
                gang.admitted_at = None
                gang.backfilled = False
                gang.announced_admit = False
                gang.announced_queue = False
                gang.reported_block = ""
                self._waiting[gang.key] = gang
                self.preemption_ledger.append((key, uid, cause))
                self.metrics.gang_preemption_inc(cause, str(gang.band))
            self._pump_locked(now)
            kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()
        return True

    def release(self, key: str) -> None:
        """The job left the contention domain (terminal, suspended, or
        deleted): free its capacity/quota and admit whoever is next. A
        key this controller never saw is a no-op — release is called
        unconditionally from every cleanup path. Releases the key's
        per-slice sub-entries ("<key>#slice-<s>") along with it: the
        cleanup paths know only the job, and a leaked slice admission
        would pin its share of the tenant's quota forever. The sub-key
        sweep runs only under slice granularity — the only mode that
        can create them — so the job-granular arbiter keeps its O(1)
        release on every terminal/suspend/delete sync."""
        with self._lock:
            doomed = {key}
            if self.slice_granular:
                prefix = key + "#slice-"
                doomed |= {
                    k
                    for k in (
                        set(self._admitted) | set(self._waiting)
                        | set(self._preempt)
                    )
                    if k.startswith(prefix)
                }
            released = False
            for k in doomed:
                released |= self._admitted.pop(k, None) is not None
                released |= self._waiting.pop(k, None) is not None
                self._preempt.pop(k, None)
            if not released:
                return
            self._pump_locked(self.clock())
            kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()

    def release_stale_granularity(self, key: str, sliced: bool) -> None:
        """Granularity-transition hygiene (an elastic resize crossing the
        numSlices>1 boundary switches which admission gate a job uses):
        entering the SLICED gate drops a stale plain-key registration;
        entering the FLAT gate drops stale '#slice-' sub-entries.
        Without this, the old granularity's admissions double-charge the
        pool and the tenant quota for the job's whole remaining life,
        and a pending preemption against a stale key is never serviced.
        Fast no-op when nothing stale exists — the flat branch probes the
        O(1) '#slice-0' sentinel (sliced registrations always include
        slice 0) before paying the full key scan, so a fleet of
        single-slice jobs never scans the arbiter per sync."""
        with self._lock:
            if sliced:
                doomed = [key] if (
                    key in self._admitted or key in self._waiting
                    or key in self._preempt
                ) else []
            else:
                sentinel = f"{key}#slice-0"
                if not (
                    sentinel in self._admitted or sentinel in self._waiting
                    or sentinel in self._preempt
                ):
                    return
                prefix = key + "#slice-"
                doomed = [
                    k
                    for k in (
                        set(self._admitted) | set(self._waiting)
                        | set(self._preempt)
                    )
                    if k.startswith(prefix)
                ]
            if not doomed:
                return
            for k in doomed:
                self._admitted.pop(k, None)
                self._waiting.pop(k, None)
                self._preempt.pop(k, None)
            self._pump_locked(self.clock())
            kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()

    # ------------------------------------------------------ observability
    def is_admitted(self, key: str) -> bool:
        with self._lock:
            return key in self._admitted

    def snapshot(self) -> dict:
        """The /debugz admission dump: bands, queue positions, aging
        clocks, usage vs capacity/quotas, pending preemptions, and the
        audit ledgers the invariants run over."""
        with self._lock:
            now = self.clock()
            cap = self.effective_capacity()

            def fmt(resources):
                return {k: str(v) for k, v in (resources or {}).items()}

            return {
                "capacity": fmt(cap) if cap is not None else None,
                "usage": fmt(self._usage_locked()),
                "quotas": {ns: fmt(q) for ns, q in self.quotas.items()},
                "namespace_usage": {
                    ns: fmt(self._ns_usage_locked(ns))
                    for ns in {g.namespace for g in self._admitted.values()}
                },
                "aging_seconds": self.aging_seconds,
                "backfill_max_members": self.backfill_max_members,
                "admitted": [
                    {
                        "key": g.key, "band": g.band, "members": g.members,
                        "demand": fmt(g.demand), "backfilled": g.backfilled,
                        "admitted_for": round(now - (g.admitted_at or now), 3),
                    }
                    for g in sorted(
                        self._admitted.values(), key=lambda g: (-g.band, g.seq)
                    )
                ],
                "waiting": [
                    {
                        "key": g.key, "band": g.band, "position": i,
                        "members": g.members, "demand": fmt(g.demand),
                        "waited": round(now - g.enqueued_at, 3),
                        "blocked_on": g.blocked_on,
                    }
                    for i, g in enumerate(self._waiting_order_locked())
                ],
                "preempting": dict(self._preempt),
                "admit_log": list(self.admit_log),
                "preemption_ledger": [list(t) for t in self.preemption_ledger],
            }
