"""Capacity-aware gang admission: quota'd queueing, priority preemption,
and bounded backfill (docs/design/gang_admission.md).

The reference operator fires PodGroups at Volcano and forgets them; the
gang unit here (per-slice PodGroups, the JOB_QUEUED condition) already
exists but admission was first-come and capacity-blind — under contention
jobs race, deadlock on partial gangs, or starve. This module is the
operator-side admission arbiter the Gavel line of work (arXiv:2008.09213)
argues for: a declared capacity pool, all-or-nothing job admission (a
job's pods stay UNBORN while it queues — no partial gang can ever exist),
per-tenant (namespace) quotas, priority bands from
``SchedulingPolicy.priorityClass``, preempt-lowest-priority-gang on
contention, and bounded backfill of small gangs into capacity gaps with
an aging bound so backfill can never starve the head-of-line gang.

Everything is deterministic given a deterministic call sequence and
clock: decisions are pure functions of (queue, pool, usage, seed) — the
DECISION PROCEDURE itself lives behind the policy seam in
core/policies.py (`policy.decide(PolicyState) -> Decisions`, selected
by --admission-policy: the default `priority` policy is the original
arbiter byte-for-byte; `gavel` adds heterogeneity-aware placement over
device-generation sub-pools; `drf` replaces hard quotas with weighted
work-conserving fairness). This class owns registration, decision
APPLICATION (in the policy's order), the preemption handshake, and the
audit ledgers — including the decision log, the byte-equality artifact
of the determinism contract. Seeded chaos/crash tiers replay
byte-identically with admission ON, and with the flag OFF (the default)
the engine never constructs this object at all and the PR 1–8 behavior
is untouched byte-for-byte.

Ordering rules of the DEFAULT policy, in one place:

- The wait queue is ordered by (band desc, seq asc): higher priority
  bands first, FIFO within a band. ``seq`` is a monotonic admission-
  controller sequence; a preempted gang re-enters at the HEAD of its
  band (seq below every current waiter of that band).
- The head-of-line is the first waiting gang whose own namespace quota
  would allow it (a tenant that exhausted its own quota must not hold
  the line against other tenants — its wait can only end with its own
  releases).
- A non-head gang may only be BACKFILLED: it must fit the free gap, its
  member count must not exceed ``backfill_max_members``, and the
  head-of-line must not have waited past ``aging_seconds`` — once the
  head ages past the bound, backfill stops until the head admits
  (starvation-freedom; audited from the admit log by
  testing/invariants.py).
- When the head does not fit, admitted gangs of STRICTLY lower band are
  preempted — lowest band first, most-recently-admitted first — until
  the head would fit. Victims are only MARKED here; the engine routes
  the teardown through the count-before-teardown disruption protocol
  and acknowledges with :meth:`note_preempted` once the counted write
  is durable, so the preemption lands in the budget-free
  ``disruptionCounts`` ledger exactly once.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left, insort
from dataclasses import dataclass, field
from fractions import Fraction
from typing import Callable, Dict, List, Optional, Tuple

from .job_controller import parse_quantity
from .policies import (
    Admit,
    AdmissionPolicy,
    GangView,
    PolicyState,
    PREEMPT_CAUSE_CAPACITY,
    PREEMPT_CAUSE_PRIORITY,
    PREEMPT_CAUSE_THROUGHPUT,
    Preempt,
    build_policy,
    fits,
    ratio_of,
)

# Priority bands for SchedulingPolicy.priorityClass. Scheduler-style
# class names map onto small integers; bare non-negative integers are
# accepted verbatim so clusters with numeric PriorityClass conventions
# can express finer ladders. Other legal PriorityClass names ride the
# DEFAULT band (never band 0 — an unrecognized name must not make a job
# globally preemptible); only un-nameable values (negative, non-DNS) are
# ValidationErrors at admission (api/defaulting.py).
PRIORITY_CLASSES = {
    "low": 0,
    "preemptible": 0,
    "best-effort": 0,
    "": 1,
    "default": 1,
    "normal": 1,
    "high": 2,
    "critical": 3,
}

# Preemption causes (the gang_preemptions_total{cause} label values):
# defined once in core/policies.py (the emitting side) and re-exported
# here, the historical import home — one source of truth, no drift.


import re as _re

# A legal Kubernetes PriorityClass name (DNS-1123 subdomain shape). Names
# outside the band vocabulary but inside this shape are legitimate
# cluster PriorityClasses the operator merely has no band opinion about —
# they ride the default band (and pass through to the PodGroup verbatim,
# exactly as before this layer existed). Anything outside the shape can
# never name a real PriorityClass and is a typed ValidationError.
_K8S_NAME_RE = _re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")


def parse_priority_class(value) -> int:
    """Band of a priorityClass: a known band name (case-insensitive), a
    bare non-negative integer, or any OTHER legal PriorityClass name —
    which maps to the default band (the operator ranks only its own band
    vocabulary; foreign class names are Volcano's business and must keep
    flowing through untouched). Raises ValueError only for values that
    could never name a PriorityClass: negatives (they would sort below
    every band and permanently starve the job) and non-DNS-shaped
    strings."""
    v = str(value or "").strip()
    band = PRIORITY_CLASSES.get(v.lower())
    if band is not None:
        return band
    if v.isdigit():
        return int(v)
    if _K8S_NAME_RE.match(v):
        return PRIORITY_CLASSES[""]
    raise ValueError(f"malformed priority class {value!r}")


def _parse_resource_entries(text):
    """The shared per-entry parse/validate of every resource-list flag:
    yields (name, qty) pairs. Quantities must be parse_quantity-legal
    and non-negative (zero is a legal bound; a negative pool or quota
    can never be satisfied and would silently wedge every tenant it
    applies to). Resource NAMES are free-form: unknown keys (device
    plugins, vendor resources) flow through verbatim, exactly like k8s
    extended resources."""
    for part in str(text or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, qty = part.partition("=")
        if not sep or not name.strip():
            raise ValueError(f"malformed resource entry {part!r} (want res=qty)")
        if parse_quantity(qty.strip()) < 0:  # raises on malformed quantities
            raise ValueError(
                f"resource entry {part!r}: quantity must be non-negative")
        yield name.strip(), qty.strip()


def parse_resource_list(text) -> Dict[str, str]:
    """Parse "res=qty[,res=qty...]" (the --capacity / quota flag syntax)
    into a resource dict; quantities stay validated strings. Empty
    input -> {}."""
    return dict(_parse_resource_entries(text))


def parse_quota_flag(text) -> Dict[str, Dict[str, str]]:
    """Parse one "--namespace-quota ns:res=qty[,res=qty...]" value."""
    ns, sep, resources = str(text or "").partition(":")
    if not sep or not ns.strip():
        raise ValueError(
            f"malformed quota {text!r} (want namespace:res=qty[,res=qty])"
        )
    return {ns.strip(): parse_resource_list(resources)}


def parse_capacity_flag(text) -> Tuple[Dict[str, str], Dict[str, Dict[str, str]]]:
    """Parse the extended --capacity syntax: plain "res=qty" entries
    declare the homogeneous pool exactly as before; "res@generation=qty"
    entries declare a device-GENERATION sub-pool (the gavel policy's
    placement unit — e.g. "pods@v5lite=8,pods@v6=8" is a 16-slot pool
    split across two chip generations). Returns (flat_entries,
    generations); the controller sums generation entries into the flat
    pool, so a generation-split pool bounds totals identically to its
    flat sum under generation-blind policies."""
    flat: Dict[str, str] = {}
    generations: Dict[str, Dict[str, str]] = {}
    for name, qty in _parse_resource_entries(text):
        resource, at, generation = name.partition("@")
        if at:
            if not resource or not generation:
                raise ValueError(
                    f"malformed generation entry {name}={qty} "
                    "(want res@generation=qty)"
                )
            bucket = generations.setdefault(generation, {})
            if resource in bucket:
                raise ValueError(
                    f"duplicate declaration of {resource!r} in "
                    f"generation {generation!r}"
                )
            bucket[resource] = qty
        else:
            flat[resource] = qty
    return flat, generations


def parse_tenant_weight(text) -> Dict[str, float]:
    """Parse one "--tenant-weight ns=w" value (the drf policy's weighted
    fairness knob). Weights must be positive finite numbers."""
    ns, sep, weight = str(text or "").partition("=")
    if not sep or not ns.strip():
        raise ValueError(f"malformed tenant weight {text!r} (want ns=weight)")
    try:
        value = float(weight.strip())
    except ValueError:
        raise ValueError(f"tenant weight {weight!r} is not a number")
    if not value > 0 or value != value or value == float("inf"):
        raise ValueError(f"tenant weight {weight!r} must be a positive "
                         "finite number")
    return {ns.strip(): value}


def gang_demand(groups: List[dict]) -> Dict[str, Fraction]:
    """Aggregate a job's gang groups (hooks.gang_groups output) into one
    admission demand: the summed minResources plus a synthetic ``pods``
    resource (the summed minMember) so a pool can be declared in plain
    pod slots even when templates carry no resource requests."""
    demand: Dict[str, Fraction] = {}
    members = Fraction(0)
    for group in groups:
        spec = group.get("spec") or {}
        members += int(spec.get("minMember") or 0)
        for name, qty in (spec.get("minResources") or {}).items():
            try:
                demand[name] = demand.get(name, Fraction(0)) + parse_quantity(qty)
            except (ValueError, ZeroDivisionError):
                continue  # malformed stored quantity: validation rejects new ones
    if members:
        demand["pods"] = demand.get("pods", Fraction(0)) + members
    return demand


def _parse_resources(resources) -> Dict[str, Fraction]:
    return {k: parse_quantity(v) for k, v in (resources or {}).items()}


@dataclass
class AdmitResult:
    """One try_admit verdict. ``newly_admitted``/``newly_queued`` fire
    exactly once per transition (the engine's event/span triggers);
    ``waited`` is the queue wait of a newly-admitted gang (the
    ``admission.queue`` span duration); ``blocked_on`` names the binding
    constraint of a queued gang (capacity | quota | order | priority)."""

    admitted: bool
    newly_admitted: bool = False
    newly_queued: bool = False
    waited: float = 0.0
    blocked_on: str = ""


@dataclass
class _Gang:
    key: str  # "<Kind>:<ns>/<name>" — the workqueue item identity
    kind: str
    namespace: str
    name: str
    uid: str
    band: int
    demand: Dict[str, Fraction]
    members: int
    seq: int
    enqueued_at: float
    # Victim preference within a band (higher = evicted sooner). The
    # engine ranks a multislice job's slices by slice index so the
    # coordinator slice (rank 0 — the worker-0 jax.distributed
    # coordinator every sibling depends on) is only ever chosen once no
    # other slice of any job in the band remains; flat jobs rank 0, so
    # with slice granularity off every ordering is byte-identical to
    # the rank-free arbiter.
    victim_rank: int = 0
    kick: Optional[Callable[[], None]] = None
    admitted_at: Optional[float] = None
    backfilled: bool = False
    blocked_on: str = ""
    # Per-generation normalized throughput from
    # schedulingPolicy.throughputRatios (empty = generation-
    # indifferent; absent generations ride 1.0 — policies.DEFAULT_RATIO).
    throughput_ratios: Dict[str, float] = field(default_factory=dict)
    # The generation sub-pool an ADMITTED gang was placed in (None on a
    # homogeneous pool, and while waiting).
    generation: Optional[str] = None
    # The demand the gate actually GRANTED at admit time (None while
    # waiting). The growth guard keeps ``demand`` pinned to this for
    # admitted gangs: an elastic grow that fits free headroom re-grants
    # in place, one that does not must re-queue through the gate — it may
    # never inflate usage past the pool by side effect of a spec refresh.
    admitted_demand: Optional[Dict[str, Fraction]] = None
    announced_admit: bool = False
    announced_queue: bool = False
    # Last blocked_on verdict the metric layer saw: the quota-denial
    # counter fires on the TRANSITION into "quota", not on every
    # fallback-requeue poll of a still-blocked gang (which would trip
    # the denial-rate alert forever for one patiently-waiting job).
    reported_block: str = ""
    # Admissibility-index bookkeeping (EngineOptions.admission_index;
    # dead weight when the index is OFF). ``reg`` is a monotonic
    # registration stamp assigned at every insertion into the waiting
    # dict: the maintained per-band order sorts by (seq, reg), which
    # reproduces the full scan's stable sort exactly — sorted() breaks
    # equal-seq ties by dict insertion order, and reg IS that order.
    # ``cached_view`` memoizes the GangView so an unchanged gang costs
    # zero per pump; every mutation of a view field clears it.
    reg: int = 0
    cached_view: Optional[GangView] = None


class AdmissionController:
    """The shared (one per operator process) admission arbiter. All
    state is in-memory by design — like expectations and the heartbeat
    observation cache, an operator restart rebuilds it from the cluster:
    jobs with live pods re-ADOPT their admission unconditionally
    (has_pods), jobs without re-queue, and any over-capacity left by the
    adoption resolves through the same preemption path a capacity
    revocation takes."""

    def __init__(
        self,
        capacity: Optional[Dict[str, str]] = None,
        quotas: Optional[Dict[str, Dict[str, str]]] = None,
        backfill_max_members: int = 8,
        aging_seconds: float = 300.0,
        clock=time.time,
        metrics=None,
        capacity_fn: Optional[Callable[[], Optional[Dict[str, str]]]] = None,
        slice_granular: bool = False,
        policy=None,
        generations: Optional[Dict[str, Dict[str, str]]] = None,
        generations_fn: Optional[Callable[[], Optional[Dict]]] = None,
        tenant_weights: Optional[Dict[str, float]] = None,
        seed: int = 0,
        decision_log_max: int = 4096,
        admission_index: bool = False,
        capacity_version_fn: Optional[Callable[[], int]] = None,
    ):
        # Per-SLICE admission (--admission-slice-granularity, flagged
        # headroom for multislice jobs): the ENGINE reads this and
        # registers each slice of a multislice job as its own demand
        # under the key "<Kind>:<ns>/<name>#slice-<s>" — individually
        # admittable, preemptable (slice-local counted teardown) and
        # backfillable, so a capacity revocation evicts one slice, not
        # the job. The arbiter itself is key-agnostic; the flag lives
        # here so the engine and the manager share one source of truth.
        self.slice_granular = bool(slice_granular)
        # The pluggable decision procedure (core/policies.py): a policy
        # name ("priority"|"gavel"|"drf"), a policy instance, or None =
        # the default priority policy — the PR 9 arbiter byte-for-byte.
        if policy is None or isinstance(policy, str):
            self.policy: AdmissionPolicy = build_policy(policy or "priority")
        else:
            self.policy = policy
        # Explicit decision seed, threaded into every PolicyState: the
        # classical policies ignore it (they are deterministic without
        # it), but it makes the purity contract auditable — decisions
        # are a function of (queue, pool, usage, seed) and nothing else,
        # and a learned/randomized policy gets its entropy ONLY here.
        self.seed = int(seed)
        self.tenant_weights: Dict[str, float] = {
            ns: float(w) for ns, w in (tenant_weights or {}).items()
        }
        # Device-generation sub-pools (the gavel placement unit). The
        # flat declared pool is the element-wise sum of the generation
        # pools plus any generation-less entries, so generation-blind
        # policies see exactly the total they always did.
        self._declared_gens: Dict[str, Dict[str, Fraction]] = {
            gen: _parse_resources(res)
            for gen, res in (generations or {}).items()
        }
        declared = _parse_resources(capacity) if capacity else None
        if self._declared_gens:
            declared = dict(declared or {})
            for res_map in self._declared_gens.values():
                for name, qty in res_map.items():
                    declared[name] = declared.get(name, Fraction(0)) + qty
        self._declared = declared
        self._generations_fn = generations_fn
        self.quotas: Dict[str, Dict[str, Fraction]] = {
            ns: _parse_resources(res) for ns, res in (quotas or {}).items()
        }
        self.backfill_max_members = int(backfill_max_members)
        self.aging_seconds = float(aging_seconds)
        self.clock = clock
        if metrics is None:
            from ..metrics import METRICS

            metrics = METRICS
        self.metrics = metrics
        # Live capacity provider (the memory cluster's schedulable-
        # capacity model, through which the seeded capacity-revocation
        # fault arrives): the effective pool is the per-resource MIN of
        # the declared pool and whatever the provider reports.
        self._capacity_fn = capacity_fn
        self._lock = threading.Lock()
        self._seq = 0
        self._admitted: Dict[str, _Gang] = {}
        self._waiting: Dict[str, _Gang] = {}
        self._preempt: Dict[str, str] = {}  # key -> cause, engine-acknowledged
        self._kicks: List[Callable[[], None]] = []
        # Audit ledgers (testing/invariants.py): every admit with its
        # backfill verdict + the head-of-line wait at that instant, and
        # every acknowledged preemption (key, uid, cause) — exactly one
        # entry per physical preemption by construction (note_preempted
        # pops the pending marker first). BOUNDED rings (the Tracer
        # convention): a long-lived operator churning jobs must not grow
        # RSS forever, and /debugz snapshots copy these under the lock —
        # the invariants read the retained window, which is exactly the
        # recent history a test scenario produces.
        from collections import deque

        self.admit_log: "deque[dict]" = deque(maxlen=1024)
        self.preemption_ledger: "deque[tuple]" = deque(maxlen=512)
        # The determinism-audit artifact: one entry per pump that took
        # an action (admits/preempts, in applied order) — a pure record
        # of the policy's observable schedule. Same-seed runs over the
        # same call sequence must produce byte-equal logs
        # (decision_log_lines); bounded like the other rings, but with
        # the cap EXPLICIT (decision_log_max — the fleet-sim smoke run
        # alone accretes ~4.1k entries) and a dropped counter so an
        # auditor can tell a complete log from a truncated window (a
        # byte-equality check over a silently-rotated ring would pass
        # on two DIFFERENT histories that merely share a tail).
        self.decision_log_max = max(1, int(decision_log_max))
        self.decision_log: "deque[dict]" = deque(maxlen=self.decision_log_max)
        self.decision_log_dropped = 0
        self._pump_count = 0
        # ---- admissibility index (EngineOptions.admission_index) ----
        # Default OFF: every structure below stays empty and _pump_locked
        # takes the historical full-scan path byte-for-byte. ON, a pump
        # touches only gangs that could NEWLY fit: (1) per-band minimum-
        # demand watermarks prune whole bands the free pool cannot cover;
        # (2) a capacity epoch / dirty bit short-circuits triggers that
        # changed nothing since the last scan (counted, never silent);
        # (3) the waiting order, the GangViews, and the usage snapshots
        # are maintained at mutation points instead of rebuilt per pump.
        # Schedule-equivalence is the contract: identical decision-log
        # bytes and verdicts vs the full scan (see
        # docs/design/gang_admission.md "Admissibility index").
        self._index = bool(admission_index)
        # Backend capacity-model epoch provider (the memory cluster's
        # schedulable_capacity_version): keys the effective-capacity /
        # effective-generations cache so a no-op pump does not re-parse
        # the pool, while a set_schedulable_capacity (revocation, grow)
        # invalidates it on the very next read.
        self._capacity_version_fn = capacity_version_fn
        self._cap_version_seen: object = object()  # never equals an int
        self._cap_cache: Optional[Dict[str, Fraction]] = None
        self._gens_cache: Dict[str, Dict[str, Fraction]] = {}
        # Waiting-set index: band -> gangs ordered by (seq, reg), plus
        # the band's minimum-demand watermark (per-resource min over its
        # members, kept only for resources every member demands).
        self._reg = 0
        self._band_order: Dict[int, List[_Gang]] = {}
        self._band_min: Dict[int, Dict[str, Fraction]] = {}
        # Admitted-set index: flat + per-tenant usage (exact Fraction
        # sums — value-identical to the scans), tenant gang counts (the
        # dominant-share tenant enumeration), and the memoized admitted
        # view tuple.
        self._usage_idx: Dict[str, Fraction] = {}
        self._ns_usage_idx: Dict[str, Dict[str, Fraction]] = {}
        self._ns_count: Dict[str, int] = {}
        self._admitted_views: Optional[tuple] = None
        # Dirty protocol: "full" = decide-relevant state changed since
        # the last scan; ("enqueue", key) = exactly one new waiter
        # arrived; None = clean. Together with the free-capacity vector
        # the last scan saw, this is the capacity epoch: a trigger that
        # changed neither is provably a fixpoint (re-deciding the
        # unchanged post-pump state yields zero actions) and skips.
        self._pending_delta = "full"
        self._scanned_cap: Optional[Dict[str, Fraction]] = None
        self._scanned_gens: Optional[Dict[str, Dict[str, Fraction]]] = None
        # Gauge memo: the admission gauges (queue depths, effective
        # throughput, dominant shares) are pure functions of (waiting
        # index, admitted set, cap). Mutation helpers flip the stale
        # bit; a decide-running pump whose inputs did not move since
        # the last publish re-publishes nothing — the values would be
        # bit-identical (same items, same iteration order).
        self._gauges_stale = True
        self._gauge_cap: Optional[Dict[str, Fraction]] = None

    # --------------------------------------------------------- capacity
    def _refresh_capacity_cache(self) -> bool:
        """True when the version-keyed capacity cache is authoritative
        (index ON and the backend exposes a capacity-model epoch); on
        an epoch move, re-derives both cached vectors. A provider error
        disables the cache for that read — a flaky provider must not
        freeze admission on a stale pool."""
        if not self._index or self._capacity_version_fn is None:
            return False
        try:
            version = self._capacity_version_fn()
        except Exception:  # noqa: BLE001
            return False
        if version != self._cap_version_seen:
            self._cap_cache = self._effective_capacity_uncached()
            self._gens_cache = self._effective_generations_uncached()
            self._cap_version_seen = version
        return True

    def effective_capacity(self) -> Optional[Dict[str, Fraction]]:
        """None = unlimited. With both a declared pool and a live
        provider, a resource's bound is the smaller of the two (a
        revocation can only shrink the pool, never grow past --capacity).
        With the admissibility index ON and a capacity_version_fn, the
        parsed vector is cached on the backend's capacity-model epoch —
        a no-op pump stops paying the re-parse, and a
        set_schedulable_capacity invalidates on the next read."""
        if self._refresh_capacity_cache():
            return dict(self._cap_cache) if self._cap_cache is not None else None
        return self._effective_capacity_uncached()

    def effective_generations(self) -> Dict[str, Dict[str, Fraction]]:
        """The device-generation sub-pools ({} = homogeneous), min-merged
        with the live provider like the flat pool; cached on the same
        capacity-model epoch (set_schedulable_capacity rewrites both)."""
        if self._refresh_capacity_cache():
            return {g: dict(r) for g, r in self._gens_cache.items()}
        return self._effective_generations_uncached()

    def _effective_capacity_uncached(self) -> Optional[Dict[str, Fraction]]:
        cap = dict(self._declared) if self._declared is not None else None
        if self._capacity_fn is not None:
            try:
                live = self._capacity_fn()
            except Exception:  # noqa: BLE001 — a flaky provider must not wedge admission
                live = None
            if live:
                parsed = _parse_resources(live)
                if cap is None:
                    cap = parsed
                else:
                    for name, qty in parsed.items():
                        cap[name] = min(cap.get(name, qty), qty)
        return cap

    def _effective_generations_uncached(self) -> Dict[str, Dict[str, Fraction]]:
        """With a live provider (the memory cluster's
        schedulable_generations), a declared generation's bound is the
        per-resource MIN of the two — a generation-scoped revocation can
        only shrink its sub-pool, mirroring the flat rule."""
        gens = {g: dict(r) for g, r in self._declared_gens.items()}
        if self._generations_fn is not None:
            try:
                live = self._generations_fn()
            except Exception:  # noqa: BLE001 — a flaky provider must not wedge admission
                live = None
            for gen, resources in (live or {}).items():
                if gen not in gens:
                    continue
                parsed = _parse_resources(resources)
                bucket = gens[gen]
                for name, qty in parsed.items():
                    bucket[name] = min(bucket.get(name, qty), qty)
        return gens

    def _usage_locked(self, exclude=()) -> Dict[str, Fraction]:
        usage: Dict[str, Fraction] = {}
        for key, gang in self._admitted.items():
            if key in exclude:
                continue
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, Fraction(0)) + qty
        return usage

    def _ns_usage_locked(self, namespace: str, exclude=()) -> Dict[str, Fraction]:
        usage: Dict[str, Fraction] = {}
        for key, gang in self._admitted.items():
            if key in exclude or gang.namespace != namespace:
                continue
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, Fraction(0)) + qty
        return usage

    # ------------------------------------------- admissibility index
    # Maintained mirrors of the per-pump scans, updated at the mutation
    # points (register/refresh/admit/demote/preempt-ack/release). Every
    # helper is a no-op with the index OFF, so the historical path never
    # touches them. Fraction arithmetic is exact, so the incremental
    # usage vectors are VALUE-identical to the scans — the one structure
    # deliberately not maintained incrementally is the float
    # effective-throughput gauge (float sums are order-sensitive and the
    # autoscaler digests its decisions).
    @staticmethod
    def _band_sort_key(gang: _Gang):
        return (gang.seq, gang.reg)

    def _index_wait_register_locked(self, gang: _Gang) -> None:
        """Gang inserted into the waiting DICT: stamp the registration
        order (the stable-sort tiebreak) and index it."""
        if not self._index:
            return
        self._reg += 1
        gang.reg = self._reg
        self._index_wait_insert_locked(gang)

    def _index_wait_insert_locked(self, gang: _Gang) -> None:
        if not self._index:
            return
        self._gauges_stale = True
        members = self._band_order.setdefault(gang.band, [])
        insort(members, gang, key=self._band_sort_key)
        wm = self._band_min.get(gang.band)
        if wm is None or len(members) == 1:
            self._band_min[gang.band] = dict(gang.demand)
        else:
            demand = gang.demand
            # Min-merge, keeping only resources EVERY member demands: a
            # resource some member lacks cannot prove that member unfit.
            self._band_min[gang.band] = {
                name: min(qty, demand[name])
                for name, qty in wm.items() if name in demand
            }

    def _index_wait_remove_locked(self, gang: _Gang) -> None:
        if not self._index:
            return
        self._gauges_stale = True
        members = self._band_order.get(gang.band)
        if not members:
            return
        i = bisect_left(members, self._band_sort_key(gang),
                        key=self._band_sort_key)
        if i < len(members) and members[i] is gang:
            del members[i]
        else:  # defensive: stamp drifted — fall back to identity scan
            for j, other in enumerate(members):
                if other is gang:
                    del members[j]
                    break
            else:
                return
        if not members:
            self._band_order.pop(gang.band, None)
            self._band_min.pop(gang.band, None)
            return
        wm = self._band_min.get(gang.band)
        if wm is None or any(
            gang.demand.get(name) == qty for name, qty in wm.items()
        ):
            # The leaver held (or tied) a band minimum: recompute
            # exactly. Otherwise keep the stale watermark — it is <=
            # the true minimum, so it can only under-prune, never
            # over-prune (soundness is one-sided by construction).
            self._recompute_band_min_locked(gang.band)

    def _recompute_band_min_locked(self, band: int) -> None:
        members = self._band_order.get(band)
        if not members:
            self._band_min.pop(band, None)
            return
        wm = dict(members[0].demand)
        for gang in members[1:]:
            demand = gang.demand
            wm = {
                name: min(qty, demand[name])
                for name, qty in wm.items() if name in demand
            }
            if not wm:
                break
        self._band_min[band] = wm

    def _index_usage_add_locked(self, gang: _Gang) -> None:
        self._gauges_stale = True
        usage = self._usage_idx
        bucket = self._ns_usage_idx.setdefault(gang.namespace, {})
        zero = Fraction(0)
        for name, qty in gang.demand.items():
            usage[name] = usage.get(name, zero) + qty
            bucket[name] = bucket.get(name, zero) + qty

    def _index_usage_sub_locked(self, gang: _Gang) -> None:
        self._gauges_stale = True
        usage = self._usage_idx
        bucket = self._ns_usage_idx.get(gang.namespace, {})
        zero = Fraction(0)
        for name, qty in gang.demand.items():
            left = usage.get(name, zero) - qty
            if left:
                usage[name] = left
            else:  # zero-pruned: `fits` reads .get(name, 0) either way
                usage.pop(name, None)
            ns_left = bucket.get(name, zero) - qty
            if ns_left:
                bucket[name] = ns_left
            else:
                bucket.pop(name, None)

    def _index_admit_add_locked(self, gang: _Gang) -> None:
        """Gang entered the admitted dict (view fields just changed)."""
        if not self._index:
            return
        gang.cached_view = None
        self._admitted_views = None
        self._index_usage_add_locked(gang)
        self._ns_count[gang.namespace] = (
            self._ns_count.get(gang.namespace, 0) + 1)

    def _index_admit_remove_locked(self, gang: _Gang) -> None:
        if not self._index:
            return
        self._admitted_views = None
        self._index_usage_sub_locked(gang)
        left = self._ns_count.get(gang.namespace, 0) - 1
        if left > 0:
            self._ns_count[gang.namespace] = left
        else:
            self._ns_count.pop(gang.namespace, None)
            self._ns_usage_idx.pop(gang.namespace, None)

    def _index_dirty_locked(self) -> None:
        """Decide-relevant state changed outside a pump: the next pump
        must run a full decide (the no-op short-circuit stands down)."""
        if self._index:
            self._pending_delta = "full"

    def _view_locked(self, gang: _Gang) -> GangView:
        view = gang.cached_view
        if view is None:
            view = self._gang_view(gang)
            gang.cached_view = view
        return view

    def _admitted_views_locked(self) -> tuple:
        views = self._admitted_views
        if views is None:
            views = tuple(
                self._view_locked(g) for g in self._admitted.values())
            self._admitted_views = views
        return views

    def _prune_ok_locked(self) -> bool:
        """May the waiting set be band-pruned for the active policy?
        Requires the policy's declared prune contract AND a quota-free
        pool (quota verdicts need every gang scanned, and the head-of-
        line selection is quota-aware)."""
        return (
            getattr(self.policy, "supports_waiting_prune", False)
            and not self.quotas
        )

    def _is_order_head_locked(self, gang: _Gang) -> bool:
        """Is this WAITING gang the (band desc, seq asc) order head —
        i.e. first in the top non-empty band?"""
        top = max(self._band_order)
        return self._band_order[top][0] is gang

    # ------------------------------------------------------------- pump
    # (Fit/quota predicates live in core/policies.py now — the seam owns
    # the decision procedure; this class owns registration, application,
    # and the audit ledgers.)
    def _waiting_order_locked(self) -> List[_Gang]:
        return sorted(self._waiting.values(), key=lambda g: (-g.band, g.seq))

    def _admit_locked(self, gang: _Gang, now: float, backfill: bool,
                      head_wait: Optional[float],
                      generation: Optional[str] = None) -> None:
        if self._waiting.pop(gang.key, None) is not None:
            self._index_wait_remove_locked(gang)
        gang.admitted_at = now
        gang.backfilled = backfill
        gang.blocked_on = ""
        gang.announced_admit = False
        gang.generation = generation
        gang.admitted_demand = dict(gang.demand)
        self._admitted[gang.key] = gang
        self._index_admit_add_locked(gang)
        entry = {
            "key": gang.key, "band": gang.band, "backfill": backfill,
            "head_wait_at_admit": head_wait,
            "wait": now - gang.enqueued_at,
        }
        if self._declared_gens:
            # Generation-pool bookkeeping rides the admit log only when
            # a generation pool exists, so the PR 9 entry shape (and
            # everything that string-compares it) is untouched on
            # homogeneous pools.
            entry["generation"] = generation
            entry["ratio"] = ratio_of(gang, generation)
            entry["best_ratio"] = max(
                ratio_of(gang, g) for g in sorted(self._declared_gens)
            )
            entry["members"] = gang.members
        self.admit_log.append(entry)
        self.metrics.observe_admission_wait(
            gang.namespace, gang.kind, max(0.0, now - gang.enqueued_at)
        )
        if gang.kick is not None:
            self._kicks.append(gang.kick)

    def _growth_fits_locked(self, gang: _Gang,
                            demand: Dict[str, Fraction]) -> bool:
        """Would re-granting ``demand`` to this ADMITTED gang (in place of
        its current charge) still fit the flat pool, its generation
        sub-pool, and its namespace quota? The growth guard's predicate:
        an elastic grow covered by free headroom is an in-place re-grant;
        one that is not must release and re-queue through the gate."""
        from .policies import fits as _fits

        exclude = {gang.key}
        if not _fits(demand, self._usage_locked(exclude),
                     self.effective_capacity()):
            return False
        quota = self.quotas.get(gang.namespace)
        if quota:
            used = self._ns_usage_locked(gang.namespace, exclude)
            if not all(
                used.get(name, Fraction(0)) + qty <= quota[name]
                for name, qty in demand.items()
                if name in quota
            ):
                return False
        gens = self.effective_generations()
        if gens and gang.generation in gens:
            gen_usage: Dict[str, Fraction] = {}
            for g in self._admitted.values():
                if g.key in exclude or g.generation != gang.generation:
                    continue
                for name, qty in g.demand.items():
                    gen_usage[name] = gen_usage.get(name, Fraction(0)) + qty
            if not _fits(demand, gen_usage, gens[gang.generation]):
                return False
        return True

    def _demote_to_queue_locked(self, gang: _Gang, now: float) -> None:
        """Release an admitted gang back to the wait queue (the growth
        guard's no-bypass path): head of its band with a fresh aging
        clock — it held capacity in good standing and must not lose its
        place to later arrivals for asking to grow."""
        if self._admitted.pop(gang.key, None) is not None:
            self._index_admit_remove_locked(gang)
        gang.admitted_at = None
        gang.backfilled = False
        gang.announced_admit = False
        gang.announced_queue = False
        gang.reported_block = ""
        gang.admitted_demand = None
        gang.generation = None
        band_seqs = [
            g.seq for g in self._waiting.values() if g.band == gang.band
        ]
        gang.seq = (min(band_seqs) - 1) if band_seqs else gang.seq
        gang.enqueued_at = now
        self._waiting[gang.key] = gang
        gang.cached_view = None
        self._index_wait_register_locked(gang)
        self._index_dirty_locked()

    def _mark_preempt_locked(self, gang: _Gang, cause: str) -> None:
        if gang.key in self._preempt:
            return
        self._preempt[gang.key] = cause
        if gang.kick is not None:
            self._kicks.append(gang.kick)

    def _adoption_generation_locked(self, gang: _Gang) -> Optional[str]:
        """Best-effort generation attribution for the has_pods adoption
        path (an operator restart must re-admit live pods wherever they
        physically run — leaving them generation-less would make every
        sub-pool look empty and let placement oversubscribe real chips).
        First-fit with room; when every sub-pool is full, the sorted-
        first generation takes the visible overcommit and the policies'
        generation-revocation sweep preempts to fit — the same path a
        flat adoption overcommit resolves through."""
        gens = self.effective_generations()
        if not gens:
            return None
        from .policies import fits as _fits

        usage: Dict[str, Dict[str, Fraction]] = {}
        for g in self._admitted.values():
            if g.generation is None:
                continue
            bucket = usage.setdefault(g.generation, {})
            for name, qty in g.demand.items():
                bucket[name] = bucket.get(name, Fraction(0)) + qty
        for name in sorted(gens):
            if _fits(gang.demand, usage.get(name, {}), gens[name]):
                return name
        return sorted(gens)[0]

    @staticmethod
    def _gang_view(gang: _Gang) -> GangView:
        return GangView(
            key=gang.key, namespace=gang.namespace, band=gang.band,
            seq=gang.seq, demand=gang.demand, members=gang.members,
            enqueued_at=gang.enqueued_at, victim_rank=gang.victim_rank,
            throughput_ratios=gang.throughput_ratios,
            generation=gang.generation,
        )

    def _policy_state_locked(self, now: float, cap) -> PolicyState:
        """The pure-function input (queue, pool, usage, seed) — an
        immutable view of everything a decision may legally depend on.
        No wall clock reaches the policy except ``now``, which is this
        controller's injected clock value, so fake-clock replays are
        exact."""
        return PolicyState(
            waiting=tuple(
                self._gang_view(g) for g in self._waiting_order_locked()
            ),
            admitted=tuple(
                self._gang_view(g) for g in self._admitted.values()
            ),
            pending_preempt=frozenset(self._preempt),
            capacity=cap,
            generations=self.effective_generations(),
            quotas=self.quotas,
            tenant_weights=self.tenant_weights,
            backfill_max_members=self.backfill_max_members,
            aging_seconds=self.aging_seconds,
            now=now,
            seed=self.seed,
        )

    def _pump_locked(self, now: float) -> None:
        """One pump = build the immutable PolicyState, ask the active
        policy for an ORDERED decision list (core/policies.py), and
        apply it verbatim: admits register capacity (admit-log entries,
        wait metrics, and requeue kicks land in list order — a policy's
        output order IS its observable schedule), preempts mark victims
        for the engine's counted teardown, and blocked verdicts land on
        whoever stays waiting. The default priority policy reproduces
        the PR 9 procedure byte-for-byte.

        With the admissibility index ON, the pump first consults the
        capacity epoch / dirty bit (_pump_indexed_locked): a trigger
        that changed nothing since the last scan short-circuits —
        counted, never silent — and a dirty pump runs decide over the
        maintained (optionally band-pruned) state instead of rebuilding
        it. Both paths share _apply_decisions_locked, so an acting pump
        writes byte-identical decision-log entries either way; skipped
        pumps still advance _pump_count and observe the pump histogram,
        keeping acting pumps' numbering and the pump_calls column
        identical to a full-scan run."""
        self._pump_count += 1
        pump_started = time.perf_counter()
        if self._index:
            self._pump_indexed_locked(now, pump_started)
            return
        cap = self.effective_capacity()
        state = self._policy_state_locked(now, cap)
        decisions = self.policy.decide(state)
        self._apply_decisions_locked(decisions, now)
        self._update_gauges_locked(cap)
        # Wall time (perf_counter), never the injected clock: under the
        # fleet simulator the virtual clock is frozen inside an event,
        # and the whole point of this histogram is the REAL per-pump
        # cost at fleet object counts.
        self.metrics.observe_admission_pump(
            time.perf_counter() - pump_started)

    def _pump_indexed_locked(self, now: float, pump_started: float) -> None:
        """The indexed pump. Skip rule (exact, not heuristic): if no
        decide-relevant mutation landed since the last scan, the last
        scan was ACTION-FREE (the only way the clean bit gets set), and
        the effective capacity/generation vectors are unchanged, the
        last scan's outcome is a FIXPOINT — any fitting+eligible gang
        would already have been admitted, the verdicts were computed
        against exactly the current usage, and time only enters decide
        through head_wait/aging, which can only retract backfill
        eligibility, never create an admit from nothing — so decide
        would return zero actions and identical verdicts for every
        policy. The
        arrival fast path extends this one step: a single new waiter
        that is not the order head and cannot fit the free pool gets
        its provable "capacity" verdict directly."""
        cap = self.effective_capacity()
        gens = self.effective_generations()
        delta = self._pending_delta
        unchanged = (
            delta != "full"
            and cap == self._scanned_cap
            and gens == self._scanned_gens
        )
        if unchanged:
            if delta is None:
                self.metrics.admission_pump_skipped_inc("no-capacity-delta")
                self.metrics.observe_admission_pump(
                    time.perf_counter() - pump_started)
                return
            gang = self._waiting.get(delta[1])
            if (
                gang is not None
                and cap is not None
                and self._prune_ok_locked()
                and not self._is_order_head_locked(gang)
                and not fits(gang.demand, self._usage_idx, cap)
            ):
                # Exactly one enqueue since a fixpoint scan: the scan
                # prefix before this gang replays unchanged (no admits
                # there — fixpoint), so by the time the full scan
                # reached it the head chain would already be occupied;
                # a non-head gang that cannot fit the free pool gets
                # verdict "capacity" under the prune contract.
                gang.blocked_on = "capacity"
                self._pending_delta = None
                self.metrics.admission_pump_skipped_inc("band-watermark")
                self.metrics.observe_admission_pump(
                    time.perf_counter() - pump_started)
                return
        state, pruned = self._policy_state_indexed_locked(now, cap, gens)
        decisions = self.policy.decide(state)
        acted = self._apply_decisions_locked(decisions, now)
        for gang in pruned:
            # Self-applied verdict for band-pruned gangs: the prune
            # contract guarantees the full scan would say exactly this.
            gang.blocked_on = "capacity"
        self._update_gauges_locked(cap)
        self._scanned_cap = cap
        self._scanned_gens = gens
        # Clean ONLY after a zero-action decide. An acting pump's blocked
        # verdicts were computed mid-scan, relative to pre-admission
        # usage — the full scan refreshes them on its NEXT pump (e.g. a
        # waiter verdict flips "capacity" -> "quota" once a same-tenant
        # admit lands), so the indexed pump must re-decide once too
        # before it may start skipping. The fixpoint argument for the
        # skip therefore always rests on an action-free scan. One exact
        # refinement: an acting pump that leaves the waiting set EMPTY
        # has no verdicts left to go stale (pending preemption marks are
        # idempotent — re-deciding emits nothing new), so it may go
        # clean immediately.
        self._pending_delta = "full" if (acted and self._waiting) else None
        self.metrics.observe_admission_pump(
            time.perf_counter() - pump_started)

    def _policy_state_indexed_locked(
        self, now: float, cap, gens,
    ) -> Tuple[PolicyState, List[_Gang]]:
        """PolicyState from the maintained structures, optionally band-
        pruned. For every band whose minimum-demand watermark cannot fit
        the free pool (some resource r with usage[r] + watermark[r] >
        cap[r] — every member's demand[r] >= watermark[r], so NO member
        fits), only the band's first gang is passed through (the scan's
        head chain stops at the first blocked waiter, which is always a
        kept gang) and the rest are returned for the self-applied
        "capacity" verdict. A policy that cannot honor the prune (drf)
        or a quota'd pool falls back to the unpruned maintained state —
        counted via admission_index_fallback_total."""
        prune_ok = self._prune_ok_locked()
        if not prune_ok:
            self.metrics.admission_index_fallback_inc(self.policy.name)
        prune = prune_ok and cap is not None
        waiting: List[GangView] = []
        pruned: List[_Gang] = []
        usage = self._usage_idx
        zero = Fraction(0)
        for band in sorted(self._band_order, reverse=True):
            members = self._band_order[band]
            if prune and len(members) > 1:
                wm = self._band_min.get(band)
                if wm and any(
                    name in cap and usage.get(name, zero) + qty > cap[name]
                    for name, qty in wm.items()
                ):
                    waiting.append(self._view_locked(members[0]))
                    pruned.extend(members[1:])
                    self.metrics.admission_pump_skipped_inc("band-watermark")
                    continue
            for gang in members:
                waiting.append(self._view_locked(gang))
        state = PolicyState(
            waiting=tuple(waiting),
            admitted=self._admitted_views_locked(),
            pending_preempt=frozenset(self._preempt),
            capacity=cap,
            generations=gens,
            quotas=self.quotas,
            tenant_weights=self.tenant_weights,
            backfill_max_members=self.backfill_max_members,
            aging_seconds=self.aging_seconds,
            now=now,
            seed=self.seed,
            # The maintained admitted-usage vector (exact Fractions —
            # value-identical to the scan): decide's prologue copies it
            # instead of re-summing the admitted set per pump.
            usage=dict(self._usage_idx),
        )
        return state, pruned

    def _apply_decisions_locked(self, decisions, now: float) -> bool:
        """Apply the policy's ordered decision list verbatim; True when
        any action actually landed (the indexed pump's clean/dirty
        signal — an acting pump may not mark the state clean)."""
        applied: List[list] = []
        admitted_keys: set = set()
        for action in decisions.actions:
            if isinstance(action, Admit):
                gang = self._waiting.get(action.key)
                if gang is None:
                    continue  # raced away (released mid-decision impossible under the lock; defensive)
                self._admit_locked(
                    gang, now, backfill=action.backfill,
                    head_wait=action.head_wait,
                    generation=action.generation,
                )
                admitted_keys.add(action.key)
                applied.append(
                    ["admit", action.key, bool(action.backfill),
                     action.generation])
            elif isinstance(action, Preempt):
                gang = self._admitted.get(action.key)
                if gang is None:
                    continue
                if gang.key not in self._preempt:
                    applied.append(["preempt", action.key, action.cause])
                self._mark_preempt_locked(gang, action.cause)
        for key, verdict in decisions.blocked.items():
            if key in admitted_keys:
                continue  # actions win over a stale verdict (drf's re-sorted passes)
            gang = self._waiting.get(key)
            if gang is not None:
                gang.blocked_on = verdict
        if applied:
            if len(self.decision_log) >= self.decision_log_max:
                # The ring is about to rotate: count the eviction so the
                # determinism audit knows its window is truncated.
                self.decision_log_dropped += 1
            self.decision_log.append(
                {"pump": self._pump_count, "policy": self.policy.name,
                 "seed": self.seed, "actions": applied}
            )
        return bool(applied)

    def _update_gauges_locked(self, cap=None) -> None:
        if self._index:
            # Gauge memo: these gauges are pure functions of (waiting
            # index, admitted set, cap). If nothing moved since the
            # last publish and the capacity vector is value-equal, the
            # recomputed floats would be bit-identical — skip the
            # re-publish. Index OFF keeps the publish-every-pump
            # behaviour untouched.
            if not self._gauges_stale and cap == self._gauge_cap:
                return
            self._gauges_stale = False
            self._gauge_cap = dict(cap) if cap is not None else None
            # Band depths straight off the maintained index (empty
            # bands are deleted on removal, so the key set matches the
            # scan's).
            depths = {
                band: len(members)
                for band, members in self._band_order.items() if members
            }
        else:
            depths = {}
            for gang in self._waiting.values():
                depths[gang.band] = depths.get(gang.band, 0) + 1
        self.metrics.set_admission_queue_depths(
            {str(band): depth for band, depth in depths.items()}
        )
        self.metrics.set_gauge(
            "training_operator_admission_effective_throughput",
            self._effective_throughput_locked(),
        )
        self.metrics.set_admission_dominant_shares(
            self._dominant_shares_locked(cap)
        )

    def _effective_throughput_locked(self) -> float:
        """Fleet-wide effective throughput of the admitted set:
        Σ ratio(assigned generation) × members — the Gavel objective in
        normalized chip-equivalents. On a homogeneous pool every ratio
        is 1.0 and this is simply the admitted member count."""
        return float(sum(
            ratio_of(g, g.generation) * max(g.members, 1)
            for g in self._admitted.values()
        ))

    def _dominant_shares_locked(self, cap=None) -> Dict[str, float]:
        """Per-tenant dominant share: max over pool resources of
        usage/capacity (the DRF coordinate). Empty without a bounded
        pool — shares are undefined against infinity."""
        if cap is None:
            cap = self.effective_capacity()
        if not cap:
            return {}
        shares: Dict[str, float] = {}
        if self._index:
            # Maintained tenant set + usage: Fraction sums are exact, so
            # the float conversion (and the round) lands on the same
            # value the scan would produce.
            namespaces = sorted(self._ns_count)
        else:
            namespaces = sorted({g.namespace for g in self._admitted.values()})
        for ns in namespaces:
            used = (
                self._ns_usage_idx.get(ns, {}) if self._index
                else self._ns_usage_locked(ns)
            )
            share = 0.0
            for resource, bound in cap.items():
                if bound <= 0:
                    continue
                share = max(share, float(used.get(resource, Fraction(0)) / bound))
            shares[ns] = round(share, 6)
        return shares

    def _drain_kicks_locked(self) -> List[Callable[[], None]]:
        kicks, self._kicks = self._kicks, []
        return kicks

    # -------------------------------------------------------- engine API
    def try_admit(
        self, *, key: str, kind: str, namespace: str, name: str, uid: str,
        priority_class: str = "", demand: Optional[Dict[str, Fraction]] = None,
        members: int = 0, has_pods: bool = False,
        kick: Optional[Callable[[], None]] = None,
        victim_rank: int = 0,
        throughput_ratios: Optional[Dict[str, float]] = None,
    ) -> AdmitResult:
        """One job's admission question, asked on every sync. Admitted
        jobs take a fast path (plus a pump so capacity revocations are
        noticed on the admitted side too); waiting jobs are (re)registered
        and the queue pumped. ``has_pods`` (live, non-terminating pods
        exist) is the adoption path: those pods were admitted by a prior
        operator incarnation and holding them "unborn" is impossible —
        admit unconditionally and let the revocation path resolve any
        over-commit."""
        try:
            band = parse_priority_class(priority_class)
        except ValueError:
            band = PRIORITY_CLASSES[""]  # stored pre-validation jobs: default band
        demand = dict(demand or {})
        with self._lock:
            now = self.clock()
            gang = self._admitted.get(key)
            if gang is not None and demand:
                # Growth guard (no-bypass rule): an elastic resize that
                # RAISES an admitted gang's demand is a fresh capacity
                # ask, not a bookkeeping refresh. Covered by free
                # headroom it re-grants in place (below, unchanged);
                # beyond headroom it must queue through the gate — while
                # the old world's pods still live (resize teardown in
                # flight) the gang stays admitted at its GRANTED demand
                # so the pool keeps charging what actually runs, and
                # once they are gone it re-queues at the head of its
                # band instead of inflating usage past the pool (which
                # would preempt an innocent victim via the revocation
                # sweep).
                granted = gang.admitted_demand
                grew = granted is not None and any(
                    qty > granted.get(name, Fraction(0))
                    for name, qty in demand.items()
                )
                if grew and not self._growth_fits_locked(gang, demand):
                    if has_pods:
                        demand = dict(granted)
                    else:
                        self._demote_to_queue_locked(gang, now)
                        gang = None
            if gang is not None:
                # Refresh demand (elastic resize changes it) and notice
                # revocations; a same-sync re-ask stays admitted.
                demand_changed = view_changed = False
                if self._index:
                    # Value comparison, not identity: the steady-state
                    # re-ask rebinds equal dicts every sync, and a
                    # no-change re-ask must stay a clean (skippable)
                    # trigger. uid/kick changes are decide-invisible.
                    demand_changed = bool(demand) and demand != gang.demand
                    view_changed = (
                        demand_changed
                        or (bool(members) and members != gang.members)
                        or victim_rank != gang.victim_rank
                        or (throughput_ratios is not None
                            and dict(throughput_ratios)
                            != gang.throughput_ratios)
                    )
                if demand_changed:
                    self._index_usage_sub_locked(gang)
                gang.demand = demand or gang.demand
                gang.admitted_demand = dict(gang.demand)
                gang.members = members or gang.members
                gang.uid = uid or gang.uid
                gang.kick = kick or gang.kick
                gang.victim_rank = victim_rank
                if throughput_ratios is not None:
                    # Full replace, including {} — deleting the map from
                    # the spec must clear the stored ratios, or gavel
                    # keeps placing on ratios the API object no longer
                    # declares.
                    gang.throughput_ratios = dict(throughput_ratios)
                if demand_changed:
                    self._index_usage_add_locked(gang)
                if view_changed:
                    gang.cached_view = None
                    self._admitted_views = None
                    self._index_dirty_locked()
                    # members/ratio edits move the effective-throughput
                    # gauge even when demand (and thus usage) held still.
                    self._gauges_stale = True
                self._pump_locked(now)
                newly = not gang.announced_admit
                gang.announced_admit = True
                waited = (
                    max(0.0, (gang.admitted_at or now) - gang.enqueued_at)
                    if newly else 0.0
                )
                kicks = self._drain_kicks_locked()
                result = AdmitResult(True, newly_admitted=newly, waited=waited)
            else:
                gang = self._waiting.get(key)
                if gang is None:
                    self._seq += 1
                    gang = _Gang(
                        key=key, kind=kind, namespace=namespace, name=name,
                        uid=uid, band=band, demand=demand, members=members,
                        seq=self._seq, enqueued_at=now,
                        victim_rank=victim_rank, kick=kick,
                        throughput_ratios=dict(throughput_ratios or {}),
                    )
                    self._waiting[key] = gang
                    if self._index:
                        self._index_wait_register_locked(gang)
                        # Single-enqueue delta: the arrival fast path
                        # may verdict this gang without a decide. Any
                        # second mutation before a scan escalates to a
                        # full dirty bit.
                        self._pending_delta = (
                            ("enqueue", key)
                            if self._pending_delta is None else "full")
                else:
                    wait_changed = False
                    if self._index:
                        wait_changed = (
                            band != gang.band
                            or (bool(demand) and demand != gang.demand)
                            or (bool(members) and members != gang.members)
                            or victim_rank != gang.victim_rank
                            or (throughput_ratios is not None
                                and dict(throughput_ratios)
                                != gang.throughput_ratios)
                        )
                        if wait_changed:
                            # Reposition under the OLD (band, seq, reg)
                            # before mutating; reg is kept — the gang's
                            # dict position (the stable-sort tiebreak)
                            # did not change.
                            self._index_wait_remove_locked(gang)
                    gang.band = band
                    gang.demand = demand or gang.demand
                    gang.members = members or gang.members
                    gang.uid = uid or gang.uid
                    gang.kick = kick or gang.kick
                    gang.victim_rank = victim_rank
                    if throughput_ratios is not None:
                        gang.throughput_ratios = dict(throughput_ratios)
                    if wait_changed:
                        gang.cached_view = None
                        self._index_wait_insert_locked(gang)
                        self._index_dirty_locked()
                if has_pods:
                    self._admit_locked(
                        gang, now, backfill=False, head_wait=None,
                        generation=self._adoption_generation_locked(gang),
                    )
                    gang.announced_admit = True
                    self._index_dirty_locked()
                    self._pump_locked(now)
                    kicks = self._drain_kicks_locked()
                    result = AdmitResult(True, newly_admitted=True)
                else:
                    self._pump_locked(now)
                    if key in self._admitted:
                        gang.announced_admit = True
                        result = AdmitResult(
                            True, newly_admitted=True,
                            waited=max(0.0, now - gang.enqueued_at),
                        )
                    else:
                        newly_queued = not gang.announced_queue
                        gang.announced_queue = True
                        if (
                            gang.blocked_on == "quota"
                            and gang.reported_block != "quota"
                        ):
                            self.metrics.quota_denial_inc(namespace)
                        gang.reported_block = gang.blocked_on
                        result = AdmitResult(
                            False, newly_queued=newly_queued,
                            blocked_on=gang.blocked_on or "capacity",
                        )
                    kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()
        return result

    def preemption_requested(self, key: str) -> Optional[str]:
        """The pending preemption cause for a job, if any — the engine's
        signal to run the counted teardown."""
        with self._lock:
            return self._preempt.get(key)

    def note_preempted(self, key: str, uid: str, cause: str = "") -> bool:
        """Engine acknowledgment that the preemption's COUNTED status
        write is durable (or that nothing was left to tear down): release
        the gang's capacity, re-queue it at the head of its band with a
        fresh aging clock, and record the exactly-once ledger entry.
        Idempotent: a second call for an already-acknowledged preemption
        is a no-op (returns False) — the crash-retry path re-enters here
        after a teardown resume without double-counting."""
        with self._lock:
            pending = self._preempt.pop(key, None)
            if pending is None:
                return False
            cause = cause or pending
            now = self.clock()
            gang = self._admitted.pop(key, None)
            if gang is not None:
                self._index_admit_remove_locked(gang)
                if cause == PREEMPT_CAUSE_THROUGHPUT:
                    # A gavel swap victim YIELDS its place: re-queueing
                    # at the head of its band (the priority/capacity
                    # contract) would let an equal-band victim overtake
                    # the very head it was evicted for and re-take the
                    # vacated generation — the swap would churn forever
                    # without the throughput gain that justified it.
                    # Tail re-queue puts it behind the head; it
                    # re-places work-conservingly on what remains.
                    self._seq += 1
                    gang.seq = self._seq
                else:
                    band_seqs = [
                        g.seq for g in self._waiting.values()
                        if g.band == gang.band
                    ]
                    gang.seq = (min(band_seqs) - 1) if band_seqs else gang.seq
                gang.enqueued_at = now
                gang.admitted_at = None
                gang.backfilled = False
                gang.announced_admit = False
                gang.announced_queue = False
                gang.reported_block = ""
                gang.generation = None  # re-placed fresh on re-admission
                self._waiting[gang.key] = gang
                gang.cached_view = None
                self._index_wait_register_locked(gang)
                self.preemption_ledger.append((key, uid, cause))
                self.metrics.gang_preemption_inc(cause, str(gang.band))
            # Dirty even when the gang was already gone: popping the
            # pending-preempt marker alone changes decide's input (it
            # suppresses backfill and excludes revocation victims).
            self._index_dirty_locked()
            self._pump_locked(now)
            kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()
        return True

    def release(self, key: str) -> None:
        """The job left the contention domain (terminal, suspended, or
        deleted): free its capacity/quota and admit whoever is next. A
        key this controller never saw is a no-op — release is called
        unconditionally from every cleanup path. Releases the key's
        per-slice sub-entries ("<key>#slice-<s>") along with it: the
        cleanup paths know only the job, and a leaked slice admission
        would pin its share of the tenant's quota forever. The sub-key
        sweep runs only under slice granularity — the only mode that
        can create them — so the job-granular arbiter keeps its O(1)
        release on every terminal/suspend/delete sync."""
        with self._lock:
            doomed = {key}
            if self.slice_granular:
                prefix = key + "#slice-"
                doomed |= {
                    k
                    for k in (
                        set(self._admitted) | set(self._waiting)
                        | set(self._preempt)
                    )
                    if k.startswith(prefix)
                }
            released = False
            for k in doomed:
                admitted = self._admitted.pop(k, None)
                if admitted is not None:
                    released = True
                    self._index_admit_remove_locked(admitted)
                waiter = self._waiting.pop(k, None)
                if waiter is not None:
                    released = True
                    self._index_wait_remove_locked(waiter)
                if self._preempt.pop(k, None) is not None:
                    # No pump on a pending-only pop (historical
                    # behavior), but the NEXT pump must not skip: the
                    # pending set is decide input.
                    self._index_dirty_locked()
            if not released:
                return
            self._index_dirty_locked()
            self._pump_locked(self.clock())
            kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()

    def release_stale_granularity(self, key: str, sliced: bool) -> None:
        """Granularity-transition hygiene (an elastic resize crossing the
        numSlices>1 boundary switches which admission gate a job uses):
        entering the SLICED gate drops a stale plain-key registration;
        entering the FLAT gate drops stale '#slice-' sub-entries.
        Without this, the old granularity's admissions double-charge the
        pool and the tenant quota for the job's whole remaining life,
        and a pending preemption against a stale key is never serviced.
        Fast no-op when nothing stale exists — the flat branch probes the
        O(1) '#slice-0' sentinel (sliced registrations always include
        slice 0) before paying the full key scan, so a fleet of
        single-slice jobs never scans the arbiter per sync."""
        with self._lock:
            if sliced:
                doomed = [key] if (
                    key in self._admitted or key in self._waiting
                    or key in self._preempt
                ) else []
            else:
                sentinel = f"{key}#slice-0"
                if not (
                    sentinel in self._admitted or sentinel in self._waiting
                    or sentinel in self._preempt
                ):
                    return
                prefix = key + "#slice-"
                doomed = [
                    k
                    for k in (
                        set(self._admitted) | set(self._waiting)
                        | set(self._preempt)
                    )
                    if k.startswith(prefix)
                ]
            if not doomed:
                return
            for k in doomed:
                admitted = self._admitted.pop(k, None)
                if admitted is not None:
                    self._index_admit_remove_locked(admitted)
                waiter = self._waiting.pop(k, None)
                if waiter is not None:
                    self._index_wait_remove_locked(waiter)
                self._preempt.pop(k, None)
            self._index_dirty_locked()
            self._pump_locked(self.clock())
            kicks = self._drain_kicks_locked()
        for fn in kicks:
            fn()

    # ------------------------------------------------------ observability
    def is_admitted(self, key: str) -> bool:
        with self._lock:
            return key in self._admitted

    def effective_throughput(self) -> float:
        """Current fleet-wide effective throughput (Σ ratio × members
        over admitted gangs) — the admission_effective_throughput gauge
        value, exposed directly for the contention benchmark's
        time-integral."""
        with self._lock:
            return self._effective_throughput_locked()

    def dominant_shares(self) -> Dict[str, float]:
        """Per-tenant dominant shares (the admission_dominant_share
        gauge values) — the fairness coordinate the drf gate samples."""
        with self._lock:
            return self._dominant_shares_locked()

    def decision_log_lines(self) -> List[str]:
        """The decision log as canonical JSON lines — the byte-equality
        artifact of the determinism regression (same seed + same call
        sequence => identical lines, across runs and policies)."""
        import json

        with self._lock:
            entries = list(self.decision_log)
        return [
            json.dumps(entry, sort_keys=True, separators=(",", ":"))
            for entry in entries
        ]

    def snapshot(self) -> dict:
        """The /debugz admission dump: bands, queue positions, aging
        clocks, usage vs capacity/quotas, pending preemptions, the audit
        ledgers the invariants run over — and, since the policy seam:
        the active policy name + seed, the per-generation sub-pools with
        their usage, and the per-tenant dominant shares. All additive
        keys: the PR 9 shape (what the smoke JSON and older dashboards
        read) is unchanged."""
        with self._lock:
            now = self.clock()
            cap = self.effective_capacity()
            gens = self.effective_generations()
            gen_usage: Dict[str, Dict[str, Fraction]] = {}
            for g in self._admitted.values():
                if g.generation is None:
                    continue
                bucket = gen_usage.setdefault(g.generation, {})
                for name, qty in g.demand.items():
                    bucket[name] = bucket.get(name, Fraction(0)) + qty

            def fmt(resources):
                return {k: str(v) for k, v in (resources or {}).items()}

            out = {
                "policy": self.policy.name,
                "seed": self.seed,
                "capacity": fmt(cap) if cap is not None else None,
                "usage": fmt(self._usage_locked()),
                "quotas": {ns: fmt(q) for ns, q in self.quotas.items()},
                "namespace_usage": {
                    ns: fmt(self._ns_usage_locked(ns))
                    for ns in sorted(
                        {g.namespace for g in self._admitted.values()}
                    )
                },
                "aging_seconds": self.aging_seconds,
                "backfill_max_members": self.backfill_max_members,
                "admitted": [
                    {
                        "key": g.key, "band": g.band, "members": g.members,
                        "demand": fmt(g.demand), "backfilled": g.backfilled,
                        "admitted_demand": fmt(
                            g.admitted_demand
                            if g.admitted_demand is not None else g.demand
                        ),
                        "admitted_for": round(now - (g.admitted_at or now), 3),
                        **({"generation": g.generation} if gens else {}),
                    }
                    for g in sorted(
                        self._admitted.values(), key=lambda g: (-g.band, g.seq)
                    )
                ],
                "waiting": [
                    {
                        "key": g.key, "band": g.band, "position": i,
                        "members": g.members, "demand": fmt(g.demand),
                        "waited": round(now - g.enqueued_at, 3),
                        "blocked_on": g.blocked_on,
                    }
                    for i, g in enumerate(self._waiting_order_locked())
                ],
                "preempting": dict(self._preempt),
                "admit_log": list(self.admit_log),
                "preemption_ledger": [list(t) for t in self.preemption_ledger],
                "effective_throughput": self._effective_throughput_locked(),
                "dominant_shares": self._dominant_shares_locked(cap),
                # Additive since the explicit decision-log bound: how
                # big the audit ring is and how many entries it has
                # rotated out (0 = the log is the complete history).
                "decision_log_max": self.decision_log_max,
                "decision_log_dropped": self.decision_log_dropped,
            }
            if self.tenant_weights:
                out["tenant_weights"] = dict(sorted(
                    self.tenant_weights.items()))
            if gens:
                out["generations"] = {
                    gen: {
                        "capacity": fmt(gens[gen]),
                        "usage": fmt(gen_usage.get(gen, {})),
                    }
                    for gen in sorted(gens)
                }
            return out
