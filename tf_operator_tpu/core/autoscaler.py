"""Signal-driven gang autoscaler: closes the loop from observed signals
(free-capacity watermarks, queue pressure, workload throughput, disruption
churn) back into the EXISTING elastic spec-resize path.

Elastic resize, suspend/resume, and preemption-resume all work today, but
only when a human edits the spec — the fleet pays for idle capacity while
queued gangs wait, and oversized gangs starve the admission pool. Podracer
(arXiv:2104.06272) is the exemplar: treating worker count as a fluid
resource is what makes large JAX fleets cheap; Gavel (arXiv:2008.09213)
shows throughput-aware allocation decisions compound. This module is the
controller that acts on the signals those PRs built:

- free-capacity watermarks from the admission pool snapshot (PR 9);
- queue depth per band from the same snapshot;
- per-job throughput from the heartbeat ``tokens_per_sec`` lease stream
  (PR 12's ``training_workload_tokens_per_sec`` signal, read at the
  source — the lease annotations — so the autoscaler needs no metrics
  round-trip);
- the checkpoint-step rider (``record_checkpoint``) on the same leases,
  the coordination signal for shrink;
- disruption pressure from the per-job ledgers (cooldown after churn);
- ``admission_effective_throughput`` placement quality: with the gavel
  policy's generation sub-pools declared, grow candidates are ordered by
  their throughput ratio on the generation with the most FREED capacity.

Determinism contract (the ``core/policies.py`` contract, verbatim): the
decision procedure is the pure function ``decide(state, config)`` over an
immutable :class:`AutoscalerState` — no wall clock, no ambient state, an
injected clock value and an explicit seed — so seeded fake-clock replays
produce byte-equal decision logs (``decision_log_lines``). All hysteresis
memory (surplus hold clocks, per-job dwell stamps, cooldowns, pending
shrink proposals, grow baselines) lives in the CONTROLLER and is
snapshotted INTO the state each tick; ``decide`` never mutates it.

Policies:

- GROW: only when free capacity has sat above the watermark for the hold
  period with an empty admission queue (surplus that nobody queued for),
  one slice at a time, bounded by ``spec.elastic.maxSlices``, and gated
  by the scale-efficiency guard: a job whose observed tokens/sec-per-
  worker regressed past the floor after a previous grow is not grown
  again (blocked ``scale-efficiency``; a grown job that has not yet
  reported throughput blocks on ``awaiting-throughput``).
- SHRINK: checkpoint-coordinated. Queue pressure (waiting gangs) PROPOSES
  a one-slice shrink of the widest elastic job; the proposal is applied
  only after the heartbeat stream reports a FRESH checkpoint (step
  strictly past the one observed at proposal time — ``record_checkpoint``
  rider, mirrored by llama_train), so a scale-down can never lose more
  than one checkpoint interval. Pressure draining away withdraws the
  proposal.
- HYSTERESIS: minimum dwell between resizes of one job, cooldown after
  any observed disruption/restart-ledger growth (which is how chaos
  ``ScheduledCapacityRevocation`` churn is kept from flapping the fleet
  — every revocation preempts somebody, and the preempted job's ledger
  bump opens its cooldown window), and the surplus hold clock resets
  whenever free capacity dips under the watermark.

Resizes are applied through the EXISTING spec-resize path — the SDK's
validated whole-slice ``scale`` (numSlices + Worker replicas + mesh DCN
axis patched together, optimistic concurrency) — so the controller's
stale-world gang restart and the admission growth guard see an
autoscaler resize exactly as they see a human one. Exactly-once across
crashes falls out of idempotence: the decision is a function of the
CURRENT spec, so a crashed apply either never wrote (the next incarnation
re-decides the same resize) or wrote (the next incarnation observes the
target reached and decides nothing).
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field
from fractions import Fraction
from typing import Dict, List, Mapping, Optional, Tuple

from .policies import ratio_of

log = logging.getLogger(__name__)

_F0 = Fraction(0)


# --------------------------------------------------------------- state view


@dataclass(frozen=True)
class ElasticJobView:
    """Immutable per-job view handed to ``decide`` — everything a resize
    decision may legally depend on, nothing it could mutate."""

    key: str  # "<Kind>:<ns>/<name>" — the admission/workqueue identity
    kind: str
    namespace: str
    name: str
    num_slices: int
    hosts_per_slice: int
    min_slices: int
    max_slices: Optional[int]  # None = unbounded (capacity is the cap)
    admitted: bool
    suspended: bool
    # Freshest gang throughput from the heartbeat lease stream (max over
    # live in-range ranks — the _check_liveness aggregation rule); None =
    # no report yet.
    tokens_per_sec: Optional[float]
    # Gang-wide durable checkpoint step (min over reporting ranks — a
    # slice mid-save holds the shrink gate); None = the workload never
    # checkpointed (shrink stays blocked).
    checkpoint_step: Optional[int]
    # Per-generation normalized throughput (schedulingPolicy.
    # throughputRatios) — the gavel placement-quality signal.
    throughput_ratios: Mapping[str, float] = field(default_factory=dict)
    # The admission generation sub-pool currently hosting the gang.
    generation: Optional[str] = None
    # Sum of the job's restart/disruption/stall/sliceRestart ledgers,
    # read off the same list_jobs dict the view was built from (the
    # cooldown signal — decide itself never reads it; the controller's
    # memory update does, without a second per-job apiserver read).
    churn_total: int = 0

    @property
    def workers(self) -> int:
        return self.num_slices * self.hosts_per_slice


@dataclass(frozen=True)
class AutoscalerState:
    """One tick's immutable input. ``now`` is the controller's injected
    clock value at the tick — ``decide`` never reads time itself."""

    jobs: Tuple[ElasticJobView, ...]
    # Free schedulable pod slots in the admission pool (effective
    # capacity minus admitted usage); None = no bounded pool declared.
    free_pods: Optional[float]
    capacity_pods: Optional[float]
    # Waiting gangs at the admission gate (all bands).
    queue_depth: int
    # Per-generation free pod slots ({} = homogeneous pool).
    generations_free: Mapping[str, float]
    # Controller memory, snapshotted in (decide never mutates it):
    surplus_since: Optional[float]  # free > watermark continuously since
    cooldown_until: Mapping[str, float]  # job key -> cooldown expiry
    last_resize_at: Mapping[str, float]  # job key -> last applied resize
    # job key -> (target slices, checkpoint baseline at proposal time)
    pending_shrinks: Mapping[str, Tuple[int, Optional[int]]]
    # job key -> tokens/sec-per-worker observed at the last grow (the
    # scale-efficiency guard's baseline); absent = never grown.
    grow_baselines: Mapping[str, float]
    now: float = 0.0
    seed: int = 0


# ---------------------------------------------------------------- decisions


@dataclass(frozen=True)
class Resize:
    key: str
    kind: str
    namespace: str
    name: str
    from_slices: int
    to_slices: int
    direction: str  # "grow" | "shrink"
    reason: str
    # The checkpoint step that credited this shrink (None on grows).
    credited_checkpoint: Optional[int] = None


@dataclass(frozen=True)
class ShrinkProposal:
    key: str
    target_slices: int
    # job.checkpoint_step at proposal time; the apply gate requires a
    # step STRICTLY past this (or any step at all when None).
    baseline_checkpoint: Optional[int]


@dataclass
class Decisions:
    """One tick's ordered output: at most one resize to APPLY, new shrink
    proposals to record, withdrawn proposals, and blocked verdicts (the
    ``autoscaler_blocked_shrinks_total{cause}`` feed)."""

    actions: List[Resize] = field(default_factory=list)
    proposals: List[ShrinkProposal] = field(default_factory=list)
    withdrawals: List[str] = field(default_factory=list)
    blocked: List[Tuple[str, str]] = field(default_factory=list)


# ------------------------------------------------------------------- config


@dataclass
class AutoscalerConfig:
    """Hysteresis and watermark knobs (cli flags ``--autoscaler-*``)."""

    # Free capacity above this many pod slots is "surplus".
    watermark_pods: float = 2.0
    # Surplus must persist this long (queue empty throughout) to grow.
    hold_seconds: float = 15.0
    # Minimum time between two applied resizes of one job.
    dwell_seconds: float = 30.0
    # No resizes of a job within this window after an observed
    # disruption/restart-ledger bump (revocation churn guard).
    cooldown_seconds: float = 60.0
    # Scale-efficiency guard: after a grow, tokens/sec-per-worker must
    # stay at or above this fraction of the pre-grow baseline for the
    # job to be grown again.
    efficiency_floor: float = 0.7
    seed: int = 0
    # Checkpoint-free warm starts (EngineOptions.warm_start, the elastic-
    # grow contract): grows are attributed "warm-start" in the resize
    # ledger and decision log — the engine injects TPU_WARM_START=1 into
    # the recreated ranks, so the grow never waits on a storage
    # round-trip. With the flag ON decide() also paces grows faster
    # (warm_grow_pacing below); shrink-side gates are untouched. Default
    # OFF keeps every seeded ledger/decision-log byte-identical.
    warm_start: bool = False
    # Grow-side pacing relaxation under warm_start: dwell and cooldown
    # windows shrink to this fraction of their configured length for
    # GROW decisions only. The hysteresis knobs were sized for grows
    # that cost a storage restore; a warm grow costs a peer fill of the
    # survivors' deltas, so holding the full windows just leaves surplus
    # idle. Shrinks (the disruptive direction) keep the full windows.
    warm_grow_pacing: float = 0.5


#: The blocked-verdict vocabulary of the SHRINK path — the only causes
#: the autoscaler_blocked_shrinks_total metric may carry (grow-side
#: guard verdicts — awaiting-throughput, scale-efficiency — ride the
#: Decisions object only).
SHRINK_BLOCK_CAUSES = frozenset(
    {"no-fresh-checkpoint", "cooldown", "dwell", "at-min"}
)


# ------------------------------------------------------------ pure decision


# (Generation-ratio lookups reuse policies.ratio_of — ElasticJobView
# carries the same .throughput_ratios surface GangView does, so the
# admission policies and the autoscaler can never disagree about a
# job's throughput on a generation.)


def decide(state: AutoscalerState, config: AutoscalerConfig) -> Decisions:
    """The pure decision function: at most ONE resize per tick (hysteresis
    is per-job, pacing is global), shrink arbitration before grow — they
    cannot co-fire (shrink requires queue pressure, grow requires an empty
    queue), but the ordering keeps the procedure readable and the log
    stable."""
    decisions = Decisions()
    jobs = sorted(state.jobs, key=lambda j: j.key)
    eligible = [j for j in jobs if j.admitted and not j.suspended]
    pressure = state.queue_depth > 0
    now = state.now

    def in_cooldown(job: ElasticJobView) -> bool:
        return now < state.cooldown_until.get(job.key, 0.0)

    def in_dwell(job: ElasticJobView) -> bool:
        last = state.last_resize_at.get(job.key)
        return last is not None and (now - last) < config.dwell_seconds

    # Warm-start grow pacing: a warm grow costs a peer delta-fill, not a
    # storage restore, so GROW decisions honor only warm_grow_pacing of
    # each hysteresis window. cooldown_until was written as
    # (disruption time + cooldown_seconds); subtracting the forgiven
    # fraction recovers the shortened deadline without new state.
    def grow_in_cooldown(job: ElasticJobView) -> bool:
        until = state.cooldown_until.get(job.key, 0.0)
        if config.warm_start:
            until -= config.cooldown_seconds * (1.0 - config.warm_grow_pacing)
        return now < until

    def grow_in_dwell(job: ElasticJobView) -> bool:
        last = state.last_resize_at.get(job.key)
        if last is None:
            return False
        window = config.dwell_seconds
        if config.warm_start:
            window *= config.warm_grow_pacing
        return (now - last) < window

    # ---- shrink side: service pending proposals first -----------------
    # A proposal whose job left the eligible set (preempted/unadmitted,
    # suspended, or gone) is withdrawn, not parked: proposals are
    # single-flight fleet-wide, so a wedged one would block every OTHER
    # job's shrink — exactly the revocation scenario (the victim's own
    # stale proposal must not stop the survivor from shrinking to
    # re-fit it).
    eligible_keys = {j.key for j in eligible}
    for key in sorted(state.pending_shrinks):
        if key not in eligible_keys:
            decisions.withdrawals.append(key)
    acted = False
    for job in eligible:
        pending = state.pending_shrinks.get(job.key)
        if pending is None:
            continue
        target, baseline = pending
        if not pressure or job.num_slices != target + 1:
            # Pressure drained, or the spec moved under the proposal —
            # a user resize in EITHER direction, or a previous apply:
            # withdraw and re-propose against the current size. Applying
            # a stale proposal would cut more than one slice at once
            # (and silently revert a user's explicit grow).
            decisions.withdrawals.append(job.key)
            continue
        if in_cooldown(job):
            decisions.blocked.append((job.key, "cooldown"))
            continue
        if in_dwell(job):
            decisions.blocked.append((job.key, "dwell"))
            continue
        fresh = job.checkpoint_step is not None and (
            baseline is None or job.checkpoint_step > baseline
        )
        if not fresh:
            # The checkpoint-coordinated contract: no shrink is ever
            # APPLIED until the lease stream reports a checkpoint landing
            # past the proposal baseline.
            decisions.blocked.append((job.key, "no-fresh-checkpoint"))
            continue
        if not acted:
            decisions.actions.append(Resize(
                key=job.key, kind=job.kind, namespace=job.namespace,
                name=job.name, from_slices=job.num_slices,
                to_slices=max(target, job.min_slices), direction="shrink",
                reason="queue-pressure",
                credited_checkpoint=job.checkpoint_step,
            ))
            acted = True

    # ---- shrink side: propose (single-flight fleet-wide) --------------
    if pressure and not state.pending_shrinks and not acted:
        candidates = [
            j for j in eligible if j.num_slices > j.min_slices
        ]
        # Widest headroom first — the job holding the most optional
        # capacity gives it back first; ties break on key.
        candidates.sort(
            key=lambda j: (-(j.num_slices - j.min_slices), -j.num_slices,
                           j.key)
        )
        for job in candidates:
            if in_cooldown(job):
                decisions.blocked.append((job.key, "cooldown"))
                continue
            if in_dwell(job):
                decisions.blocked.append((job.key, "dwell"))
                continue
            decisions.proposals.append(ShrinkProposal(
                key=job.key, target_slices=job.num_slices - 1,
                baseline_checkpoint=job.checkpoint_step,
            ))
            break
        else:
            if not candidates:
                # Pressure with every elastic job at its floor: the
                # at-min verdict (visibility only; nothing to do).
                for job in eligible:
                    if job.num_slices <= job.min_slices:
                        decisions.blocked.append((job.key, "at-min"))

    if acted or pressure:
        return decisions

    # ---- grow side ----------------------------------------------------
    if state.free_pods is None:
        return decisions  # no bounded pool: nothing to watermark against
    surplus_held = (
        state.surplus_since is not None
        and (now - state.surplus_since) >= config.hold_seconds
    )
    if not surplus_held:
        return decisions
    candidates = []
    for job in eligible:
        if job.max_slices is not None and job.num_slices >= job.max_slices:
            continue
        delta = job.hosts_per_slice
        # The watermark buffer stays FREE through a grow: consuming it
        # would make the very next small arrival queue, and that queue
        # pressure would shrink the job just grown — the flap the
        # watermark exists to prevent.
        if delta <= 0 or delta > state.free_pods - config.watermark_pods:
            continue
        if grow_in_cooldown(job) or grow_in_dwell(job):
            continue
        baseline = state.grow_baselines.get(job.key)
        if baseline is not None:
            # Scale-efficiency guard: a previous grow happened. 0.0 is
            # the grew-before-first-report sentinel (the controller
            # upgrades it to a real per-worker baseline at the first
            # report) — either way, a grown job that has not reported
            # throughput yet may not grow AGAIN on faith.
            if job.tokens_per_sec is None:
                decisions.blocked.append((job.key, "awaiting-throughput"))
                continue
            per_worker = job.tokens_per_sec / max(job.workers, 1)
            if baseline > 0 and (
                per_worker < config.efficiency_floor * baseline
            ):
                decisions.blocked.append((job.key, "scale-efficiency"))
                continue
        candidates.append(job)
    if not candidates:
        return decisions
    if state.generations_free:
        # Placement-quality ordering (the admission_effective_throughput
        # signal, read at its source): prefer the job with the best
        # throughput ratio on the generation holding the most freed
        # capacity — growing a ratio-1.0 job into v6 headroom beats
        # growing a 0.25x one into it.
        freed_gen = max(
            sorted(state.generations_free),
            key=lambda g: state.generations_free[g],
        )
        candidates.sort(
            key=lambda j: (-ratio_of(j, freed_gen), j.num_slices, j.key)
        )
    else:
        # Smallest world first: surplus lifts the job furthest from its
        # ceiling, which also keeps a fleet of equals balanced.
        candidates.sort(key=lambda j: (j.num_slices, j.key))
    job = candidates[0]
    decisions.actions.append(Resize(
        key=job.key, kind=job.kind, namespace=job.namespace, name=job.name,
        from_slices=job.num_slices, to_slices=job.num_slices + 1,
        direction="grow",
        reason=(
            "placement-quality" if state.generations_free
            else "free-capacity"
        ),
    ))
    return decisions


# -------------------------------------------------------------- controller


class GangAutoscaler:
    """The opt-in controller loop (one per operator, like the
    AdmissionController): collects the signal state, runs the pure
    decision function, applies at most one resize per tick through the
    SDK's validated scale path, and keeps the hysteresis memory + audit
    ledgers. All state is in-memory by design: an operator restart
    re-observes everything, and the safe direction of every lost memory
    is DELAY (a fresh dwell clock, a re-proposed shrink) — never a
    double resize, because the decision is a function of the current
    spec."""

    def __init__(self, cluster, admission, config: Optional[AutoscalerConfig]
                 = None, clock=time.time, metrics=None,
                 kinds: Tuple[str, ...] = ("JAXJob",)):
        self.cluster = cluster
        self.admission = admission
        self.config = config or AutoscalerConfig()
        self.clock = clock
        self.kinds = tuple(kinds)
        if metrics is None:
            from ..metrics import METRICS

            metrics = METRICS
        self.metrics = metrics
        # One lock over tick() and the observability reads: the loop
        # thread mutates the hysteresis maps while /debugz snapshots
        # them from the HTTP thread — the AdmissionController rule.
        import threading

        self._lock = threading.Lock()
        self._tick_count = 0
        self._surplus_since: Optional[float] = None
        self._cooldown_until: Dict[str, float] = {}
        self._last_resize: Dict[str, float] = {}
        self._pending: Dict[str, Tuple[int, Optional[int]]] = {}
        self._grow_baseline: Dict[str, float] = {}
        self._last_churn: Dict[str, int] = {}
        # Audit ledgers (testing/invariants.py check_autoscaler_invariants):
        # one entry per APPLIED resize, carrying everything the invariants
        # need to audit bounds/dwell/cooldown/checkpoint from the ledger
        # alone. Bounded rings, the AdmissionController convention.
        self.resize_ledger: "deque[dict]" = deque(maxlen=512)
        # The determinism artifact: one entry per tick that took an
        # action/proposal/withdrawal, in applied order. Same-seed runs
        # over the same observation sequence are byte-equal
        # (decision_log_lines).
        self.decision_log: "deque[dict]" = deque(maxlen=4096)

    # ------------------------------------------------------- observation
    @staticmethod
    def _pods_of(resources: Optional[Mapping[str, str]]) -> Optional[float]:
        if resources is None:
            return None
        raw = resources.get("pods")
        if raw is None:
            return None
        try:
            from .job_controller import parse_quantity

            return float(parse_quantity(raw))
        except (ValueError, ZeroDivisionError):
            return None

    def _read_heartbeats(self, namespace: str, name: str,
                         workers: int) -> Tuple[Optional[float], Optional[int]]:
        """(gang tokens/sec, gang-wide durable checkpoint step) from the
        heartbeat lease stream — live, in-range ranks only (the
        _check_liveness pruning rule: a shrunk-away rank's lease may
        never inflate the gang number). Throughput aggregates as MAX
        (the _check_liveness rule: a global reporter yields the job
        number directly); the checkpoint step aggregates as MIN over the
        ranks that report one — with per-slice checkpoint dirs a slice
        mid-save must hold the shrink gate until ITS shard is durable,
        or the teardown loses it."""
        from ..cluster.base import NotFound
        from . import constants

        best_tps: Optional[float] = None
        best_ckpt: Optional[int] = None
        try:
            pods = self.cluster.list_pods(
                namespace,
                labels={
                    constants.LABEL_GROUP_NAME: constants.GROUP_NAME,
                    constants.LABEL_JOB_NAME: name,
                },
            )
        except Exception:  # noqa: BLE001 — observation must not kill the tick
            return None, None
        for pod in pods:
            if pod.metadata.deletion_timestamp is not None:
                continue
            try:
                index = int(
                    pod.metadata.labels.get(constants.LABEL_REPLICA_INDEX, -1)
                )
            except (TypeError, ValueError):
                continue
            if index < 0 or index >= workers:
                continue
            try:
                lease = self.cluster.get_lease(
                    namespace,
                    constants.heartbeat_lease_name(pod.metadata.name),
                )
            except NotFound:
                continue
            except Exception:  # noqa: BLE001
                continue
            annotations = (
                (lease.get("metadata") or {}).get("annotations") or {}
            )
            raw_tps = annotations.get(constants.ANNOTATION_HEARTBEAT_TPS)
            if raw_tps is not None:
                try:
                    tps = float(raw_tps)
                except (TypeError, ValueError):
                    tps = None
                if tps is not None and tps >= 0:
                    best_tps = max(best_tps or 0.0, tps)
            raw_ckpt = annotations.get(constants.ANNOTATION_HEARTBEAT_CKPT)
            if raw_ckpt is not None:
                try:
                    ckpt = int(float(raw_ckpt))
                except (TypeError, ValueError):
                    ckpt = None
                if ckpt is not None:
                    best_ckpt = (
                        ckpt if best_ckpt is None else min(best_ckpt, ckpt)
                    )
        return best_tps, best_ckpt

    def _job_views(self) -> List[ElasticJobView]:
        views: List[ElasticJobView] = []
        for kind in self.kinds:
            try:
                job_dicts = self.cluster.list_jobs(kind)
            except Exception:  # noqa: BLE001
                continue
            for job in job_dicts:
                spec = job.get("spec") or {}
                elastic = spec.get("elastic")
                if elastic is None:
                    continue
                meta = job.get("metadata") or {}
                namespace = meta.get("namespace", "default")
                name = meta.get("name", "")
                status = job.get("status") or {}
                conditions = status.get("conditions") or []
                terminal = any(
                    c.get("type") in ("Succeeded", "Failed")
                    and c.get("status") == "True"
                    for c in conditions
                )
                if terminal:
                    continue
                run_policy = spec.get("runPolicy") or {}
                suspended = bool(run_policy.get("suspend"))
                num_slices = int(spec.get("numSlices") or 1)
                workers = int((
                    (spec.get("jaxReplicaSpecs") or {}).get("Worker") or {}
                ).get("replicas") or 0)
                if workers <= 0 or workers % max(1, num_slices) != 0:
                    continue  # hosts-per-slice unknowable: never resize it
                hosts = workers // max(1, num_slices)
                key = f"{kind}:{namespace}/{name}"
                admitted = True
                generation = None
                if self.admission is not None:
                    admitted = self.admission.is_admitted(key)
                    if not admitted and getattr(
                        self.admission, "slice_granular", False
                    ):
                        # Slice-granular gate: the job is "admitted" for
                        # resize purposes when every current slice is.
                        admitted = all(
                            self.admission.is_admitted(f"{key}#slice-{s}")
                            for s in range(num_slices)
                        )
                sp = run_policy.get("schedulingPolicy") or {}
                ratios = dict(sp.get("throughputRatios") or {})
                churn = 0
                for ledger in ("restartCounts", "disruptionCounts",
                               "stallCounts", "sliceRestartCounts"):
                    for value in (status.get(ledger) or {}).values():
                        if isinstance(value, int):
                            churn += value
                tps, ckpt = self._read_heartbeats(namespace, name, workers)
                views.append(ElasticJobView(
                    key=key, kind=kind, namespace=namespace, name=name,
                    num_slices=num_slices, hosts_per_slice=hosts,
                    min_slices=int(elastic.get("minSlices") or 1),
                    max_slices=(
                        int(elastic["maxSlices"])
                        if elastic.get("maxSlices") is not None else None
                    ),
                    admitted=admitted, suspended=suspended,
                    tokens_per_sec=tps, checkpoint_step=ckpt,
                    throughput_ratios=ratios, generation=generation,
                    churn_total=churn,
                ))
        views.sort(key=lambda v: v.key)
        return views

    def collect_state(self) -> AutoscalerState:
        """Build one tick's immutable state AND advance the hysteresis
        memory (cooldown on ledger growth, the surplus hold clock)."""
        now = self.clock()
        views = self._job_views()
        free = capacity = None
        queue_depth = 0
        generations_free: Dict[str, float] = {}
        if self.admission is not None:
            snap = self.admission.snapshot()
            capacity = self._pods_of(snap.get("capacity"))
            used = self._pods_of(snap.get("usage")) or 0.0
            if capacity is not None:
                free = max(0.0, capacity - used)
            queue_depth = len(snap.get("waiting") or [])
            for gen, pools in (snap.get("generations") or {}).items():
                gen_cap = self._pods_of(pools.get("capacity"))
                gen_used = self._pods_of(pools.get("usage")) or 0.0
                if gen_cap is not None:
                    generations_free[gen] = max(0.0, gen_cap - gen_used)
            # Admission placement attribution for the gavel signal.
            by_key = {
                entry.get("key"): entry.get("generation")
                for entry in snap.get("admitted") or []
            }
            if any(by_key.values()):
                import dataclasses

                views = [
                    dataclasses.replace(v, generation=by_key.get(v.key))
                    for v in views
                ]
        # Cooldown memory: any ledger growth opens the window (the churn
        # totals ride the views — read off the same list_jobs pass, no
        # second per-job apiserver read).
        live_keys = set()
        for view in views:
            live_keys.add(view.key)
            total = view.churn_total
            prev = self._last_churn.get(view.key)
            if prev is not None and total > prev:
                self._cooldown_until[view.key] = (
                    now + self.config.cooldown_seconds
                )
            self._last_churn[view.key] = total
            # Upgrade the grew-before-first-report sentinel: the job's
            # first throughput report after such a grow becomes its
            # baseline (conservative — the POST-grow number — so any
            # further regression still trips the guard).
            if (
                self._grow_baseline.get(view.key) == 0.0
                and view.tokens_per_sec
            ):
                self._grow_baseline[view.key] = (
                    view.tokens_per_sec / max(view.workers, 1)
                )
        # Prune memory of vanished jobs (terminal/deleted) so a fleet
        # with churn doesn't grow these maps forever.
        for stash in (self._cooldown_until, self._last_resize,
                      self._pending, self._grow_baseline, self._last_churn):
            for key in [k for k in stash if k not in live_keys]:
                stash.pop(key, None)
        # Surplus hold clock: resets the moment free dips under the
        # watermark or anyone queues — churn can't accumulate hold time.
        if (free is not None and free > self.config.watermark_pods
                and queue_depth == 0):
            if self._surplus_since is None:
                self._surplus_since = now
        else:
            self._surplus_since = None
        return AutoscalerState(
            jobs=tuple(views),
            free_pods=free,
            capacity_pods=capacity,
            queue_depth=queue_depth,
            generations_free=dict(generations_free),
            surplus_since=self._surplus_since,
            cooldown_until=dict(self._cooldown_until),
            last_resize_at=dict(self._last_resize),
            pending_shrinks=dict(self._pending),
            grow_baselines=dict(self._grow_baseline),
            now=now,
            seed=self.config.seed,
        )

    # ------------------------------------------------------------- apply
    def _apply(self, resize: Resize) -> bool:
        """One resize through the EXISTING validated spec-resize path
        (sdk scale: numSlices + Worker replicas + mesh DCN axis together,
        optimistic concurrency). False = the job moved under us (gone,
        no longer elastic, validation refused) — never an error; the
        next tick re-decides against fresh state. Unexpected exceptions
        (including injected crashes) propagate: the loop wrapper owns
        survival, and a crash-point test must see the crash."""
        from ..api.defaulting import ValidationError
        from ..cluster.base import Conflict, NotFound
        from ..sdk.client import JobClient

        client = JobClient(self.cluster, resize.kind)
        last: Optional[Exception] = None
        for _ in range(5):
            try:
                client._scale_once(
                    resize.name, resize.to_slices, resize.namespace
                )
                return True
            except Conflict as exc:
                last = exc
                continue
            except (NotFound, ValidationError, ValueError):
                return False
        log.warning("autoscaler resize of %s gave up on conflicts: %s",
                    resize.key, last)
        return False

    def tick(self) -> List[Resize]:
        """One control-loop round: observe → decide (pure) → apply →
        record. Returns the resizes actually applied. Serialized with
        the observability reads via the controller lock (one loop
        thread ticks; /debugz snapshots concurrently)."""
        with self._lock:
            return self._tick_locked()

    def _tick_locked(self) -> List[Resize]:
        started = time.perf_counter()
        self._tick_count += 1
        state = self.collect_state()
        decide_started = time.perf_counter()
        decisions = decide(state, self.config)
        # The pure planning cost alone (observe/apply excluded) — the
        # fleet simulator's per-tick hot-path column. Wall time by
        # design: the injected clock is virtual there.
        self.metrics.observe_autoscaler_decide(
            time.perf_counter() - decide_started)
        views = {j.key: j for j in state.jobs}
        applied: List[Resize] = []
        logged: List[list] = []
        for proposal in decisions.proposals:
            self._pending[proposal.key] = (
                proposal.target_slices, proposal.baseline_checkpoint
            )
            logged.append(["propose-shrink", proposal.key,
                           proposal.target_slices,
                           proposal.baseline_checkpoint])
        for key in decisions.withdrawals:
            if self._pending.pop(key, None) is not None:
                logged.append(["withdraw-shrink", key])
        for resize in decisions.actions:
            if not self._apply(resize):
                continue
            applied.append(resize)
            warm = (self.config.warm_start and resize.direction == "grow")
            entry = [
                resize.direction, resize.key, resize.from_slices,
                resize.to_slices, resize.reason,
            ]
            if warm:
                # Attribution rides as an extra column ONLY when the
                # feature is on — seeded logs with it off stay
                # byte-identical to every prior PR.
                entry.append("warm-start")
            logged.append(entry)
            view = views.get(resize.key)
            ledger_entry = {
                "key": resize.key,
                "direction": resize.direction,
                "from": resize.from_slices,
                "to": resize.to_slices,
                "reason": resize.reason,
                "at": state.now,
                "credited_checkpoint": resize.credited_checkpoint,
                "min_slices": view.min_slices if view else None,
                "max_slices": view.max_slices if view else None,
                "cooldown_until": self._cooldown_until.get(resize.key, 0.0),
                "prev_resize_at": self._last_resize.get(resize.key),
                "dwell_seconds": self.config.dwell_seconds,
            }
            if warm:
                ledger_entry["warm_start"] = True
                # The hysteresis audit (testing/invariants.py) checks
                # each entry against the windows recorded IN it, so a
                # warm grow must record the paced windows it was
                # actually subject to — the raw config values would
                # flag every legitimately-early warm grow.
                pacing = self.config.warm_grow_pacing
                ledger_entry["dwell_seconds"] = (
                    self.config.dwell_seconds * pacing)
                ledger_entry["cooldown_until"] = (
                    ledger_entry["cooldown_until"]
                    - self.config.cooldown_seconds * (1.0 - pacing))
            self.resize_ledger.append(ledger_entry)
            self.metrics.autoscaler_resize_inc(
                resize.direction, resize.reason
            )
            self._last_resize[resize.key] = state.now
            if resize.direction == "shrink":
                self._pending.pop(resize.key, None)
            elif view is not None:
                # The scale-efficiency baseline: per-worker throughput
                # at the moment we grew past this world size. 0.0 when
                # the job has not reported yet — the guard then blocks
                # further grows on "awaiting-throughput" and
                # collect_state upgrades the sentinel at first report.
                self._grow_baseline[resize.key] = (
                    view.tokens_per_sec / max(view.workers, 1)
                    if view.tokens_per_sec else 0.0
                )
        for key, cause in decisions.blocked:
            # Only shrink-side verdicts feed the blocked-SHRINKS metric;
            # grow-side guard verdicts (awaiting-throughput,
            # scale-efficiency) stay in the decisions for tests and
            # callers but must not masquerade as shrink-coordination
            # problems on dashboards.
            if cause in SHRINK_BLOCK_CAUSES:
                self.metrics.autoscaler_blocked_shrink_inc(cause)
        if logged:
            self.decision_log.append({
                "tick": self._tick_count,
                "seed": self.config.seed,
                "actions": logged,
            })
        self.metrics.observe_autoscaler_decision_latency(
            time.perf_counter() - started
        )
        return applied

    # ----------------------------------------------------- observability
    def decision_log_lines(self) -> List[str]:
        """Canonical JSON lines — the byte-equality artifact (same seed +
        same observation sequence => identical lines across runs)."""
        import json

        with self._lock:
            entries = list(self.decision_log)
        return [
            json.dumps(entry, sort_keys=True, separators=(",", ":"))
            for entry in entries
        ]

    def snapshot(self) -> dict:
        """The /debugz autoscaler dump + the invariants' input."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        return {
            "config": {
                "watermark_pods": self.config.watermark_pods,
                "hold_seconds": self.config.hold_seconds,
                "dwell_seconds": self.config.dwell_seconds,
                "cooldown_seconds": self.config.cooldown_seconds,
                "efficiency_floor": self.config.efficiency_floor,
                "seed": self.config.seed,
            },
            "ticks": self._tick_count,
            "surplus_since": self._surplus_since,
            "cooldown_until": dict(self._cooldown_until),
            "last_resize_at": dict(self._last_resize),
            "pending_shrinks": {
                k: list(v) for k, v in self._pending.items()
            },
            "grow_baselines": dict(self._grow_baseline),
            "resize_ledger": [dict(e) for e in self.resize_ledger],
        }
