"""Job-lifecycle tracing: per-job span timelines for the operator.

The reference's only per-sync observability is a log line ("Finished
syncing tfjob %q (%v)", controller.go:306). Histograms and counters say
how MUCH the operator did; this module answers "what did the operator do
to job X, in what order, and how many apiserver calls did it cost" — the
causally-ordered control-action timeline TF-Replicator (arXiv:1902.00465)
argues is the debugging primitive for rendezvous-heavy systems.

Design rules (docs/design/tracing.md):

- One trace per JOB INCARNATION, keyed (kind, namespace, name, uid): a
  deleted-and-recreated job starts a fresh trace, exactly like the
  UID-keyed terminal-metrics dedup.
- Spans are recorded into a bounded per-trace ring buffer and the trace
  map itself is a bounded LRU — a long-lived operator with job churn
  holds a fixed memory ceiling, like every other per-job cache here.
- DETERMINISTIC IDs: trace ids are a per-tracer creation counter, span
  ids a per-trace counter — no wall clock, no randomness. The seeded
  chaos/crash/failover tiers replay byte-identical fault logs with
  tracing on, and the span SEQUENCE (names/parents/non-timing attrs)
  replays identically too (`span_sequence`). Wall-clock timestamps exist
  only as start/end fields, excluded from determinism comparisons.
- Tracing NEVER touches the cluster: no writes, no reads, no sleeps —
  it cannot perturb a chaos schedule keyed on (method, call index).
- Thread model: the active span stack is thread-local (the workqueue
  serializes each job onto one worker). Parallel fan-out propagates the
  parent context onto pool threads explicitly (`call_in_context`), so
  per-job request attribution survives concurrent writes.

Request accounting (cluster/accounting.py) feeds `record_request`: every
apiserver call made while a job's span is active is attributed to that
job's trace, and write calls additionally become `api.<verb>` child
spans — which is what makes span-order invariants like "the counted
status write precedes the gang teardown's deletions" checkable from the
trace alone (testing/invariants.py check_span_invariants).
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

WRITE_VERBS = frozenset({"create", "update", "patch", "delete"})


class Span:
    """One timed operation inside a trace. `span_id` is the per-trace
    deterministic sequence number (also the causal order key: ids are
    assigned in call order, so `a.span_id < b.span_id` means a was
    recorded before b)."""

    __slots__ = ("span_id", "parent_id", "name", "attrs", "events",
                 "start", "end")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 attrs: Optional[dict], start: float):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.events: List[tuple] = []
        self.start = start
        self.end: Optional[float] = None

    def set(self, **attrs) -> None:
        """Copy-on-write: the attrs mapping is REPLACED, never mutated —
        an exporter on another thread (a /tracez scrape mid-sync) reads
        the reference it snapshotted without 'dict changed size during
        iteration' ever being possible."""
        self.attrs = {**self.attrs, **attrs}

    def to_dict(self) -> dict:
        return {
            "id": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "attrs": dict(self.attrs),
            "events": [
                {"name": n, "attrs": dict(a)} for n, a in self.events
            ],
        }


class _NullSpan:
    """No-op stand-in when tracing is disabled or no trace is active."""

    span_id = None
    parent_id = None
    attrs: dict = {}

    def set(self, **attrs) -> None:
        pass


NULL_SPAN = _NullSpan()


class _Trace:
    __slots__ = ("trace_id", "kind", "namespace", "name", "uid", "spans",
                 "span_seq", "requests", "writes", "created_seq")

    def __init__(self, trace_id: str, kind: str, namespace: str, name: str,
                 uid: str, max_spans: int, created_seq: int):
        self.trace_id = trace_id
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.uid = uid
        self.spans: deque = deque(maxlen=max_spans)
        self.span_seq = 0
        # (verb, resource, code) -> count; bounded by the method table.
        self.requests: Dict[Tuple[str, str, str], int] = {}
        self.writes = 0
        self.created_seq = created_seq


class Tracer:
    """Dependency-free in-process tracer. A process-wide default lives at
    module level (`TRACER`, the METRICS idiom); harnesses and benchmarks
    construct their own for isolation."""

    def __init__(self, max_traces: int = 256, max_spans: int = 512,
                 clock=time.time, enabled: bool = True):
        self.enabled = enabled
        self.max_traces = max_traces
        self.max_spans = max_spans
        self.clock = clock
        self._lock = threading.Lock()
        # (kind, namespace, name, uid) -> _Trace, in creation order; LRU
        # eviction drops the OLDEST trace when the map is full.
        self._traces: "OrderedDict[tuple, _Trace]" = OrderedDict()
        self._trace_seq = 0
        self._tls = threading.local()

    # ------------------------------------------------------------ context
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current(self) -> Optional[tuple]:
        """The active (trace, span) context of THIS thread, or None —
        capture it before handing work to a pool thread and re-install
        there with `attach`/`call_in_context`."""
        stack = self._stack()
        return stack[-1] if stack else None

    @contextmanager
    def attach(self, ctx):
        """Install a captured (trace, span) context on this thread."""
        if ctx is None:
            yield
            return
        stack = self._stack()
        stack.append(ctx)
        try:
            yield
        finally:
            stack.pop()

    def call_in_context(self, ctx, fn, *args, **kwargs):
        with self.attach(ctx):
            return fn(*args, **kwargs)

    def current_log_context(self) -> dict:
        """{job, trace_id, span_id} of the active context (empty when
        none) — the structured-logging stamp (`--log-format json`)."""
        ctx = self.current()
        if ctx is None:
            return {}
        trace, span = ctx
        return {
            "job": f"{trace.namespace}/{trace.name}",
            "trace_id": trace.trace_id,
            "span_id": span.span_id,
        }

    # ------------------------------------------------------------- traces
    def _trace_for_locked(self, kind: str, namespace: str, name: str,
                          uid: str) -> _Trace:
        key = (kind, namespace, name, uid)
        trace = self._traces.get(key)
        if trace is not None:
            # True LRU, not FIFO: a hit refreshes recency, so the
            # busiest (oldest-created) job's live trace is never the one
            # evicted while idle newer traces survive. Recency order is
            # a pure function of the operation sequence — deterministic
            # under seeded replay.
            self._traces.move_to_end(key)
        else:
            self._trace_seq += 1
            trace = _Trace(
                f"trace-{self._trace_seq:06d}", kind, namespace, name, uid,
                self.max_spans, self._trace_seq,
            )
            self._traces[key] = trace
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
        return trace

    def _touch_locked(self, trace: _Trace) -> None:
        """Refresh (or restore) `trace`'s slot in the LRU map. Threads
        hold direct _Trace references on their context stacks for the
        whole sync, so a long sync racing heavy job churn can have its
        trace evicted mid-flight — without this, every later span and
        write attribution of that sync would land on a detached object
        and vanish from export()/writes_by_job(). Touch order is a pure
        function of the operation sequence — deterministic under replay."""
        key = (trace.kind, trace.namespace, trace.name, trace.uid)
        existing = self._traces.get(key)
        if existing is trace:
            self._traces.move_to_end(key)
            return
        # Evicted (or clobbered by a fresh same-key root after eviction):
        # the object the live sync is recording into wins the slot.
        self._traces[key] = trace
        self._traces.move_to_end(key)
        while len(self._traces) > self.max_traces:
            self._traces.popitem(last=False)

    # -------------------------------------------------------------- spans
    @contextmanager
    def span(self, name: str, job: Optional[tuple] = None,
             parent: Optional[int] = None, attrs: Optional[dict] = None):
        """Record one span. `job` = (kind, namespace, name, uid) roots the
        span in that job's trace; without it the span nests under the
        thread's current context (and is silently dropped when there is
        none — engine helpers called outside a sync never crash on
        tracing). `parent` overrides the parent span id (the
        workqueue-wait linkage)."""
        if not self.enabled:
            yield NULL_SPAN
            return
        stack = self._stack()
        if job is None and not stack:
            yield NULL_SPAN
            return
        # One critical section for lookup + touch + append: this lock is
        # the hottest in the process (every span AND every accounted
        # request), so no double round-trips.
        with self._lock:
            if job is not None:
                trace = self._trace_for_locked(*job)
            else:
                trace = stack[-1][0]
                self._touch_locked(trace)
            if parent is None and stack and stack[-1][0] is trace:
                parent = stack[-1][1].span_id
            trace.span_seq += 1
            span = Span(trace.span_seq, parent, name, attrs, self.clock())
            trace.spans.append(span)
        stack.append((trace, span))
        try:
            yield span
        except BaseException as exc:
            if "error" not in span.attrs:
                span.set(error=type(exc).__name__)
            raise
        finally:
            span.end = self.clock()
            stack.pop()

    def record_span(self, name: str, job: Optional[tuple] = None,
                    duration: float = 0.0,
                    attrs: Optional[dict] = None) -> Optional[int]:
        """Record an already-finished span (e.g. the measured workqueue
        wait, known only after the fact). Returns its span id so a
        follow-on span can parent to it."""
        if not self.enabled:
            return None
        ctx = None
        if job is None:
            ctx = self.current()
            if ctx is None:
                return None
        with self._lock:
            if job is not None:
                trace = self._trace_for_locked(*job)
            else:
                trace = ctx[0]
                self._touch_locked(trace)
            trace.span_seq += 1
            end = self.clock()
            span = Span(trace.span_seq, None, name, attrs,
                        end - max(0.0, duration))
            span.end = end
            trace.spans.append(span)
            return span.span_id

    def event(self, name: str, **attrs) -> None:
        """Append a point-in-time event to the active span (no-op without
        one) — cheaper than a span for things like fan-out waves."""
        ctx = self.current()
        if ctx is not None:
            ctx[1].events.append((name, attrs))

    # ----------------------------------------------------------- requests
    def record_request(self, verb: str, resource: str, code: str,
                       duration: float = 0.0) -> None:
        """One apiserver request completed under the active job context:
        counted into the trace's per-job attribution, and — for writes —
        recorded as an `api.<verb>` child span of the active span."""
        ctx = self.current()
        if ctx is None or not self.enabled:
            return
        trace, parent = ctx
        with self._lock:
            self._touch_locked(trace)
            key = (verb, resource, code)
            trace.requests[key] = trace.requests.get(key, 0) + 1
            if verb not in WRITE_VERBS:
                return
            trace.writes += 1
            trace.span_seq += 1
            end = self.clock()
            span = Span(
                trace.span_seq, parent.span_id, f"api.{verb}",
                {"resource": resource, "code": code}, end - max(0.0, duration),
            )
            span.end = end
            trace.spans.append(span)

    # ------------------------------------------------------------- export
    def export(self, namespace: Optional[str] = None,
               job: Optional[str] = None,
               limit: Optional[int] = None) -> List[dict]:
        """JSON-able snapshot of recent traces (newest last), filterable
        by namespace and job name — the /tracez payload. Only a SHALLOW
        snapshot (deque->list, request-table copy) happens under the
        tracer lock — the same lock every hot-path span()/record_request
        must take — so a /tracez scrape of max_traces full ring buffers
        never stalls controller workers for the full serialization.
        Building the dicts outside the lock is safe: spans are
        append-only, attrs are copy-on-write (Span.set replaces the
        mapping), and a mid-scrape live sync at worst contributes a span
        whose `end` is still None."""
        snapshot = []
        with self._lock:
            for trace in self._traces.values():
                if namespace and trace.namespace != namespace:
                    continue
                if job and trace.name != job:
                    continue
                snapshot.append((trace, list(trace.spans),
                                 dict(trace.requests), trace.writes))
        if limit is not None and limit >= 0:
            # Applied BEFORE serialization (newest-last is already the
            # map order), so ?limit=1 over a full map costs O(1) traces,
            # not a full export. -limit slicing alone would turn limit=0
            # into "everything".
            snapshot = snapshot[-limit:] if limit > 0 else []
        out = []
        for trace, spans, requests, writes in snapshot:
            out.append({
                "trace_id": trace.trace_id,
                "kind": trace.kind,
                "namespace": trace.namespace,
                "job": trace.name,
                "uid": trace.uid,
                "writes": writes,
                "requests": [
                    {"verb": v, "resource": r, "code": c, "count": n}
                    for (v, r, c), n in sorted(requests.items())
                ],
                "spans": [s.to_dict() for s in spans],
            })
        return out

    def export_json(self, **kwargs) -> str:
        return json.dumps({"traces": self.export(**kwargs)}, indent=2)

    def span_sequence(self, namespace: Optional[str] = None,
                      job: Optional[str] = None) -> List[tuple]:
        """The determinism artifact: every span's (trace_id, span_id,
        parent, name, attrs, events) with float-valued attrs dropped —
        floats are wall-clock-derived (durations, ages), everything else
        (causes, resources, codes, counts) is a pure function of the
        operation sequence. Two same-seed runs must compare equal."""
        def clean(attrs: dict) -> tuple:
            return tuple(sorted(
                (k, v) for k, v in attrs.items()
                if not isinstance(v, float)
            ))

        out = []
        for trace in self.export(namespace=namespace, job=job):
            for span in trace["spans"]:
                out.append((
                    trace["trace_id"], span["id"], span["parent"],
                    span["name"], clean(span["attrs"]),
                    tuple((e["name"], clean(e["attrs"]))
                          for e in span["events"]),
                ))
        return out

    # --------------------------------------------------------- accounting
    def writes_by_job(self) -> Dict[str, int]:
        """job 'kind/namespace/name' -> attributed apiserver writes
        (latest incarnation wins on a reused name; the kind is part of
        the key so a TFJob and a JAXJob sharing a name never collide)."""
        with self._lock:
            return {
                f"{t.kind}/{t.namespace}/{t.name}": t.writes
                for t in self._traces.values()
            }

    def total_writes(self) -> int:
        with self._lock:
            return sum(t.writes for t in self._traces.values())

    def total_writes_by_resource(self) -> Dict[str, int]:
        """Attributed write counts aggregated per resource — what lets the
        scale benchmark split writes-per-converged-job into its structural
        floor (pod/service creates) and the coalescible remainder
        (events, status updates/patches) the write-pressure gate bounds."""
        out: Dict[str, int] = {}
        with self._lock:
            for trace in self._traces.values():
                for (verb, resource, _code), n in trace.requests.items():
                    if verb in WRITE_VERBS:
                        out[resource] = out.get(resource, 0) + n
        return out


# Process-wide default, like metrics.METRICS. Tests and benchmarks that
# need isolation construct their own Tracer.
TRACER = Tracer()

# Shared disabled instance for components constructed without a tracer
# (the engine's default): every call is a cheap no-op.
NOOP_TRACER = Tracer(enabled=False)
