"""Pluggable admission policies: the pure-function seam behind
``AdmissionController`` (docs/design/gang_admission.md "Policy seam").

PR 9's arbiter was ONE hard-coded decision procedure (priority bands +
hard namespace quotas + bounded backfill) buried inside
``AdmissionController._pump_locked``. This module extracts it behind a
pure function::

    policy.decide(state: PolicyState) -> Decisions

where ``state`` is an immutable view of (queue, pool, usage, seed) and
``Decisions`` is an ORDERED action list (admit / backfill / preempt)
plus a blocked-verdict map for whoever stays waiting. Determinism
contract: ``decide`` reads NO wall clock and NO ambient state — for a
fixed ``PolicyState`` it returns the same ``Decisions``, byte for byte.
The controller applies the action list strictly in order (admit-log
entries, metrics, and requeue kicks land in list order), so a policy's
output order IS its observable schedule — which is what lets the
PR 9/11 seeded admission tiers replay byte-identically under the
default policy: :class:`PriorityPolicy` is the old ``_pump_locked``
decision procedure transplanted verbatim.

Three policies ship behind ``--admission-policy``:

- ``priority`` (default): the PR 9 arbiter — priority bands, hard
  namespace quotas, preempt-strictly-lower-band, bounded backfill with
  the aging starvation bound. Byte-identical to the pre-seam code.
- ``gavel``: heterogeneity-aware placement (Gavel, arXiv:2008.09213
  §3). The capacity pool is split into device GENERATIONS (``--capacity
  pods@v5lite=8,pods@v6=8``) and jobs declare per-generation normalized
  throughput (``schedulingPolicy.throughputRatios``). Placement
  greedily maximizes fleet-wide EFFECTIVE throughput
  (Σ ratio(assigned generation) × members): a gang lands on its
  best-ratio generation with room, falls back work-conservingly to the
  best available one, and preemption fires ONLY when evicting the
  chosen victims strictly raises the fleet-wide effective throughput
  (never on band alone — see the failure-modes note on
  preemption-cause attribution).
- ``drf``: weighted dominant-resource fairness across tenants
  (``--tenant-weight ns=w``), REPLACING the hard ``--namespace-quota``
  ceiling with a work-conserving share bound: the next admit always
  goes to the eligible tenant with the smallest weighted dominant
  share, and a lone tenant with demand takes the whole pool (no
  capacity is ever parked behind an absent tenant's reservation).

Every policy is exercised head-to-head by
``scripts/measure_control_plane.py --mode contention`` (the
policy-vs-policy table persisted to build/contention_policies_last.json)
and must pass ``check_admission_invariants`` (no partial gang, pool
never exceeded).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction
from typing import Dict, List, Mapping, Optional, Tuple

# Preemption causes (the single definition — core/admission.py
# re-exports them for its historical import home).
PREEMPT_CAUSE_PRIORITY = "PriorityPreemption"
PREEMPT_CAUSE_CAPACITY = "CapacityRevoked"
# Gavel's improvement-gated eviction: the victim was not outranked, it
# was out-THROUGHPUT — evicting it and placing the head strictly raised
# fleet-wide effective throughput.
PREEMPT_CAUSE_THROUGHPUT = "ThroughputPreemption"

_F0 = Fraction(0)


# --------------------------------------------------------------- state view


@dataclass(frozen=True)
class GangView:
    """Immutable per-gang view handed to policies. Mirrors the fields of
    the controller's ``_Gang`` a decision may legally depend on —
    policies never see (and can never mutate) controller bookkeeping."""

    key: str
    namespace: str
    band: int
    seq: int
    demand: Mapping[str, Fraction]
    members: int
    enqueued_at: float
    victim_rank: int = 0
    # Per-generation normalized throughput (schedulingPolicy.
    # throughputRatios); a generation absent from the map rides
    # DEFAULT_RATIO. Empty = the gang is generation-indifferent.
    throughput_ratios: Mapping[str, float] = field(default_factory=dict)
    # Set on ADMITTED gangs only: which generation sub-pool holds it.
    generation: Optional[str] = None


#: Throughput assumed for a generation a job declares no ratio for: 1.0
#: (full speed). Declaring ratios only for slow generations therefore
#: "just works", and ratio-less jobs are generation-indifferent.
DEFAULT_RATIO = 1.0


def ratio_of(gang: GangView, generation: Optional[str]) -> float:
    if generation is None:
        return DEFAULT_RATIO
    try:
        return float(gang.throughput_ratios.get(generation, DEFAULT_RATIO))
    except (TypeError, ValueError):
        return DEFAULT_RATIO


@dataclass(frozen=True)
class PolicyState:
    """One pump's immutable input: (queue, pool, usage, seed). ``now``
    is the controller's injected clock value AT the pump — a policy
    never reads time itself, so seeded fake-clock replays are exact."""

    # Waiting gangs in canonical queue order (band desc, seq asc) — the
    # ONE ordering the controller guarantees; policies that want another
    # (drf) re-sort deterministically.
    waiting: Tuple[GangView, ...]
    # Admitted gangs, seq order.
    admitted: Tuple[GangView, ...]
    # Keys already marked for preemption (engine ack pending). Their
    # capacity still counts as used until note_preempted.
    pending_preempt: frozenset
    # Effective flat pool (None = unlimited), per-resource Fractions.
    capacity: Optional[Mapping[str, Fraction]]
    # Device-generation sub-pools ({} = homogeneous pool, the PR 9
    # world). The flat pool already includes their element-wise sum.
    generations: Mapping[str, Mapping[str, Fraction]]
    quotas: Mapping[str, Mapping[str, Fraction]]
    # Weighted-DRF tenant weights (ns -> weight > 0); tenants absent
    # from the map ride weight 1.0.
    tenant_weights: Mapping[str, float]
    backfill_max_members: int
    aging_seconds: float
    now: float
    seed: int = 0
    # Precomputed exact usage of the admitted tuple — the admissibility
    # index's maintained Fraction vector, VALUE-identical to
    # usage_of(admitted) (Fraction arithmetic is exact, so incremental
    # maintenance cannot drift). None (the full-scan arbiter) means
    # policies compute their own scan; policies must never mutate this
    # mapping — they copy before charging.
    usage: Optional[Mapping[str, Fraction]] = None


# ---------------------------------------------------------------- decisions


@dataclass(frozen=True)
class Admit:
    key: str
    backfill: bool = False
    # The head-of-line's wait at a backfill admit (the starvation-audit
    # number recorded in the admit log); None for head admits.
    head_wait: Optional[float] = None
    generation: Optional[str] = None


@dataclass(frozen=True)
class Preempt:
    key: str
    cause: str = PREEMPT_CAUSE_PRIORITY


@dataclass
class Decisions:
    """Ordered decision list + blocked verdicts. ``actions`` is applied
    strictly in order by the controller (admits register capacity,
    preempts mark victims); ``blocked`` maps every still-waiting key to
    the verdict vocabulary the snapshot/conditions surface:
    capacity | quota | order | priority."""

    actions: List[object] = field(default_factory=list)
    blocked: Dict[str, str] = field(default_factory=dict)


# ------------------------------------------------------------ shared helpers


def fits(demand: Mapping[str, Fraction], usage: Mapping[str, Fraction],
         cap: Optional[Mapping[str, Fraction]]) -> bool:
    """Resources absent from the pool are unconstrained (a pool declared
    in chips does not bound cpu) — the PR 9 rule, unchanged."""
    if cap is None:
        return True
    return all(
        usage.get(name, _F0) + qty <= cap[name]
        for name, qty in demand.items()
        if name in cap
    )


def usage_of(gangs, exclude=frozenset()) -> Dict[str, Fraction]:
    usage: Dict[str, Fraction] = {}
    for gang in gangs:
        if gang.key in exclude:
            continue
        for name, qty in gang.demand.items():
            usage[name] = usage.get(name, _F0) + qty
    return usage


def starting_usage(state: "PolicyState", admitted_now) -> Dict[str, Fraction]:
    """The decide prologue's admitted-usage vector: the precomputed
    state.usage when the arbiter maintains one (a private copy — decide
    charges admits into it), else the O(admitted) scan."""
    if state.usage is not None:
        return dict(state.usage)
    return usage_of(admitted_now)


def ns_usage_of(gangs, namespace: str, exclude=frozenset()) -> Dict[str, Fraction]:
    usage: Dict[str, Fraction] = {}
    for gang in gangs:
        if gang.key in exclude or gang.namespace != namespace:
            continue
        for name, qty in gang.demand.items():
            usage[name] = usage.get(name, _F0) + qty
    return usage


def gen_usage_of(gangs, exclude=frozenset()) -> Dict[str, Dict[str, Fraction]]:
    """Per-generation usage from admitted gangs' placements."""
    out: Dict[str, Dict[str, Fraction]] = {}
    for gang in gangs:
        if gang.key in exclude or gang.generation is None:
            continue
        bucket = out.setdefault(gang.generation, {})
        for name, qty in gang.demand.items():
            bucket[name] = bucket.get(name, _F0) + qty
    return out


def quota_ok(state: PolicyState, gang: GangView, admitted_now,
             exclude=frozenset()) -> bool:
    quota = state.quotas.get(gang.namespace)
    if not quota:
        return True
    used = ns_usage_of(admitted_now, gang.namespace, exclude)
    return all(
        used.get(name, _F0) + qty <= quota[name]
        for name, qty in gang.demand.items()
        if name in quota
    )


def generation_candidates(state: PolicyState, gang: GangView,
                          admitted_now, exclude=frozenset()) -> List[str]:
    """Generations with room for the gang (every resource the generation
    declares bounds it), sorted by name — the deterministic first-fit
    order the chip-count-greedy default uses."""
    if not state.generations:
        return []
    gen_usage = gen_usage_of(admitted_now, exclude)
    return [
        name
        for name in sorted(state.generations)
        if fits(gang.demand, gen_usage.get(name, {}), state.generations[name])
    ]


def first_fit_generation(state: PolicyState, gang: GangView,
                         admitted_now, exclude=frozenset()) -> Optional[str]:
    candidates = generation_candidates(state, gang, admitted_now, exclude)
    return candidates[0] if candidates else None


def first_fit_in(state: PolicyState, gang: GangView,
                 gen_usage: Mapping[str, Mapping[str, Fraction]]
                 ) -> Optional[str]:
    """first_fit_generation against a PREBUILT per-generation usage map
    — the hot-path form (scan loops maintain the map incrementally;
    rebuilding it per waiter is the O(admitted × waiters) lock stall
    the incremental caches exist to avoid)."""
    for name in sorted(state.generations):
        if fits(gang.demand, gen_usage.get(name, {}),
                state.generations[name]):
            return name
    return None


def best_ratio(state: PolicyState, gang: GangView) -> float:
    """The gang's throughput on its best generation (1.0 when the pool
    is homogeneous) — the ETW denominator."""
    if not state.generations:
        return DEFAULT_RATIO
    return max(ratio_of(gang, g) for g in sorted(state.generations))


def _admissible(state: PolicyState, gang: GangView, usage, gen_usage):
    """(fits, generation) under the flat pool AND the generation
    sub-pools: with generations declared, a gang must land whole in ONE
    generation — the flat pool fitting while every sub-pool is
    fragmented is a wait, not an admit. ``gen_usage`` is the caller's
    incrementally-maintained per-generation usage map."""
    if not fits(gang.demand, usage, state.capacity):
        return False, None
    if not state.generations:
        return True, None
    gen = first_fit_in(state, gang, gen_usage)
    return (gen is not None), gen


# ------------------------------------------------------------------ policies


class AdmissionPolicy:
    """Base class: ``decide`` must be a pure function of ``state``."""

    name = "base"

    # Prune contract for the admissibility index (core/admission.py,
    # EngineOptions.admission_index). True declares: on a pool with NO
    # namespace quotas, a PolicyState whose waiting tuple keeps, for
    # every band that provably cannot fit its smallest waiter against
    # the free pool, only that band's FIRST gang (band desc, seq asc)
    # yields the SAME ordered action list as the full waiting set, and
    # every omitted gang's verdict is exactly "capacity". Sound for
    # scan policies whose head-of-line chain stops at the first blocked
    # waiter and whose non-head actions require a flat-pool fit. A
    # policy that cannot honor this (drf re-sorts the scan by dominant
    # share, so an omitted gang could BE the head) leaves it False and
    # the arbiter falls back to the full scan — counted via
    # admission_index_fallback_total, never silent.
    supports_waiting_prune = False

    def decide(self, state: PolicyState) -> Decisions:  # pragma: no cover
        raise NotImplementedError

    def _revocation_preempts(self, state: PolicyState, decisions: Decisions,
                             pending: set, order_key) -> None:
        """Shared capacity-revocation phase: the pool shrank under the
        admitted set — preempt gangs in ``order_key`` order until what
        remains fits. Pending victims still count as usage until the
        engine's counted teardown acknowledges them, so the check
        excludes only gangs already marked. (Byte-identical port of the
        PR 9 revocation phase when ``order_key`` is the priority
        policy's victim order.)"""
        cap = state.capacity
        if cap is None:
            return
        victims_pool = sorted(
            (g for g in state.admitted if g.key not in pending),
            key=order_key,
        )
        excluded = set(pending)
        for victim in victims_pool:
            # Read-only overcommit check: reuse the precomputed vector
            # when nothing is excluded (the common no-revocation pump);
            # any exclusion means a live revocation sweep — scan.
            usage = (
                state.usage
                if not excluded and state.usage is not None
                else usage_of(state.admitted, excluded)
            )
            if all(usage.get(r, _F0) <= cap[r] for r in cap):
                break
            decisions.actions.append(
                Preempt(victim.key, PREEMPT_CAUSE_CAPACITY))
            excluded.add(victim.key)
            pending.add(victim.key)
        # Generation sub-pool overcommit (only possible via operator-
        # restart adoption — live pods must be re-admitted wherever they
        # physically are — or a live generation-scoped shrink): preempt
        # gangs placed IN the oversubscribed generation, same order,
        # until its sub-pool fits. Runs only on generation-split pools,
        # so homogeneous replays are untouched.
        for gen_name in sorted(state.generations):
            bound = state.generations[gen_name]
            victims_pool = sorted(
                (g for g in state.admitted
                 if g.generation == gen_name and g.key not in pending),
                key=order_key,
            )
            for victim in victims_pool:
                gen_usage = gen_usage_of(
                    state.admitted, excluded).get(gen_name, {})
                if all(gen_usage.get(r, _F0) <= bound[r] for r in bound):
                    break
                decisions.actions.append(
                    Preempt(victim.key, PREEMPT_CAUSE_CAPACITY))
                excluded.add(victim.key)
                pending.add(victim.key)


class PriorityPolicy(AdmissionPolicy):
    """The PR 9 arbiter, re-expressed behind the seam — the decision
    procedure of the old ``AdmissionController._pump_locked`` verbatim
    (same orderings, same verdicts, same action order), so every seeded
    admission tier replays byte-identically with the seam in place.
    With generations declared (new territory — no seeded tier predates
    it), placement is chip-count-greedy first-fit in sorted generation
    order: the policy is deliberately throughput-BLIND, which is
    exactly the strawman the gavel gate measures against."""

    name = "priority"
    # Scan order is (band desc, seq asc) and stops acting at the first
    # blocked head; every later no-fit waiter gets verdict "capacity".
    # A band whose minimum demand exceeds the free pool therefore
    # contributes at most its first gang (as head or as the blocked
    # verdict the arbiter self-applies) — the prune is exact without
    # quotas (quota verdicts would need the pruned gangs scanned).
    supports_waiting_prune = True

    @staticmethod
    def _victim_order(g: GangView):
        return (g.band, -g.victim_rank, -g.seq)

    def decide(self, state: PolicyState) -> Decisions:
        decisions = Decisions()
        pending = set(state.pending_preempt)
        cap = state.capacity
        self._revocation_preempts(state, decisions, pending,
                                  self._victim_order)
        # Admission scan, priority order. Head-of-line = first waiter its
        # own quota allows; it admits as soon as it fits, schedules
        # preemption of strictly-lower bands when it doesn't, and bounds
        # backfill behind it by its age. While preemptions are PENDING,
        # backfill is suppressed (a victim slipping back into the gap its
        # own eviction opened is a preemption livelock).
        pending_preempt = bool(pending)
        head: Optional[GangView] = None
        head_wait = 0.0
        admitted_now: List[GangView] = list(state.admitted)
        usage = starting_usage(state, admitted_now)
        gen_usage: Dict[str, Dict[str, Fraction]] = (
            gen_usage_of(admitted_now) if state.generations else {}
        )
        ns_usage: Dict[str, Dict[str, Fraction]] = {}

        def ns_usage_view(namespace: str) -> Dict[str, Fraction]:
            if namespace not in ns_usage:
                ns_usage[namespace] = ns_usage_of(admitted_now, namespace)
            return ns_usage[namespace]

        def scan_quota_ok(gang: GangView) -> bool:
            quota = state.quotas.get(gang.namespace)
            if not quota:
                return True
            used = ns_usage_view(gang.namespace)
            return all(
                used.get(name, _F0) + qty <= quota[name]
                for name, qty in gang.demand.items()
                if name in quota
            )

        def charge(gang: GangView, generation: Optional[str]) -> None:
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, _F0) + qty
            used = ns_usage_view(gang.namespace)
            for name, qty in gang.demand.items():
                used[name] = used.get(name, _F0) + qty
            if generation is not None:
                bucket = gen_usage.setdefault(generation, {})
                for name, qty in gang.demand.items():
                    bucket[name] = bucket.get(name, _F0) + qty
            admitted_now.append(GangView(
                key=gang.key, namespace=gang.namespace, band=gang.band,
                seq=gang.seq, demand=gang.demand, members=gang.members,
                enqueued_at=gang.enqueued_at, victim_rank=gang.victim_rank,
                throughput_ratios=gang.throughput_ratios,
                generation=generation,
            ))

        for gang in state.waiting:
            if not scan_quota_ok(gang):
                decisions.blocked[gang.key] = "quota"
                continue
            is_head = head is None
            if is_head:
                head = gang
                head_wait = state.now - gang.enqueued_at
            ok, generation = _admissible(state, gang, usage, gen_usage)
            if ok:
                if is_head:
                    decisions.actions.append(
                        Admit(gang.key, generation=generation))
                    charge(gang, generation)
                    head = None  # the next eligible waiter takes the line
                elif (
                    not pending_preempt
                    and state.backfill_max_members > 0
                    and gang.members <= state.backfill_max_members
                    and head_wait < state.aging_seconds
                ):
                    decisions.actions.append(Admit(
                        gang.key, backfill=True, head_wait=head_wait,
                        generation=generation,
                    ))
                    charge(gang, generation)
                else:
                    decisions.blocked[gang.key] = "order"
                continue
            if is_head:
                # Priority preemption: strictly lower bands only — equal-
                # band contention waits its turn (FIFO within a band is
                # the fairness contract). Check-before-marking, INCLUDING
                # the already-pending set: the pending evictions alone may
                # already satisfy the head.
                candidates = sorted(
                    (g for g in admitted_now
                     if g.band < gang.band and g.key not in pending),
                    key=self._victim_order,
                )
                freed: set = set(pending)
                chosen: List[GangView] = []

                def satisfied() -> bool:
                    flat = fits(
                        gang.demand, usage_of(admitted_now, freed), cap
                    ) and quota_ok(state, gang, admitted_now, freed)
                    if not flat or not state.generations:
                        return flat
                    return first_fit_generation(
                        state, gang, admitted_now, freed) is not None

                satisfiable = satisfied()
                if not satisfiable:
                    for candidate in candidates:
                        chosen.append(candidate)
                        freed.add(candidate.key)
                        if satisfied():
                            satisfiable = True
                            break
                if satisfiable:
                    for victim in chosen:
                        decisions.actions.append(
                            Preempt(victim.key, PREEMPT_CAUSE_PRIORITY))
                        pending.add(victim.key)
                    pending_preempt = True
                    decisions.blocked[gang.key] = "priority"
                else:
                    decisions.blocked[gang.key] = "capacity"
            else:
                decisions.blocked[gang.key] = "capacity"
        return decisions


class GavelPolicy(AdmissionPolicy):
    """Heterogeneity-aware placement (Gavel §3, greedy form): maximize
    fleet-wide effective throughput Σ ratio(assigned generation) ×
    members. Wait order stays (band desc, seq asc) — Gavel arbitrates
    WHERE a gang runs, the band ladder still says WHO asks first.

    Per head-of-line, in order of preference:

    1. admit on the best-RATIO generation with room (ties break by
       generation name — deterministic, and a tie means the gang is
       indifferent);
    2. preempt-to-improve: evict the cheapest victims (lowest current
       contribution, band ≤ the head's) from the head's best generation
       IFF the swap STRICTLY raises fleet-wide effective throughput —
       head.ratio(g*)×members > Σ victims' current contribution AND
       beats admitting on the best available generation outright. The
       victims re-queue at the TAIL of their bands (head re-queue would
       let an equal-band victim overtake the head it was evicted for
       and re-take the vacated generation — endless churn) and
       typically re-place on whatever the head left behind (the classic
       Gavel swap), cause ``ThroughputPreemption``;
    3. otherwise admit work-conservingly on the best AVAILABLE
       generation (a 0.25x slot beats an idle slot — utilization is
       half the objective);
    4. nothing available and no improving swap → wait ("capacity").

    Bounded backfill and the aging starvation bound carry over
    unchanged; hard namespace quotas still apply when declared.
    Capacity revocation evicts lowest-contribution gangs first (the
    throughput-greedy mirror of the priority policy's
    lowest-band-first)."""

    name = "gavel"
    # Same (band desc, seq asc) scan and head chain as priority, and
    # ``fits_somewhere`` REQUIRES a flat-pool fit (a gang that cannot
    # fit the flat pool can never be admitted on any generation, and
    # only the head gets swap/priority treatment) — so the band
    # watermark prune is exact here too, with the same no-quota caveat.
    supports_waiting_prune = True

    @staticmethod
    def _contribution(g: GangView) -> float:
        return ratio_of(g, g.generation) * max(g.members, 1)

    def _revocation_order(self, g: GangView):
        return (self._contribution(g), g.band, -g.victim_rank, -g.seq)

    def _best_generations(self, state: PolicyState, gang: GangView):
        """Every generation ranked by the gang's preference: ratio
        desc, then name asc — fully deterministic."""
        return sorted(
            state.generations,
            key=lambda name: (-ratio_of(gang, name), name),
        )

    def decide(self, state: PolicyState) -> Decisions:
        decisions = Decisions()
        pending = set(state.pending_preempt)
        cap = state.capacity
        self._revocation_preempts(state, decisions, pending,
                                  self._revocation_order)
        pending_preempt = bool(pending)
        head: Optional[GangView] = None
        head_wait = 0.0
        admitted_now: List[GangView] = list(state.admitted)
        usage = starting_usage(state, admitted_now)

        # Incremental usage caches (the PriorityPolicy discipline — a
        # naive recompute per waiter makes every sync O(admitted x
        # waiters) inside the controller lock at fleet scale).
        gen_usage: Dict[str, Dict[str, Fraction]] = gen_usage_of(admitted_now)
        ns_usage: Dict[str, Dict[str, Fraction]] = {}

        def ns_usage_view(namespace: str) -> Dict[str, Fraction]:
            if namespace not in ns_usage:
                ns_usage[namespace] = ns_usage_of(admitted_now, namespace)
            return ns_usage[namespace]

        def scan_quota_ok(gang: GangView) -> bool:
            quota = state.quotas.get(gang.namespace)
            if not quota:
                return True
            used = ns_usage_view(gang.namespace)
            return all(
                used.get(name, _F0) + qty <= quota[name]
                for name, qty in gang.demand.items()
                if name in quota
            )

        def place_best(gang: GangView):
            """Best-ratio generation with room, or None."""
            for name in self._best_generations(state, gang):
                if fits(gang.demand, gen_usage.get(name, {}),
                        state.generations[name]):
                    return name
            return None

        def best_free_after_pending(gang: GangView,
                                    best_gen: str) -> bool:
            """Would the head fit its BEST generation once the pending
            teardowns ack? Pending victims' capacity is spoken for the
            line — the priority policy's pending-evictions-first rule,
            generation-aware."""
            return fits(
                gang.demand, usage_of(admitted_now, pending), cap
            ) and quota_ok(state, gang, admitted_now, pending) and fits(
                gang.demand,
                gen_usage_of(admitted_now, pending).get(best_gen, {}),
                state.generations[best_gen],
            )

        def charge(gang: GangView, generation: Optional[str]) -> None:
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, _F0) + qty
            used = ns_usage_view(gang.namespace)
            for name, qty in gang.demand.items():
                used[name] = used.get(name, _F0) + qty
            if generation is not None:
                bucket = gen_usage.setdefault(generation, {})
                for name, qty in gang.demand.items():
                    bucket[name] = bucket.get(name, _F0) + qty
            admitted_now.append(GangView(
                key=gang.key, namespace=gang.namespace, band=gang.band,
                seq=gang.seq, demand=gang.demand, members=gang.members,
                enqueued_at=gang.enqueued_at, victim_rank=gang.victim_rank,
                throughput_ratios=gang.throughput_ratios,
                generation=generation,
            ))

        for gang in state.waiting:
            if not scan_quota_ok(gang):
                decisions.blocked[gang.key] = "quota"
                continue
            is_head = head is None
            if is_head:
                head = gang
                head_wait = state.now - gang.enqueued_at
            flat_fits = fits(gang.demand, usage, cap)
            generation = place_best(gang) if state.generations else None
            fits_somewhere = flat_fits and (
                not state.generations or generation is not None)
            if is_head and state.generations:
                best_gen = self._best_generations(state, gang)[0]
                current_ratio = (
                    ratio_of(gang, generation) if fits_somewhere else -1.0
                )
                if (
                    ratio_of(gang, best_gen) > current_ratio
                    and pending
                    and best_free_after_pending(gang, best_gen)
                ):
                    # A pump landing between a swap's preempt-mark and
                    # its teardown ack must keep the head WAITING for
                    # the generation being freed — admitting it onto an
                    # inferior generation here would waste the eviction
                    # it (or an earlier head) just ordered.
                    decisions.blocked[gang.key] = "priority"
                    continue
            if fits_somewhere and is_head and state.generations:
                # Preempt-to-improve beats a worse-generation admit only
                # when the strict-gain condition holds; checked below.
                if ratio_of(gang, generation) < ratio_of(gang, best_gen):
                    swap = self._improving_swap(
                        state, gang, best_gen, admitted_now, pending,
                        beat=ratio_of(gang, generation) * max(gang.members, 1),
                    )
                    if swap:
                        # The head stays at the line while its victims
                        # tear down (pending_preempt suppresses backfill
                        # into the gap being freed for it).
                        for victim in swap:
                            decisions.actions.append(
                                Preempt(victim.key,
                                        PREEMPT_CAUSE_THROUGHPUT))
                            pending.add(victim.key)
                        pending_preempt = True
                        decisions.blocked[gang.key] = "priority"
                        continue
            if fits_somewhere:
                if is_head:
                    decisions.actions.append(
                        Admit(gang.key, generation=generation))
                    charge(gang, generation)
                    head = None
                elif (
                    not pending_preempt
                    and state.backfill_max_members > 0
                    and gang.members <= state.backfill_max_members
                    and head_wait < state.aging_seconds
                ):
                    decisions.actions.append(Admit(
                        gang.key, backfill=True, head_wait=head_wait,
                        generation=generation,
                    ))
                    charge(gang, generation)
                else:
                    decisions.blocked[gang.key] = "order"
                continue
            if is_head:
                if state.generations:
                    best_gen = self._best_generations(state, gang)[0]
                    swap = self._improving_swap(
                        state, gang, best_gen, admitted_now, pending,
                        beat=0.0,
                    )
                    if swap:
                        for victim in swap:
                            decisions.actions.append(
                                Preempt(victim.key,
                                        PREEMPT_CAUSE_THROUGHPUT))
                            pending.add(victim.key)
                        pending_preempt = True
                        decisions.blocked[gang.key] = "priority"
                        continue
                decisions.blocked[gang.key] = "capacity"
            else:
                decisions.blocked[gang.key] = "capacity"
        return decisions

    def _improving_swap(self, state: PolicyState, gang: GangView,
                        generation: str, admitted_now, pending,
                        beat: float) -> Optional[List[GangView]]:
        """Victims in ``generation`` (band ≤ the head's, cheapest
        contribution first) whose eviction makes room for the head AND
        satisfies the STRICT Gavel gain condition:
        head.ratio(g)×members − Σ victim contribution > ``beat`` (the
        value of the head's next-best alternative; 0.0 when it has
        none). Returns None when no improving set exists."""
        gain_cap = ratio_of(gang, generation) * max(gang.members, 1)
        if gain_cap <= beat:
            return None

        def head_fits(freed: set) -> bool:
            if not fits(
                gang.demand, usage_of(admitted_now, freed), state.capacity
            ) or not quota_ok(state, gang, admitted_now, freed):
                return False
            gen_usage = gen_usage_of(admitted_now, freed)
            return fits(gang.demand, gen_usage.get(generation, {}),
                        state.generations[generation])

        candidates = sorted(
            (g for g in admitted_now
             if g.generation == generation and g.key not in pending
             and g.band <= gang.band),
            key=lambda g: (self._contribution(g), -g.seq),
        )
        chosen: List[GangView] = []
        freed: set = set(pending)
        for candidate in candidates:
            chosen.append(candidate)
            freed.add(candidate.key)
            if head_fits(freed):
                break
        else:
            return None
        # Prune gratuitous victims: the cheapest-contribution-first
        # greedy can collect small gangs whose room a later, bigger
        # victim made unnecessary — every survivor of this pass is
        # load-bearing (dropping it un-fits the head). The strict-gain
        # check runs on the PRUNED loss, so a big-victim-only swap is
        # not rejected for the prefix's dead weight.
        for candidate in list(chosen):
            trial = freed - {candidate.key}
            if head_fits(trial):
                chosen.remove(candidate)
                freed = trial
        lost = sum(self._contribution(c) for c in chosen)
        if gain_cap - lost <= beat:
            return None
        return chosen


class DrfPolicy(AdmissionPolicy):
    """Weighted dominant-resource fairness (DRF) across tenants. The
    next admit always goes to the eligible gang of the tenant with the
    SMALLEST weighted dominant share (max over pool resources of
    usage/capacity, divided by the tenant's ``--tenant-weight``; absent
    tenants ride weight 1.0); ties break (band desc, seq asc) — the
    fairness ordering REPLACES hard quota ceilings, so the share bound
    is work-conserving: a tenant alone with demand takes the whole
    pool, and under contention admitted shares track declared weights
    (the ``--mode contention`` drf gate bounds the spread at ≤1.5× the
    weight ratio). Declared ``--namespace-quota``s, if any, still cap a
    tenant hard (belt over suspenders; drf normally runs without).
    Backfill/aging carry over against the DRF head-of-line. Capacity
    revocation evicts from the LARGEST weighted-share tenant first —
    fairness decides who gives back. Generation placement is first-fit
    (drf arbitrates shares, not heterogeneity)."""

    name = "drf"
    # drf re-sorts the waiting set by weighted dominant share each
    # round, so an omitted band-tail gang could be the share-ordered
    # HEAD (is_head drives head_wait/backfill verdicts) — pruning would
    # change bytes. Declared here so the admissibility index falls back
    # to the full scan for decide; the capacity-epoch no-op
    # short-circuit (policy-agnostic) still applies.
    supports_waiting_prune = False

    def _weight(self, state: PolicyState, namespace: str) -> float:
        try:
            w = float(state.tenant_weights.get(namespace, 1.0))
        except (TypeError, ValueError):
            w = 1.0
        return w if w > 0 else 1.0

    def _dominant_share(self, state: PolicyState, namespace: str,
                        admitted_now, exclude=frozenset()) -> float:
        cap = state.capacity
        if not cap:
            return 0.0
        used = ns_usage_of(admitted_now, namespace, exclude)
        share = 0.0
        for resource, bound in cap.items():
            if bound <= 0:
                continue
            share = max(share, float(used.get(resource, _F0) / bound))
        return share

    def decide(self, state: PolicyState) -> Decisions:
        decisions = Decisions()
        pending = set(state.pending_preempt)

        def revocation_order(g: GangView):
            return (
                -self._dominant_share(state, g.namespace, state.admitted)
                / self._weight(state, g.namespace),
                g.band, -g.victim_rank, -g.seq,
            )

        self._revocation_preempts(state, decisions, pending,
                                  revocation_order)
        pending_preempt = bool(pending)
        admitted_now: List[GangView] = list(state.admitted)
        usage = starting_usage(state, admitted_now)
        remaining: List[GangView] = list(state.waiting)
        head_wait: Optional[float] = None
        backfilling = False
        # Incremental per-tenant usage (shares are recomputed on every
        # re-sort — a full admitted-set scan per waiter per pass is the
        # O(admitted x waiters) lock stall PriorityPolicy's caches
        # exist to avoid).
        ns_usage: Dict[str, Dict[str, Fraction]] = {}
        for g in admitted_now:
            bucket = ns_usage.setdefault(g.namespace, {})
            for name, qty in g.demand.items():
                bucket[name] = bucket.get(name, _F0) + qty
        gen_usage: Dict[str, Dict[str, Fraction]] = (
            gen_usage_of(admitted_now) if state.generations else {}
        )

        def dominant_share(namespace: str) -> float:
            if not state.capacity:
                return 0.0
            used = ns_usage.get(namespace, {})
            share = 0.0
            for resource, bound in state.capacity.items():
                if bound <= 0:
                    continue
                share = max(share, float(used.get(resource, _F0) / bound))
            return share

        def charge(gang: GangView, generation: Optional[str]) -> None:
            for name, qty in gang.demand.items():
                usage[name] = usage.get(name, _F0) + qty
            bucket = ns_usage.setdefault(gang.namespace, {})
            for name, qty in gang.demand.items():
                bucket[name] = bucket.get(name, _F0) + qty
            if generation is not None:
                gen_bucket = gen_usage.setdefault(generation, {})
                for name, qty in gang.demand.items():
                    gen_bucket[name] = gen_bucket.get(name, _F0) + qty
            admitted_now.append(GangView(
                key=gang.key, namespace=gang.namespace, band=gang.band,
                seq=gang.seq, demand=gang.demand, members=gang.members,
                enqueued_at=gang.enqueued_at, victim_rank=gang.victim_rank,
                throughput_ratios=gang.throughput_ratios,
                generation=generation,
            ))

        def drf_order(gang: GangView):
            return (
                dominant_share(gang.namespace)
                / self._weight(state, gang.namespace),
                -gang.band, gang.seq,
            )

        # Repeated-selection loop: shares move with every admit, so the
        # "most underserved tenant" is recomputed after each one —
        # that recomputation IS the fairness mechanism. Terminates
        # because every pass either shrinks `remaining` (admit or
        # quota-block, both `break` to re-sort) or completes break-free
        # (nothing actionable) and exits via the for/else.
        while remaining:
            order = sorted(remaining, key=drf_order)
            for position, gang in enumerate(order):
                if not quota_ok(state, gang, admitted_now):
                    decisions.blocked[gang.key] = "quota"
                    remaining.remove(gang)
                    break
                is_head = position == 0 and not backfilling
                if is_head and head_wait is None:
                    head_wait = state.now - gang.enqueued_at
                ok, generation = _admissible(
                    state, gang, usage, gen_usage)
                if ok and (
                    is_head
                    or (
                        not pending_preempt
                        and state.backfill_max_members > 0
                        and gang.members <= state.backfill_max_members
                        and (head_wait or 0.0) < state.aging_seconds
                    )
                ):
                    decisions.actions.append(Admit(
                        gang.key, backfill=not is_head,
                        head_wait=None if is_head else head_wait,
                        generation=generation,
                    ))
                    charge(gang, generation)
                    remaining.remove(gang)
                    if is_head:
                        head_wait = None
                    break
                if is_head:
                    # The DRF head doesn't fit: everything behind it may
                    # only BACKFILL from here on (same starvation rule
                    # as the priority policy).
                    backfilling = True
                    decisions.blocked[gang.key] = "capacity"
                else:
                    decisions.blocked[gang.key] = (
                        "order" if ok else "capacity")
            else:
                break
        # Whoever the inner loop never verdicted (it restarts on every
        # admit) keeps a capacity verdict.
        for gang in remaining:
            decisions.blocked.setdefault(
                gang.key, "order" if backfilling else "capacity")
        return decisions


POLICIES = {
    PriorityPolicy.name: PriorityPolicy,
    GavelPolicy.name: GavelPolicy,
    DrfPolicy.name: DrfPolicy,
}


def build_policy(name: str) -> AdmissionPolicy:
    """Policy registry lookup (--admission-policy). Raises ValueError on
    an unknown name — a typo'd policy silently falling back to the
    default would run the wrong scheduler for the fleet's whole life."""
    try:
        return POLICIES[str(name or "priority")]()
    except KeyError:
        raise ValueError(
            f"unknown admission policy {name!r} "
            f"(known: {', '.join(sorted(POLICIES))})"
        )
