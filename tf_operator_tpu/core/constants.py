"""Engine-wide constants: label keys, event reasons, condition reasons.

Reference parity: kubeflow/common label keys as used at
tfjob_controller.go:764-770 and pkg/controller.v1/tensorflow/controller.go:55-62.
"""

# Label keys stamped on every pod/service the operator creates.
GROUP_NAME = "kubeflow.org"
LABEL_GROUP_NAME = "group-name"
LABEL_JOB_NAME = "job-name"
LABEL_REPLICA_TYPE = "replica-type"
LABEL_REPLICA_INDEX = "replica-index"
LABEL_JOB_ROLE = "job-role"
JOB_ROLE_MASTER = "master"

# TPU-native labels/annotations (no reference counterpart): identify the
# slice a worker belongs to so schedulers and debuggers can reason per-slice.
LABEL_SLICE_INDEX = "tpu-slice-index"
# Hash of the world a pod's rendezvous env was computed from (worker count,
# slice count, coordinator port, mesh). A pod whose label differs from the
# current spec belongs to a stale world: SPMD membership changed, and the
# whole gang must re-init through the coordinator (elastic slice resize —
# SURVEY.md §2.5 elastic row, generalizing the reference's
# EnableDynamicWorker to all-or-nothing slices).
LABEL_WORLD_GENERATION = "world-generation"
ANNOTATION_TPU_TOPOLOGY = "tpu.kubeflow.org/topology"
ANNOTATION_TPU_ACCELERATOR = "tpu.kubeflow.org/accelerator-type"

# Gang scheduling (reference pod.go:220-237, tfjob_controller.go:798-815).
GANG_SCHEDULER_NAME_DEFAULT = "volcano"
ANNOTATION_GANG_GROUP_NAME = "scheduling.k8s.io/group-name"
ANNOTATION_GANG_TASK_SPEC = "volcano.sh/task-spec"

# Event reasons (reference pod.go:45-55, status.go:34-45).
REASON_SUCCESSFUL_CREATE_POD = "SuccessfulCreatePod"
REASON_FAILED_CREATE_POD = "FailedCreatePod"
REASON_SUCCESSFUL_DELETE_POD = "SuccessfulDeletePod"
REASON_FAILED_DELETE_POD = "FailedDeletePod"
REASON_SUCCESSFUL_CREATE_SERVICE = "SuccessfulCreateService"
REASON_SUCCESSFUL_DELETE_SERVICE = "SuccessfulDeleteService"
REASON_EXITED_WITH_CODE = "ExitedWithCode"
REASON_JOB_DEADLINE_EXCEEDED = "DeadlineExceeded"
REASON_JOB_BACKOFF_EXCEEDED = "BackoffLimitExceeded"

# Condition reasons; the reference builds "<Kind>Created" etc. per framework
# (e.g. tfJobCreatedReason). job_reason(kind, suffix) reproduces that.


def job_reason(kind: str, suffix: str) -> str:
    return f"{kind}{suffix}"


REASON_CREATED = "Created"
REASON_RUNNING = "Running"
REASON_RESTARTING = "Restarting"
REASON_SUCCEEDED = "Succeeded"
REASON_FAILED = "Failed"
REASON_SUSPENDED = "Suspended"
REASON_RESUMED = "Resumed"
REASON_QUEUED = "GangQueued"

# Exit code sentinel when the framework container has not terminated
# (reference tfjob_controller.go:707 "magic number").
EXIT_CODE_UNSET = 0xBEEF
