"""Engine-wide constants: label keys, event reasons, condition reasons.

Reference parity: kubeflow/common label keys as used at
tfjob_controller.go:764-770 and pkg/controller.v1/tensorflow/controller.go:55-62.
"""

# Label keys stamped on every pod/service the operator creates.
GROUP_NAME = "kubeflow.org"
LABEL_GROUP_NAME = "group-name"
LABEL_JOB_NAME = "job-name"
LABEL_REPLICA_TYPE = "replica-type"
LABEL_REPLICA_INDEX = "replica-index"
LABEL_JOB_ROLE = "job-role"
JOB_ROLE_MASTER = "master"

# TPU-native labels/annotations (no reference counterpart): identify the
# slice a worker belongs to so schedulers and debuggers can reason per-slice.
LABEL_SLICE_INDEX = "tpu-slice-index"
# Hash of the world a pod's rendezvous env was computed from (worker count,
# slice count, coordinator port, mesh). A pod whose label differs from the
# current spec belongs to a stale world: SPMD membership changed, and the
# whole gang must re-init through the coordinator (elastic slice resize —
# SURVEY.md §2.5 elastic row, generalizing the reference's
# EnableDynamicWorker to all-or-nothing slices).
LABEL_WORLD_GENERATION = "world-generation"
ANNOTATION_TPU_TOPOLOGY = "tpu.kubeflow.org/topology"
ANNOTATION_TPU_ACCELERATOR = "tpu.kubeflow.org/accelerator-type"

# Gang scheduling (reference pod.go:220-237, tfjob_controller.go:798-815).
GANG_SCHEDULER_NAME_DEFAULT = "volcano"
ANNOTATION_GANG_GROUP_NAME = "scheduling.k8s.io/group-name"
ANNOTATION_GANG_TASK_SPEC = "volcano.sh/task-spec"

# Event reasons (reference pod.go:45-55, status.go:34-45).
REASON_SUCCESSFUL_CREATE_POD = "SuccessfulCreatePod"
REASON_FAILED_CREATE_POD = "FailedCreatePod"
REASON_SUCCESSFUL_DELETE_POD = "SuccessfulDeletePod"
REASON_FAILED_DELETE_POD = "FailedDeletePod"
REASON_SUCCESSFUL_CREATE_SERVICE = "SuccessfulCreateService"
REASON_SUCCESSFUL_DELETE_SERVICE = "SuccessfulDeleteService"
REASON_EXITED_WITH_CODE = "ExitedWithCode"
REASON_JOB_DEADLINE_EXCEEDED = "DeadlineExceeded"
REASON_JOB_BACKOFF_EXCEEDED = "BackoffLimitExceeded"
# Disruption budget exhausted (RunPolicy.maxDisruptionRetries): distinct
# from BackoffLimitExceeded so dashboards can tell "crash-looped" from
# "preempted more times than the job allows".
REASON_JOB_DISRUPTION_EXCEEDED = "DisruptionBudgetExceeded"
# A 5-minute-stale expectation expired (core/expectations.py): the watch
# event the controller was waiting for never arrived. The job self-heals,
# but silently-self-healing wedges are exactly what chaos tiers must see.
REASON_EXPECTATION_TIMEOUT = "ExpectationTimeout"
# Stuck-terminating escalation (runPolicy.forceDeleteAfterSeconds): a pod
# lingered Terminating past deletionTimestamp + grace + the opt-in bound —
# dead kubelet on a reclaimed host — and the operator force-deleted it
# (grace-period-0) to unblock gang recovery. Always a Warning: a force
# delete abandons a node that may still be running the container.
REASON_FORCE_DELETE_POD = "ForceDeletePod"
# Cause label for the force-delete metric (the only cause today; the label
# exists so future escalation triggers stay distinguishable).
FORCE_DELETE_CAUSE_STUCK_TERMINATING = "StuckTerminating"

# Condition reasons; the reference builds "<Kind>Created" etc. per framework
# (e.g. tfJobCreatedReason). job_reason(kind, suffix) reproduces that.


def job_reason(kind: str, suffix: str) -> str:
    return f"{kind}{suffix}"


REASON_CREATED = "Created"
REASON_RUNNING = "Running"
REASON_RESTARTING = "Restarting"
# Restarting with cause InfrastructureDisruption: preemption/eviction/
# drain recovery. Same Restarting condition TYPE (the status machine's
# mutual-exclusion invariants apply unchanged); the reason carries the
# cause so conditions/events distinguish "recovering from preemption"
# from "retrying a crash".
REASON_DISRUPTION_RESTARTING = "DisruptionRestarting"
# Restarting with cause ProgressStall: every pod reported Running but a
# replica's heartbeat went stale past progressDeadlineSeconds (or the
# first heartbeat never arrived within rendezvousDeadlineSeconds). Same
# Restarting condition TYPE; the reason carries the liveness verdict so
# "wedged collective" is distinguishable from both crash and preemption.
REASON_STALL_RESTARTING = "ProgressStallRestarting"
REASON_SUCCEEDED = "Succeeded"
REASON_FAILED = "Failed"
REASON_SUSPENDED = "Suspended"
REASON_RESUMED = "Resumed"
REASON_QUEUED = "GangQueued"
# Gang admission (core/admission.py, --enable-gang-admission): the job's
# gang cleared capacity/quota/priority arbitration and its pods may now
# be born; and the counterpart Warning when a running gang is preempted
# by the admission layer (a higher-priority gang needed its capacity, or
# the pool shrank) — the restart lands in the budget-free
# disruptionCounts ledger and the job re-queues at the head of its band.
REASON_GANG_ADMITTED = "GangAdmitted"
REASON_GANG_PREEMPTED = "GangPreempted"
# Slice-scoped failure domains (docs/design/failure_modes.md §12): a
# multislice job's retryable failure restarts only the lost slice — the
# same Restarting condition TYPE, reason carrying the slice scope so a
# slice-local incident is distinguishable from a whole-world restart.
REASON_SLICE_RESTARTING = "SliceRestarting"
REASON_SLICE_DISRUPTION_RESTARTING = "SliceDisruptionRestarting"
REASON_SLICE_STALL_RESTARTING = "SliceProgressStallRestarting"
# Escalation out of the slice domain: losing the coordinator slice
# (slice 0 hosts the worker-0 jax.distributed coordinator every other
# slice re-rendezvouses through) or dropping below the spec.minSlices
# quorum within the restart window restarts the WHOLE world through the
# same counted protocol — exactly one ledger entry, labeled with this
# reason so dashboards can tell "a slice bounced" from "the world went".
REASON_SLICE_QUORUM_LOST = "SliceQuorumLost"

# Disruption restart backoff (jittered exponential, engine
# `_disruption_backoff_seconds`): the FIRST disruption restarts
# immediately (a preempted slice should re-queue for capacity at once);
# consecutive disruptions without reaching Running back off
# BASE * 2^(streak-2), capped — a reclaim loop must not hammer the
# scheduler with gang-sized pod churn every sync.
DISRUPTION_BACKOFF_BASE_SECONDS = 1.0
DISRUPTION_BACKOFF_MAX_SECONDS = 300.0

# Gang liveness (docs/design/failure_modes.md §8): each worker renews a
# per-pod heartbeat Lease named "<pod>-hb"; a lease annotation carries the
# training step the workload last reported via record_progress(). The
# controller measures staleness on ITS clock from the moment a renewal is
# observed — the leaderelection skew rule — never remote-vs-local time.
HEARTBEAT_LEASE_SUFFIX = "-hb"
ANNOTATION_HEARTBEAT_STEP = "tpu.kubeflow.org/progress-step"
# Workload-reported training throughput (record_progress(tokens_per_sec=)),
# riding the same lease annotations: the utilization signal the controller
# exports as training_workload_tokens_per_sec for autoscaling/dashboards.
ANNOTATION_HEARTBEAT_TPS = "tpu.kubeflow.org/tokens-per-sec"
# Last checkpoint the workload reported durable (record_checkpoint(step)),
# riding the same lease annotations: the coordination signal the autoscaler's
# checkpoint-gated shrink waits on — a shrink is applied only after a FRESH
# checkpoint lands (strictly newer than the one observed at proposal time),
# so an elastic scale-down can never lose more progress than one
# checkpoint interval.
ANNOTATION_HEARTBEAT_CKPT = "tpu.kubeflow.org/checkpoint-step"
# Peer-restore shard-server address (record_peer_address("host:port")),
# riding the same lease annotations: survivors advertise where a recreated
# slice can fetch host-resident snapshot shards instead of paying the
# storage round-trip (docs/design/checkpoint_recovery.md). The engine
# aggregates live survivors' addresses into TPU_PEER_RESTORE_ADDRS on
# recreated pods when EngineOptions.peer_restore is on.
ANNOTATION_HEARTBEAT_PEER = "tpu.kubeflow.org/peer-restore-addr"
# Last restore outcome (record_restore(path, cause, seconds)), riding the
# same lease annotations as a compact "path:cause:seconds" string — the
# observability tail of the restore ladder (which leg won and why),
# exported by the controller as training_restore_total/seconds.
ANNOTATION_HEARTBEAT_RESTORE = "tpu.kubeflow.org/restore-outcome"
# Renewal cadence injected into heartbeat-enabled pods: a quarter of the
# progress deadline, floored — several renewals must fit inside one
# deadline window or scheduling jitter alone could trip it.
HEARTBEAT_INTERVAL_FRACTION = 4


def heartbeat_lease_name(pod_name: str) -> str:
    return f"{pod_name}{HEARTBEAT_LEASE_SUFFIX}"


# Exit code sentinel when the framework container has not terminated
# (reference tfjob_controller.go:707 "magic number").
EXIT_CODE_UNSET = 0xBEEF
