"""Controller expectations cache.

Guards against acting on a stale object cache: after issuing N creates the
reconciler "expects" to observe N create events before trusting its listing
again. Without this, an informer-lagged re-sync would double-create pods.

Reference parity: kubeflow/common controller.v1/expectation (embedded into
every reconciler, gate at tfjob_controller.go:140-147, bumps at :754-758,
rollback on failed create at :828-833). Semantics match
k8s.io/kubernetes/pkg/controller.ControllerExpectations.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple

_log = logging.getLogger(__name__)

# Expectations are forgotten after this long, so a crashed watch channel can
# never wedge a job forever (same 5-minute timeout as upstream).
EXPECTATION_TIMEOUT_SECONDS = 5 * 60.0

# (key, kind, outstanding adds, outstanding dels) — fired once per
# expectation that expires unfulfilled, so wedged-then-self-healed jobs are
# observable (metric + warning event at the controller) instead of silent.
TimeoutHandler = Callable[[str, str, int, int], None]


class _Expectation:
    __slots__ = ("adds", "dels", "timestamp", "timed_out")

    def __init__(self, adds: int, dels: int, now: float):
        self.adds = adds
        self.dels = dels
        self.timestamp = now
        self.timed_out = False

    def fulfilled(self) -> bool:
        return self.adds <= 0 and self.dels <= 0

    def expired(self, now: float) -> bool:
        return now - self.timestamp > EXPECTATION_TIMEOUT_SECONDS


class ControllerExpectations:
    """Thread-safe store of (controller key, kind) -> outstanding add/del counts.

    Keys look like "<namespace>/<name>"; kind is "pods" or "services" so one
    store serves both caches (the reference keys them as "<key>/pods").
    """

    def __init__(self, clock=time.monotonic, on_timeout: Optional[TimeoutHandler] = None):
        self._lock = threading.Lock()
        self._store: Dict[Tuple[str, str], _Expectation] = {}
        self._clock = clock
        self._on_timeout = on_timeout

    def expect_creations(self, key: str, kind: str, count: int) -> None:
        """Raise the outstanding-creation count by `count`. Accumulates on an
        unfulfilled expectation (the engine issues creates one at a time, so
        overwriting would under-record all but the last one and let a single
        observed event unlock a stale re-list -> double creates)."""
        self._accumulate(key, kind, adds=count)

    def expect_deletions(self, key: str, kind: str, count: int) -> None:
        self._accumulate(key, kind, dels=count)

    def _accumulate(self, key: str, kind: str, adds: int = 0, dels: int = 0) -> None:
        fire = None
        with self._lock:
            now = self._clock()
            exp = self._store.get((key, kind))
            if exp is None or exp.fulfilled() or exp.expired(now):
                fire = self._note_timeout_locked(key, kind, exp, now)
                self._store[(key, kind)] = _Expectation(max(adds, 0), max(dels, 0), now)
            else:
                exp.adds = max(exp.adds, 0) + adds
                exp.dels = max(exp.dels, 0) + dels
                exp.timestamp = now
        if fire is not None:
            self._fire_timeout(*fire)

    def creation_observed(self, key: str, kind: str) -> None:
        self._lower(key, kind, add_delta=-1)

    def deletion_observed(self, key: str, kind: str) -> None:
        self._lower(key, kind, del_delta=-1)

    def _lower(self, key: str, kind: str, add_delta: int = 0, del_delta: int = 0) -> None:
        with self._lock:
            exp = self._store.get((key, kind))
            if exp is None:
                return
            exp.adds += add_delta
            exp.dels += del_delta

    def satisfied(self, key: str, kind: str) -> bool:
        """True when it is safe to re-list and act: no expectation recorded,
        expectation fulfilled, or expectation expired."""
        fire = None
        with self._lock:
            exp = self._store.get((key, kind))
            if exp is None:
                return True
            if exp.fulfilled():
                return True
            now = self._clock()
            if not exp.expired(now):
                return False
            fire = self._note_timeout_locked(key, kind, exp, now)
        if fire is not None:
            self._fire_timeout(*fire)
        return True

    def _note_timeout_locked(self, key: str, kind: str, exp, now: float):
        """Mark an expired-unfulfilled expectation as timed out exactly
        once; returns the callback args to fire outside the lock (the
        handler writes metrics/events and must not reenter under it)."""
        if (
            exp is None
            or exp.fulfilled()
            or exp.timed_out
            or not exp.expired(now)
        ):
            return None
        exp.timed_out = True
        return (key, kind, max(exp.adds, 0), max(exp.dels, 0))

    def _fire_timeout(self, key: str, kind: str, adds: int, dels: int) -> None:
        _log.warning(
            "expectation for %s/%s expired unfulfilled (adds=%d dels=%d): "
            "the watch event never arrived; proceeding on a possibly-stale view",
            key, kind, adds, dels,
        )
        if self._on_timeout is None:
            return
        try:
            self._on_timeout(key, kind, adds, dels)
        except Exception:  # noqa: BLE001 — observability must not wedge syncs
            _log.exception("expectation-timeout handler failed for %s/%s", key, kind)

    def delete_expectations(self, key: str, kind: str) -> None:
        with self._lock:
            self._store.pop((key, kind), None)

    def get(self, key: str, kind: str) -> Optional[Tuple[int, int]]:
        with self._lock:
            exp = self._store.get((key, kind))
            return (exp.adds, exp.dels) if exp else None
