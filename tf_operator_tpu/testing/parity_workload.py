"""Throughput-parity workload: the measurement half of the multi-process
parity e2e (tests/test_throughput_parity.py).

Where rendezvous_workload proves the collective FABRIC through the
operator-injected env, this proves the fabric's SPEED: the same sharded
llama train step the bench harness times, run through ``tpu_init()`` (env
rendezvous + declared mesh) with the full input pipeline — host stream ->
DevicePrefetch device double-buffer -> donated batch — and timed. One JSON
line on stdout per process::

    {"process_id": N, "devices": N, "tokens_per_sec_chip": X,
     "step_ms": X, "loss": X}

The e2e compares a 2-process run (1 device per process, cross-process
collectives over gloo) against a single-process run of the SAME global
batch over the SAME mesh shape (2 local devices, in-process collectives):
the operator-injected env must cost nothing but the transport. Tolerance is
documented in docs/design/workload_performance.md — on CPU/gloo the bound
is deliberately loose (transport dominates tiny models); on TPU/ICI the
contract is near-parity.
"""

from __future__ import annotations

import json
import sys
import time


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--global-batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=64)
    parser.add_argument("--model", default="llama-tiny")
    args = parser.parse_args(argv)

    import jax

    from tf_operator_tpu.models import llama
    from tf_operator_tpu.parallel.sharding import batch_sharding
    from tf_operator_tpu.runtime.tpu_init import tpu_init
    from tf_operator_tpu.train.data import DevicePrefetch, SyntheticTokens
    from tf_operator_tpu.train.train_step import (
        init_sharded_train_state,
        make_optimizer,
        make_train_step,
    )

    topo, mesh = tpu_init(timeout_seconds=60)
    n = jax.device_count()
    print(
        f"[parity] process {topo.process_id}/{topo.num_processes} devices={n} "
        f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}",
        file=sys.stderr, flush=True,
    )
    if args.global_batch % topo.num_processes:
        print("[parity] global batch must divide process count", file=sys.stderr)
        return 2
    local_batch = args.global_batch // topo.num_processes

    config = llama.CONFIGS[args.model]
    model = llama.Llama(config)
    opt = make_optimizer(warmup_steps=1, decay_steps=max(args.steps, 10))
    state, sharding = init_sharded_train_state(
        model, jax.random.PRNGKey(0), opt, mesh, batch=1,
        seq=min(args.seq, 128),
    )
    step_fn, _ = make_train_step(
        model, opt, mesh, state, sharding=sharding, donate_batch=True
    )
    data = SyntheticTokens(local_batch, args.seq, config.vocab_size,
                           seed=topo.process_id)
    batches = DevicePrefetch(data, batch_sharding(mesh, with_sp=False),
                             depth=2)
    for _ in range(max(args.warmup, 1)):
        state, loss = step_fn(state, next(batches))
    float(loss)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, loss = step_fn(state, next(batches))
    final_loss = float(loss)  # device->host fetch is the barrier
    dt = time.perf_counter() - t0

    tokens_per_sec = args.global_batch * args.seq * args.steps / dt
    print(json.dumps({
        "process_id": topo.process_id,
        "num_processes": topo.num_processes,
        "devices": n,
        "tokens_per_sec_chip": round(tokens_per_sec / n, 1),
        "step_ms": round(dt / args.steps * 1000.0, 3),
        "loss": round(final_loss, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
