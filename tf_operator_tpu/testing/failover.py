"""Crash-point failover harness.

Drives a framework controller against a (usually chaos-proxied) cluster
and, whenever a planted `SimulatedCrash` escapes a sync, simulates a full
controller-process death + leader failover:

- the controller instance is discarded WHOLESALE — expectations, the
  gang-sweep cache, heartbeat observations, `_known_uids`, the workqueue:
  every piece of in-memory state dies with the process, exactly as it
  would with the pod;
- its watch registrations are severed (a dead process receives no
  events) via a generation-gated cluster proxy, since in-memory backends
  have no unsubscribe;
- a FRESH controller is constructed over the same cluster backend and
  cold-start resynced — the `cli.py resync_once` path: LIST every job of
  every enabled kind and enqueue it, which is all a real replacement
  leader has (persisted status; none of its predecessor's memory).

The chaos proxy (and its per-method call counters) lives on the CLUSTER
side of the crash, so the fault schedule keeps advancing across
failovers: a fixed seed replays the identical crash/fault schedule
byte-for-byte, run to run — the property the crash tier asserts.

Sync concurrency: the driver steps `process_next` from the test thread,
so it is a one-worker pool by construction no matter what
`EngineOptions.sync_workers` requests — the same serial verdict the
chaos seam's `supports_concurrent_syncs=False` forces on a
manager-hosted pool (`resolve_sync_workers`). Crash schedules therefore
stay byte-reproducible with the worker pool feature enabled.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from ..cluster.base import Cluster
from ..cluster.chaos import SimulatedCrash


class _GenerationGate:
    """Cluster proxy handed to ONE controller incarnation: everything
    delegates to the shared backend, but watch handlers registered
    through it are dropped once the incarnation is superseded — the
    in-memory backends have no unsubscribe, and a discarded controller
    must not keep reacting to events (updating its dead expectations,
    enqueuing into its dead queue) like a process that never died."""

    def __init__(self, inner: Cluster, driver: "FailoverDriver", generation: int):
        self._inner = inner
        self._driver = driver
        self._generation = generation

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def watch(self, kind, handler):
        def gated(event_type, obj):
            if self._driver.generation != self._generation:
                return  # this incarnation is dead; it receives nothing
            handler(event_type, obj)

        self._inner.watch(kind, gated)


class FailoverDriver:
    """Runs `controller_factory(cluster)` to convergence, failing over on
    every SimulatedCrash. `controller_factory` must build a COMPLETE
    controller (its own queue, metrics, expectations) from nothing but a
    cluster — any state smuggled past it would survive the "crash" and
    invalidate the whole exercise."""

    def __init__(
        self,
        cluster: Cluster,
        controller_factory: Callable[[Cluster], object],
        kinds: Sequence[str] = ("JAXJob",),
        namespace: Optional[str] = None,
        max_failovers: int = 100,
        tracer=None,
    ):
        self._cluster = cluster
        self._factory = controller_factory
        self.kinds = tuple(kinds)
        self.namespace = namespace
        self.max_failovers = max_failovers
        # Optional core/tracing.py Tracer shared by every controller
        # incarnation (the factory must wire it in): the trace OUTLIVES
        # each simulated crash, so a post-mortem reads one causal
        # timeline across failovers. On a budget-exceeded failure the
        # export is dumped into build/ and referenced from the assertion.
        self.tracer = tracer
        self.generation = 0
        self.crashes: List[str] = []  # one entry per failover, in order
        self.controller = None
        self._boot()

    # ------------------------------------------------------------ lifecycle
    def _boot(self) -> None:
        """Construct a fresh controller incarnation over the shared
        backend and cold-start resync it (the cli.py resync_once path)."""
        self.generation += 1
        gate = _GenerationGate(self._cluster, self, self.generation)
        self.controller = self._factory(gate)
        self.resync()

    def fail_over(self, crash: BaseException) -> None:
        """Record the crash and replace the controller. Public so tests
        can also force a failover at a chosen point (leader handoff
        without a crash)."""
        self.crashes.append(str(crash))
        if len(self.crashes) > self.max_failovers:
            message = (
                f"failover budget exceeded ({self.max_failovers}): the "
                "crash schedule never lets the controller converge"
            )
            if self.tracer is not None:
                from .invariants import dump_trace

                path = dump_trace(self.tracer, "failover_budget_exceeded")
                if path:
                    message += f"; trace dump: {path}"
            raise AssertionError(message) from crash
        self._boot()

    def resync(self) -> None:
        """Cold-start enqueue from a LIST — everything a fresh leader has."""
        for kind in self.kinds:
            for job in self._cluster.list_jobs(kind, self.namespace):
                meta = job.get("metadata", {}) or {}
                self.controller._enqueue(
                    meta.get("namespace", "default"), meta.get("name", "")
                )

    # ------------------------------------------------------------- driving
    def step(self) -> bool:
        """One process_next, converting a SimulatedCrash into a failover.
        Returns whether an item was processed (or a failover happened)."""
        try:
            return self.controller.process_next(timeout=0.01)
        except SimulatedCrash as crash:
            self.fail_over(crash)
            return True

    def run_until_idle(self, max_iterations: int = 10_000) -> None:
        """Drain to convergence across however many failovers the
        schedule inflicts (the crash-surviving run_until_idle)."""
        for _ in range(max_iterations):
            if self.controller.queue.empty_and_idle():
                return
            self.step()
