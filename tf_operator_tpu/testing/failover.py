"""Crash-point failover harness.

Drives a framework controller against a (usually chaos-proxied) cluster
and, whenever a planted `SimulatedCrash` escapes a sync, simulates a full
controller-process death + leader failover:

- the controller instance is discarded WHOLESALE — expectations, the
  gang-sweep cache, heartbeat observations, `_known_uids`, the workqueue:
  every piece of in-memory state dies with the process, exactly as it
  would with the pod;
- its watch registrations are severed (a dead process receives no
  events) via a generation-gated cluster proxy, since in-memory backends
  have no unsubscribe;
- a FRESH controller is constructed over the same cluster backend and
  cold-start resynced — the `cli.py resync_once` path: LIST every job of
  every enabled kind and enqueue it, which is all a real replacement
  leader has (persisted status; none of its predecessor's memory).

The chaos proxy (and its per-method call counters) lives on the CLUSTER
side of the crash, so the fault schedule keeps advancing across
failovers: a fixed seed replays the identical crash/fault schedule
byte-for-byte, run to run — the property the crash tier asserts.

Sync concurrency: the driver steps `process_next` from the test thread,
so it is a one-worker pool by construction no matter what
`EngineOptions.sync_workers` requests — the same serial verdict the
chaos seam's `supports_concurrent_syncs=False` forces on a
manager-hosted pool (`resolve_sync_workers`). Crash schedules therefore
stay byte-reproducible with the worker pool feature enabled.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from ..cluster.base import Cluster
from ..cluster.chaos import SimulatedCrash


class _GenerationGate:
    """Cluster proxy handed to ONE controller incarnation: everything
    delegates to the shared backend, but watch handlers registered
    through it are dropped once the incarnation is superseded — the
    in-memory backends have no unsubscribe, and a discarded controller
    must not keep reacting to events (updating its dead expectations,
    enqueuing into its dead queue) like a process that never died."""

    def __init__(self, inner: Cluster, driver: "FailoverDriver", generation: int):
        self._inner = inner
        self._driver = driver
        self._generation = generation

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def watch(self, kind, handler):
        def gated(event_type, obj):
            if self._driver.generation != self._generation:
                return  # this incarnation is dead; it receives nothing
            handler(event_type, obj)

        self._inner.watch(kind, gated)


class FailoverDriver:
    """Runs `controller_factory(cluster)` to convergence, failing over on
    every SimulatedCrash. `controller_factory` must build a COMPLETE
    controller (its own queue, metrics, expectations) from nothing but a
    cluster — any state smuggled past it would survive the "crash" and
    invalidate the whole exercise."""

    def __init__(
        self,
        cluster: Cluster,
        controller_factory: Callable[[Cluster], object],
        kinds: Sequence[str] = ("JAXJob",),
        namespace: Optional[str] = None,
        max_failovers: int = 100,
        tracer=None,
    ):
        self._cluster = cluster
        self._factory = controller_factory
        self.kinds = tuple(kinds)
        self.namespace = namespace
        self.max_failovers = max_failovers
        # Optional core/tracing.py Tracer shared by every controller
        # incarnation (the factory must wire it in): the trace OUTLIVES
        # each simulated crash, so a post-mortem reads one causal
        # timeline across failovers. On a budget-exceeded failure the
        # export is dumped into build/ and referenced from the assertion.
        self.tracer = tracer
        self.generation = 0
        self.crashes: List[str] = []  # one entry per failover, in order
        self.controller = None
        self._boot()

    # ------------------------------------------------------------ lifecycle
    def _boot(self) -> None:
        """Construct a fresh controller incarnation over the shared
        backend and cold-start resync it (the cli.py resync_once path)."""
        self.generation += 1
        gate = _GenerationGate(self._cluster, self, self.generation)
        self.controller = self._factory(gate)
        self.resync()

    def fail_over(self, crash: BaseException) -> None:
        """Record the crash and replace the controller. Public so tests
        can also force a failover at a chosen point (leader handoff
        without a crash)."""
        self.crashes.append(str(crash))
        if len(self.crashes) > self.max_failovers:
            message = (
                f"failover budget exceeded ({self.max_failovers}): the "
                "crash schedule never lets the controller converge"
            )
            if self.tracer is not None:
                from .invariants import dump_trace

                path = dump_trace(self.tracer, "failover_budget_exceeded")
                if path:
                    message += f"; trace dump: {path}"
            raise AssertionError(message) from crash
        self._boot()

    def resync(self) -> None:
        """Cold-start enqueue from a LIST — everything a fresh leader has."""
        for kind in self.kinds:
            for job in self._cluster.list_jobs(kind, self.namespace):
                meta = job.get("metadata", {}) or {}
                self.controller._enqueue(
                    meta.get("namespace", "default"), meta.get("name", "")
                )

    # ------------------------------------------------------------- driving
    def step(self) -> bool:
        """One process_next, converting a SimulatedCrash into a failover.
        Returns whether an item was processed (or a failover happened)."""
        try:
            return self.controller.process_next(timeout=0.01)
        except SimulatedCrash as crash:
            self.fail_over(crash)
            return True

    def run_until_idle(self, max_iterations: int = 10_000) -> None:
        """Drain to convergence across however many failovers the
        schedule inflicts (the crash-surviving run_until_idle)."""
        for _ in range(max_iterations):
            if self.controller.queue.empty_and_idle():
                return
            self.step()


# --------------------------------------------------------------- sharded HA


class _AliveGate:
    """Per-replica cluster proxy: watch handlers registered through it go
    dead with the replica (the multi-replica analog of _GenerationGate —
    a crashed replica's process receives no events, but the in-memory
    backends have no unsubscribe)."""

    def __init__(self, inner: Cluster, replica: "_ShardReplica"):
        self._inner = inner
        self._replica = replica

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def watch(self, kind, handler):
        def gated(event_type, obj):
            if not self._replica.alive:
                return
            handler(event_type, obj)

        self._inner.watch(kind, gated)


class _ShardReplica:
    """One replica slot of the ShardFailoverDriver: a ShardCoordinator
    plus a controller incarnation (and, when the driver runs with watch
    caches, a per-replica shard-scoped SharedWatchCache), all discarded
    wholesale on a simulated crash."""

    def __init__(self, identity: str):
        self.identity = identity
        self.alive = True
        self.coordinator = None
        self.controller = None
        self.cache = None


class ShardFailoverDriver:
    """The sharded extension of FailoverDriver: N replica slots over ONE
    shared (usually chaos-proxied) cluster, each with its own
    ShardCoordinator (core/sharding.py) and its own controller built by
    `controller_factory(cluster, owns)` — the factory must wire `owns`
    into the controller's enqueue scope filter, exactly as
    OperatorManager does.

    Time is FULLY driver-owned: one fake clock (`self.now`, advanced via
    `advance()`) feeds every lease lock and liveness observation, so
    lease expiry — and with it the steal schedule — is a pure function of
    the step/advance sequence. One `step()` = one coordinator tick per
    live replica (sorted identity order) followed by one process_next
    per live replica; a SimulatedCrash escaping either kills THAT replica
    wholesale (controller, coordinator, expectations, queue, watches —
    nothing survives but persisted cluster state and the replica's
    now-unrenewed leases). Survivors steal its shards once `advance()`
    ages the leases past their duration on the survivors' observation
    clocks.

    The chaos proxy's per-method counters live on the shared cluster, so
    a fixed (seed, plan, drive sequence) replays the identical fault AND
    crash schedule byte-for-byte — the property the shard-failover tier
    asserts across ownership migrations."""

    def __init__(
        self,
        cluster: Cluster,
        controller_factory: Callable[[Cluster, Callable[[str, str], bool]], object],
        shards: int = 4,
        replicas: int = 2,
        kinds: Sequence[str] = ("JAXJob",),
        namespace: Optional[str] = None,
        lease_name: str = "shard-ha",
        duration: float = 10.0,
        max_failovers: int = 100,
        tracer=None,
        affinity: str = "uniform",
        affinity_spread: int = 1,
        use_watch_cache: bool = False,
    ):
        from ..core.sharding import ShardCoordinator, shard_for_key

        self._cluster = cluster
        self._factory = controller_factory
        self.shards = shards
        self.kinds = tuple(kinds)
        self.namespace = namespace
        self.lease_name = lease_name
        self.duration = duration
        self.max_failovers = max_failovers
        self.tracer = tracer
        self.affinity = affinity
        self.affinity_spread = affinity_spread
        # When True each replica gets its own shard-scoped
        # SharedWatchCache (cluster/watchcache.py) wired exactly like
        # OperatorManager does: scope = the replica's coordinator, prime
        # on claim BEFORE the resync, teardown on release. The factory
        # is then called with a `watch_cache=` keyword. Requires a
        # backend whose supports_watch_cache is True (NOT the chaos
        # seam).
        self.use_watch_cache = use_watch_cache
        self.now = 1000.0  # the one clock; advance() moves it
        self.crashes: List[str] = []
        self.handoffs: List[str] = []  # "identity:claim|steal|...:shard"
        self._shard_for_key = shard_for_key
        self._coordinator_cls = ShardCoordinator
        self.replicas: Dict[str, _ShardReplica] = {}
        for i in range(replicas):
            self.boot(f"replica-{i}")

    def _clock(self) -> float:
        return self.now

    # ------------------------------------------------------------ lifecycle
    def boot(self, identity: str) -> _ShardReplica:
        """Start (or restart after kill) one replica: fresh coordinator,
        fresh controller, nothing carried over — `revive` semantics for a
        rolling-restart scenario."""
        replica = _ShardReplica(identity)
        gate = _AliveGate(self._cluster, replica)

        def on_claim(shard: int, cause: str, _replica=replica) -> None:
            self.handoffs.append(f"{_replica.identity}:{cause}:{shard}")
            # Same ordering contract as OperatorManager._on_shard_claimed:
            # warm the scoped cache FIRST, so the resync's enqueued keys
            # sync against a primed store (zero accounted reads even on
            # the first post-steal sync).
            if _replica.cache is not None:
                _replica.cache.prime_shard(shard)
            self._resync_shard(_replica, shard)

        def on_release(shard: int, cause: str, _replica=replica) -> None:
            self.handoffs.append(f"{_replica.identity}:{cause}:{shard}")
            if _replica.cache is not None:
                _replica.cache.drop_shard(shard)

        replica.coordinator = self._coordinator_cls(
            gate,
            shards=self.shards,
            identity=identity,
            namespace=self.namespace or "default",
            lease_name=self.lease_name,
            duration=self.duration,
            clock=self._clock,
            mono=self._clock,
            on_claim=on_claim,
            on_release=on_release,
            # The driver steps replicas from one thread: nothing is ever
            # mid-sync at tick time, so drains complete instantly and
            # deterministically.
            drain_check=None,
            affinity=self.affinity,
            affinity_spread=self.affinity_spread,
        )
        # Enqueue filter = admits (the claim resync enqueues through it
        # while the shard is still warming); the step() gate syncs
        # through allows, exactly like OperatorManager.
        owns = replica.coordinator.admits
        if self.use_watch_cache:
            from ..cluster.watchcache import SharedWatchCache

            # Built AFTER the coordinator (it is the scope) and BEFORE
            # the controller (the cache's watch handlers must run first
            # in dispatch order — the PR 7 ordering contract).
            replica.cache = SharedWatchCache(
                gate, namespace=self.namespace, scope=replica.coordinator)
            replica.controller = self._factory(
                gate, owns, watch_cache=replica.cache)
        else:
            replica.controller = self._factory(gate, owns)
        self.replicas[identity] = replica
        return replica

    def kill(self, identity: str, crash: Optional[BaseException] = None) -> None:
        """Simulated process death: the replica stops renewing member and
        shard leases at once (no release — that is the crash/steal path,
        not the drain path) and its in-memory state is discarded."""
        replica = self.replicas.pop(identity)
        replica.alive = False
        self.crashes.append(str(crash) if crash is not None else f"killed:{identity}")
        if len(self.crashes) > self.max_failovers:
            message = (
                f"failover budget exceeded ({self.max_failovers}): the "
                "crash schedule never lets the fleet converge"
            )
            if self.tracer is not None:
                from .invariants import dump_trace

                path = dump_trace(self.tracer, "shard_failover_budget_exceeded")
                if path:
                    message += f"; trace dump: {path}"
            raise AssertionError(message) from crash

    def advance(self, seconds: float) -> None:
        """Move the fake clock: leases age, liveness observations go
        stale, steal windows open. One full-duration jump ages EVERY
        lease at once — live replicas then mutually rank each other dead
        on their next tick (nobody renewed "during" the jump). That is
        the right tool for "the fleet was frozen/partitioned"; for
        ordinary wall-time passage where live replicas keep renewing, use
        run_clock."""
        self.now += seconds

    def run_clock(self, seconds: float, step: Optional[float] = None) -> None:
        """Advance the fake clock the way real time passes: in
        sub-duration increments with coordination+sync rounds between,
        so LIVE replicas keep each other's liveness observations fresh
        (their elect loops tick every duration/3) while anything that
        genuinely stopped renewing — a killed replica, a holder whose
        renewals chaos swallows — ages toward expiry and steal."""
        step = step if step is not None else self.duration / 3.0
        remaining = seconds
        while remaining > 0:
            delta = min(step, remaining)
            self.now += delta
            remaining -= delta
            self.settle()

    # ------------------------------------------------------------- queries
    def _live(self) -> List[_ShardReplica]:
        return [self.replicas[k] for k in sorted(self.replicas)]

    def shard_of(self, namespace: str, name: str) -> int:
        """Placement under the CURRENT ring: a live replica's coordinator
        view when one exists (it tracks live resizes), else the boot
        parameters."""
        live = self._live()
        if live:
            return live[0].coordinator.shard_of(namespace, name)
        return self._shard_for_key(namespace, name, self.shards,
                                   self.affinity, self.affinity_spread)

    def owner_of(self, namespace: str, name: str) -> Optional[str]:
        """Which live replica owns the job's shard right now (None = the
        shard is currently orphaned — mid-migration). Each replica's
        placement is computed under ITS ring view: mid-resize the views
        diverge, and a replica only counts as owner by its own ring."""
        for replica in self._live():
            coordinator = replica.coordinator
            if coordinator.owns(coordinator.shard_of(namespace, name)):
                return replica.identity
        return None

    def request_resize(self, shards: int) -> int:
        """Publish a live ring resize through the shared cluster (the
        config-lease protocol); replicas migrate on their next ticks.
        Returns the published epoch."""
        from ..core.sharding import publish_ring_resize

        return publish_ring_resize(
            self._cluster, self.namespace or "default", self.lease_name,
            shards)

    def owned_map(self) -> Dict[str, List[int]]:
        return {
            r.identity: r.coordinator.owned_shards() for r in self._live()
        }

    # ------------------------------------------------------------- driving
    def _resync_shard(self, replica: _ShardReplica, shard: int) -> None:
        """The claim half of the handoff — the SAME resync_shard_jobs
        helper OperatorManager runs, so the harness can never drift from
        the production protocol. All a new owner has is persisted status."""
        from ..core.sharding import resync_shard_jobs

        controller = replica.controller
        if controller is None:
            return  # claim fired during boot, before the controller exists
        for kind in self.kinds:
            resync_shard_jobs(
                controller, self._cluster, kind, self.namespace, shard,
                replica.coordinator.shards,
                shard_of=replica.coordinator.shard_of,
            )

    def tick(self) -> None:
        """One coordination round per live replica, in identity order."""
        for replica in self._live():
            try:
                replica.coordinator.tick()
            except SimulatedCrash as crash:
                self.kill(replica.identity, crash)

    def step(self) -> bool:
        """tick + one process_next per live replica; crashes kill the
        crashing replica and the fleet drives on. Returns whether any
        replica made progress (or died trying)."""
        self.tick()
        processed = False
        for replica in self._live():
            def gate(item, _c=replica.coordinator):
                ns, _, name = item.partition(":")[2].partition("/")
                return _c.allows(ns, name)

            try:
                processed = replica.controller.process_next(
                    timeout=0.01, gate=gate
                ) or processed
            except SimulatedCrash as crash:
                self.kill(replica.identity, crash)
                processed = True
        return processed

    def settle(self, max_iterations: int = 10_000) -> None:
        """Drive until every live replica's queue is idle for two full
        rounds (ticks keep running inside — claims and drains settle as
        part of it)."""
        idle_rounds = 0
        for _ in range(max_iterations):
            if self.step() or not all(
                r.controller.queue.empty_and_idle() for r in self._live()
            ):
                idle_rounds = 0
                continue
            idle_rounds += 1
            if idle_rounds >= 2:
                return
        raise AssertionError(
            f"shard fleet never settled in {max_iterations} iterations "
            f"(owned={self.owned_map()}, crashes={self.crashes})"
        )
