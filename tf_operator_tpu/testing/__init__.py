"""Controllable test workloads for the e2e tier.

Reference parity: test/test-server (the flask app TFJob e2e suites run as
the training container — test/test-server/test_app.py:27-58) plus a
JAX-native rendezvous workload the reference has no equivalent of.
"""
