"""Real-TensorFlow MultiWorkerMirroredStrategy training workload.

Run as a TFJob container command in the process-backed e2e tier: REAL
TensorFlow consumes the operator-injected TF_CONFIG (no repo re-parse, no
stdlib stand-in), builds a MultiWorkerMirroredStrategy whose collectives
rendezvous over the injected cluster addresses, and trains a tiny linear
model for a few steps on CPU with a custom loop (Keras 3 model.fit does
not support MWMS). This is the loop the reference closes with dist-mnist
on a live cluster (examples/tensorflow/dist-mnist/dist_mnist.py:139-143
builds tf.train.Server straight from TF_CONFIG); VERDICT r3 missing #1
asked for the same proof here.

Success criteria, each printed as a parseable log line:
  MWMS_TOPOLOGY {json}   — what TF's resolver observed (type/index/cluster)
  MWMS_REPLICAS n        — strategy.num_replicas_in_sync (must == world)
  MWMS_ALLREDUCE v       — mean of per-worker task ids (proves the
                           collective actually spanned workers)
  MWMS_LOSS_{first,last} — training-step losses (last < first => learning,
                           and identical across workers => synchronized)
  MWMS_OK                — everything above passed in-process
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    import numpy as np
    import tensorflow as tf

    resolver = tf.distribute.cluster_resolver.TFConfigClusterResolver()
    topo = {
        "task_type": resolver.task_type,
        "task_id": int(resolver.task_id),
        "cluster_spec": resolver.cluster_spec().as_dict(),
    }
    print(f"MWMS_TOPOLOGY {json.dumps(topo)}", flush=True)

    strategy = tf.distribute.MultiWorkerMirroredStrategy(cluster_resolver=resolver)
    world = sum(len(v) for v in topo["cluster_spec"].values())
    n_sync = int(strategy.num_replicas_in_sync)
    print(f"MWMS_REPLICAS {n_sync}", flush=True)
    if n_sync != world:
        print(f"MWMS_FAIL num_replicas_in_sync {n_sync} != world {world}",
              flush=True)
        return 1

    # Cross-worker collective proof: each replica contributes its position
    # in the flattened cluster (generalizes over chief+worker layouts);
    # the all-reduced MEAN is only correct if the ring spanned every task.
    flat = sorted(
        (t, i)
        for t, addrs in topo["cluster_spec"].items()
        for i in range(len(addrs))
    )
    my_pos = flat.index((topo["task_type"], topo["task_id"]))

    @tf.function
    def contribute():
        ctx = tf.distribute.get_replica_context()
        return ctx.all_reduce(
            tf.distribute.ReduceOp.MEAN, tf.cast(my_pos, tf.float32)
        )

    reduced = strategy.run(contribute)
    reduced = float(strategy.reduce(tf.distribute.ReduceOp.MEAN, reduced, axis=None))
    expect = sum(range(world)) / world
    print(f"MWMS_ALLREDUCE {reduced}", flush=True)
    if abs(reduced - expect) > 1e-5:
        print(f"MWMS_FAIL allreduce {reduced} != {expect}", flush=True)
        return 1

    # Synchronized custom training loop: tiny linear regression; every
    # worker must see the SAME loss trajectory (same data, all-reduced
    # grads) and it must fall.
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = x @ rng.normal(size=(8, 1)).astype(np.float32)
    with strategy.scope():
        w = tf.Variable(tf.zeros((8, 1)), aggregation=tf.VariableAggregation.MEAN)

    @tf.function
    def train_step(xb, yb):
        def step_fn(xb, yb):
            with tf.GradientTape() as tape:
                loss = tf.reduce_mean(tf.square(tf.matmul(xb, w) - yb))
            g = tape.gradient(loss, w)
            ctx = tf.distribute.get_replica_context()
            g = ctx.all_reduce(tf.distribute.ReduceOp.MEAN, g)
            w.assign_sub(0.1 * g)
            return loss

        per = strategy.run(step_fn, args=(xb, yb))
        return strategy.reduce(tf.distribute.ReduceOp.MEAN, per, axis=None)

    losses = []
    for step in range(24):
        lo = step * 32 % 256
        losses.append(float(train_step(x[lo:lo + 32], y[lo:lo + 32])))
    print(f"MWMS_LOSS_first {losses[0]:.6f}", flush=True)
    print(f"MWMS_LOSS_last {losses[-1]:.6f}", flush=True)
    if not losses[-1] < losses[0]:
        print("MWMS_FAIL loss did not decrease", flush=True)
        return 1
    print("MWMS_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
