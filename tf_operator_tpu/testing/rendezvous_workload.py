"""Real `jax.distributed` rendezvous workload for the process e2e tier.

What dist_mnist.py is to the reference's e2e suites (SURVEY.md §3.5), this
is to ours: a container program that consumes ONLY the operator-injected
env, rendezvouses through `tpu_init`, and proves the collective fabric by
psum-ing each process's contribution across every device. Exit code 0 only
if the global sum matches the expected closed form.

``--progress-steps N`` appends a liveness-exercising training loop: N
steps, each running the same psum collective (so a wedged peer stalls the
whole gang, exactly like a real SPMD step) and reporting progress via
``record_progress`` — the workload half of the gang-liveness contract the
ProgressStall e2e regression SIGSTOPs mid-loop.
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--progress-steps", type=int, default=0)
    parser.add_argument("--step-seconds", type=float, default=0.25)
    args = parser.parse_args(argv)
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.runtime.tpu_init import global_mesh, initialize

    topo = initialize(timeout_seconds=60)
    print(
        f"[rendezvous] process_id={topo.process_id} "
        f"num_processes={topo.num_processes} "
        f"coordinator={topo.coordinator_address}",
        flush=True,
    )
    n_global = jax.device_count()
    n_local = jax.local_device_count()
    print(
        f"[rendezvous] device_count={n_global} local_device_count={n_local}",
        flush=True,
    )
    if topo.distributed and n_global == n_local:
        print("[rendezvous] FAIL: rendezvous did not federate devices", flush=True)
        return 3

    # Every device contributes 1; psum across all must equal device_count.
    mesh = global_mesh(topo)
    axis_names = mesh.axis_names

    from jax.sharding import PartitionSpec as P

    from tf_operator_tpu.parallel.compat import shard_map

    def contribute():
        total = jnp.float32(1.0)
        for name in axis_names:
            total = jax.lax.psum(total, name)
        return total

    summed = jax.jit(
        shard_map(contribute, mesh=mesh, in_specs=(), out_specs=P())
    )()
    got = float(jnp.asarray(summed.addressable_data(0)))
    want = float(n_global)
    print(f"[rendezvous] psum={got} want={want}", flush=True)
    if got != want:
        print("[rendezvous] FAIL: collective mismatch", flush=True)
        return 4

    if args.progress_steps > 0:
        import time

        from tf_operator_tpu.runtime.heartbeat import record_progress

        step_fn = jax.jit(
            shard_map(contribute, mesh=mesh, in_specs=(), out_specs=P())
        )
        for step in range(args.progress_steps):
            # A real collective per step: a SIGSTOPped peer blocks every
            # process here (its heartbeat thread freezes with it), while
            # healthy peers keep renewing from their own threads — the
            # asymmetry the stall detector keys on.
            jax.block_until_ready(step_fn())
            record_progress(step=step)
            time.sleep(args.step_seconds)
        print(f"[rendezvous] progress loop done ({args.progress_steps} steps)",
              flush=True)

    print("[rendezvous] OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
