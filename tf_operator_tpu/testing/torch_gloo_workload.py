"""Real-torch.distributed gloo rendezvous workload.

Run as a PyTorchJob container command in the process-backed e2e tier:
genuine torch.distributed reads the operator-injected MASTER_ADDR /
MASTER_PORT / RANK / WORLD_SIZE (bootstrap/c10d.py, reference
pytorch.go:27-82) through init_process_group's env:// rendezvous — the
exact consumption path `torchrun`-less reference jobs use (reference
examples/pytorch/smoke-dist/dist_sendrecv.py) — then proves the process
group with one allreduce and one send/recv ring.

Log lines the e2e asserts on:
  GLOO_ENV {json}     — the env contract as torch consumed it
  GLOO_ALLREDUCE v    — sum of (rank+1) across the world
  GLOO_RING v         — received value from the left neighbor
  GLOO_OK             — all checks passed in-process
"""

from __future__ import annotations

import datetime
import json
import os
import sys


def main() -> int:
    import torch
    import torch.distributed as dist

    env = {k: os.environ.get(k) for k in
           ("MASTER_ADDR", "MASTER_PORT", "RANK", "WORLD_SIZE")}
    print(f"GLOO_ENV {json.dumps(env)}", flush=True)

    # env:// rendezvous — torch reads MASTER_ADDR/PORT/RANK/WORLD_SIZE
    # itself; passing them explicitly would defeat the contract test.
    dist.init_process_group(
        backend="gloo", init_method="env://",
        timeout=datetime.timedelta(seconds=60),
    )
    rank, world = dist.get_rank(), dist.get_world_size()
    if rank != int(env["RANK"]) or world != int(env["WORLD_SIZE"]):
        print(f"GLOO_FAIL rank/world mismatch: {rank}/{world} vs env", flush=True)
        return 1

    t = torch.tensor([float(rank + 1)])
    dist.all_reduce(t, op=dist.ReduceOp.SUM)
    expect = world * (world + 1) / 2
    print(f"GLOO_ALLREDUCE {t.item()}", flush=True)
    if t.item() != expect:
        print(f"GLOO_FAIL allreduce {t.item()} != {expect}", flush=True)
        return 1

    # Send/recv ring (smoke-dist parity): pass rank to the right neighbor.
    # Degenerate world=1 has no neighbor — send-to-self would deadlock.
    if world > 1:
        recv = torch.zeros(1)
        send = torch.tensor([float(rank)])
        right, left = (rank + 1) % world, (rank - 1) % world
        if rank % 2 == 0:
            dist.send(send, dst=right)
            dist.recv(recv, src=left)
        else:
            dist.recv(recv, src=left)
            dist.send(send, dst=right)
        print(f"GLOO_RING {recv.item()}", flush=True)
        if int(recv.item()) != left:
            print(f"GLOO_FAIL ring recv {recv.item()} != {left}", flush=True)
            return 1

    dist.barrier()
    dist.destroy_process_group()
    print("GLOO_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
