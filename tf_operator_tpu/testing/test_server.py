"""Controllable in-pod test server.

Reference parity: test/test-server/test_app.py — the flask app e2e suites
run as the TFJob container, with `/runconfig` returning the *observed*
cluster topology (test_app.py:31-44) and `/exit?exitCode=N` forcing a
specific exit code (test_app.py:46-58). This version is stdlib-only (fast
cold start, no flask dependency) and adds `/meshconfig`: the JAX-era view
of the operator-injected env (process id/count, slice coords, mesh axes).

The server derives its own bind address the same way a TF worker does —
from TF_CONFIG's cluster spec at [task.type][task.index] — so it listens on
exactly the address the operator's service DNS points at. Under
LocalProcessCluster that hostname has been rewritten to the service's own
loopback alias IP (declared port preserved).

Endpoints:
  GET /runconfig          observed TF view: task type/index, cluster spec
  GET /env                injected JAX_/TPU_/MEGASCALE_/TF_CONFIG env dump
  GET /meshconfig         observed JAX view: topology_from_env() fields
  GET /healthz            "ok"
  GET /exit?exitCode=N    responds "exiting N" then exits with code N
"""

from __future__ import annotations

import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


def _own_address() -> tuple:
    """(host, port) this replica should listen on, from injected env."""
    raw = os.environ.get("TF_CONFIG")
    if raw:
        cfg = json.loads(raw)
        task = cfg.get("task", {})
        ttype, tindex = task.get("type", ""), int(task.get("index", 0))
        cluster = cfg.get("cluster") or {}
        if ttype in cluster:
            entry = cluster[ttype][tindex]
            host, port = entry.rsplit(":", 1)
            return host, int(port)
        sparse = cfg.get("sparseCluster") or {}
        entry = None
        if ttype in sparse:
            group = sparse[ttype]
            if isinstance(group, dict):
                entry = group.get(str(tindex)) or group.get(tindex)
            elif isinstance(group, list) and tindex < len(group):
                entry = group[tindex]
        if entry:
            host, port = entry.rsplit(":", 1)
            return host, int(port)
    # MXJob path: MX_CONFIG carries {cluster: {type: [{url, port}]}, task}.
    raw = os.environ.get("MX_CONFIG")
    if raw:
        cfg = json.loads(raw)
        task = cfg.get("task", {})
        entries = (cfg.get("cluster") or {}).get(task.get("type", ""), [])
        tindex = int(task.get("index", 0))
        if tindex < len(entries):
            entry = entries[tindex]
            return entry["url"], int(entry["port"])
    # JAXJob path: every worker listens on its own slice hostname at the
    # coordinator port (worker-0's IS the coordinator address).
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coord:
        host, port = coord.rsplit(":", 1)
        if os.environ.get("JAX_PROCESS_ID", "0") != "0":
            hosts = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
            wid = int(os.environ.get("TPU_WORKER_ID", "0"))
            if wid < len(hosts):
                host = hosts[wid]
        return host, int(port)
    return "127.0.0.1", int(os.environ.get("TEST_SERVER_PORT", "0"))


def _runconfig(use_tf: bool = None) -> dict:
    raw = os.environ.get("TF_CONFIG")
    if not raw:
        return {}
    if use_tf is None:
        use_tf = bool(os.environ.get("TEST_SERVER_RUNCONFIG_TF"))
    if use_tf:
        # Report what REAL TensorFlow observed, like the reference
        # test-server returning tf.estimator.RunConfig fields
        # (test/test-server/test_app.py:31-44) — the operator-injected env
        # interpreted by the framework it targets, not re-parsed by repo
        # code. Opt-in per job (TF import costs ~20 s per pod; the broad
        # e2e matrix stays on the stdlib path below).
        os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
        import tensorflow as tf  # type: ignore

        resolver = tf.distribute.cluster_resolver.TFConfigClusterResolver()
        return {
            "task_type": resolver.task_type,
            "task_id": int(resolver.task_id),
            "cluster_spec": resolver.cluster_spec().as_dict(),
            "is_chief": resolver.task_type in ("chief", "master"),
            "environment": resolver.environment or "",
            "source": "tensorflow",
        }
    cfg = json.loads(raw)
    return {
        "task_type": cfg.get("task", {}).get("type", ""),
        "task_id": int(cfg.get("task", {}).get("index", 0)),
        "cluster_spec": cfg.get("cluster") or cfg.get("sparseCluster") or {},
        "is_chief": cfg.get("task", {}).get("type") in ("chief", "master"),
        "environment": cfg.get("environment", ""),
        "source": "env",
    }


def _meshconfig() -> dict:
    from ..runtime.tpu_init import topology_from_env

    topo = topology_from_env()
    return {
        "coordinator_address": topo.coordinator_address,
        "num_processes": topo.num_processes,
        "process_id": topo.process_id,
        "worker_id": topo.worker_id,
        "num_slices": topo.num_slices,
        "slice_index": topo.slice_index,
        "mesh_axes": topo.mesh_axes,
        "accelerator_type": topo.accelerator_type,
    }


class Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):
        print(f"[test-server] {fmt % args}", flush=True)

    def _json(self, payload: dict, code: int = 200) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib API)
        url = urlparse(self.path)
        if url.path == "/runconfig":
            self._json(_runconfig())
        elif url.path == "/meshconfig":
            self._json(_meshconfig())
        elif url.path == "/env":
            # Injected-bootstrap dump: the JAX/TPU rendezvous env exactly as
            # the operator delivered it (elastic-resize e2e asserts on it).
            self._json(
                {
                    k: v
                    for k, v in os.environ.items()
                    if k.startswith(
                        ("JAX_", "TPU_", "MEGASCALE_", "TF_CONFIG",
                         "DMLC_", "MX_CONFIG", "MASTER_", "WORLD_SIZE", "RANK")
                    )
                }
            )
        elif url.path == "/healthz":
            self._json({"status": "ok"})
        elif url.path == "/exit":
            code = int(parse_qs(url.query).get("exitCode", ["0"])[0])
            self._json({"exiting": code})
            print(f"[test-server] exiting with code {code}", flush=True)
            # Flush the response before dying (reference test_app.py:46-58
            # uses a timer for the same reason).
            threading.Timer(0.2, os._exit, args=(code,)).start()
        else:
            self._json({"error": "not found"}, code=404)


def main() -> None:
    host, port = _own_address()
    server = ThreadingHTTPServer((host, port), Handler)
    # Startup log always uses the cheap env parse: the TF-observed view
    # (TEST_SERVER_RUNCONFIG_TF) costs a ~20 s import and must not delay
    # the listen socket the e2e harness is polling for.
    print(
        f"[test-server] listening on {host}:{port} "
        f"runconfig={json.dumps(_runconfig(use_tf=False))}",
        flush=True,
    )
    server.serve_forever()


if __name__ == "__main__":
    sys.exit(main())
