"""A minimal kube-apiserver stub for exercising KubeCluster.

Translates the REST surface the operator uses — CRD jobs, core
pods/services/events, volcano PodGroups, coordination Leases, streaming
watches (cluster- and namespace-scoped, labelSelector-filtered) — onto an
InMemoryCluster, so the full operator stack can run over real HTTP
without a cluster. The analog of controller-runtime's envtest
(SURVEY.md §4 T2: real apiserver, no kubelet), minus etcd.
"""

from __future__ import annotations

import base64
import json
import queue
import re
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..api.k8s import Event, Pod, Service, from_dict, to_dict
from .. import api as api_pkg
from ..cluster.base import Conflict
from ..cluster.memory import InMemoryCluster
from ..manifests.schema_validate import SchemaError, validate_job_dict

_PLURAL_TO_KIND = {
    getattr(api_pkg, m).PLURAL: getattr(api_pkg, m).KIND
    for m in ("tfjob", "pytorchjob", "mxjob", "xgboostjob", "jaxjob")
}

_JOB_RE = re.compile(
    r"^/apis/kubeflow\.org/v1/namespaces/(?P<ns>[^/]+)/(?P<plural>[^/]+)"
    r"(?:/(?P<name>[^/]+))?(?P<status>/status)?$"
)
_JOB_ALL_RE = re.compile(r"^/apis/kubeflow\.org/v1/(?P<plural>[^/]+)$")
_CORE_RE = re.compile(
    r"^/api/v1/namespaces/(?P<ns>[^/]+)/(?P<resource>pods|services|events)"
    r"(?:/(?P<name>[^/]+))?(?P<log>/log)?$"
)
_CORE_ALL_RE = re.compile(r"^/api/v1/(?P<resource>pods|services|events)$")
_PG_RE = re.compile(
    r"^/apis/scheduling\.volcano\.sh/v1beta1/namespaces/(?P<ns>[^/]+)/podgroups"
    r"(?:/(?P<name>[^/]+))?$"
)
_PG_ALL_RE = re.compile(r"^/apis/scheduling\.volcano\.sh/v1beta1/podgroups$")
_LEASE_RE = re.compile(
    r"^/apis/coordination\.k8s\.io/v1/namespaces/(?P<ns>[^/]+)/leases"
    r"(?:/(?P<name>[^/]+))?$"
)


class StubApiServer:
    """HTTP facade over an InMemoryCluster. `mem` stays accessible so tests
    can simulate the kubelet (set_pod_phase) and inspect state."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 required_token: Optional[str] = None,
                 ssl_context=None):
        self.mem = InMemoryCluster()
        # Auth enforcement (None = accept anything): set/replace via
        # set_required_token to exercise bearer rotation — requests carrying
        # any other token get 401, like an apiserver after the bound SA
        # token expired.
        self._required_token = required_token
        self._auth_lock = threading.Lock()
        # ---- watch cache (the apiserver behaviors VERDICT r3 flagged as
        # never emitted by this stub): a per-collection ring of recent
        # events enables TRUE resourceVersion resume (no full ADDED replay),
        # in-stream 410 Expired when a client's rv predates the ring,
        # periodic BOOKMARK events, and chunked LIST with continue tokens.
        self._history_lock = threading.Lock()
        self._history: Dict[str, deque] = {}
        # rv horizon per collection: events at-or-below are compacted away.
        self._history_start: Dict[str, int] = {}
        self.watch_history_depth = 1024
        self.bookmark_interval: float = 30.0  # tests shrink this
        # continue tokens minted from a list snapshot older than this rv
        # answer 410 Expired (expire_continue_tokens test hook).
        self._continue_floor = 0
        # Consistent-list snapshots: a continue token pages over the EXACT
        # item list its first page saw (a real apiserver pages an etcd
        # snapshot at the token's rv; re-listing live state per page would
        # skip/duplicate items that move across a boundary mid-pagination).
        # Bounded: oldest snapshots evict, and an evicted token gets 410 —
        # also real behavior.
        self._list_snapshots: "dict" = {}
        self._snapshot_seq = 0
        # Request log (method, path, single-valued query) for conformance
        # assertions; bounded so long-lived stubs don't grow unboundedly.
        self.requests: deque = deque(maxlen=10000)
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Unbuffered wfile (the BaseHTTPRequestHandler default) makes
            # every status/header line its own TCP send; with Nagle +
            # delayed ACKs each response then costs ~40ms — which tripled
            # measured restart MTTR. Buffer responses; streaming paths
            # (watches, log follow) flush explicitly.
            wbufsize = -1
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                pass

            def _json(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length", "0"))
                return json.loads(self.rfile.read(length)) if length else {}

            def _dispatch(self, method: str) -> None:
                with stub._auth_lock:
                    required = stub._required_token
                if required is not None:
                    got = self.headers.get("Authorization", "")
                    if got != f"Bearer {required}":
                        return self._json(
                            401, {"kind": "Status", "code": 401,
                                  "message": "Unauthorized"}
                        )
                try:
                    stub._route(self, method)
                except SchemaError as exc:
                    # Real apiservers answer 422 Unprocessable Entity for
                    # schema violations on structurally-validated CRDs.
                    self._json(422, {"kind": "Status", "code": 422,
                                     "reason": "Invalid", "message": str(exc)})
                except Conflict as exc:
                    self._json(409, {"kind": "Status", "code": 409, "message": str(exc)})
                except KeyError:
                    self._json(404, {"kind": "Status", "code": 404})
                except Exception as exc:  # noqa: BLE001 — surface as 500
                    self._json(500, {"kind": "Status", "message": str(exc)})

            def do_GET(self):  # noqa: N802
                self._dispatch("GET")

            def do_POST(self):  # noqa: N802
                self._dispatch("POST")

            def do_PUT(self):  # noqa: N802
                self._dispatch("PUT")

            def do_PATCH(self):  # noqa: N802
                self._dispatch("PATCH")

            def do_DELETE(self):  # noqa: N802
                self._dispatch("DELETE")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        # Real-TLS tier: wrap the listener so the production client's ssl
        # context (CA verification, mTLS client certs) is exercised over a
        # genuine handshake — what a kind/real apiserver run would cover.
        self._tls = ssl_context is not None
        if ssl_context is not None:
            self.httpd.socket = ssl_context.wrap_socket(
                self.httpd.socket, server_side=True
            )
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        scheme = "https" if self._tls else "http"
        return f"{scheme}://127.0.0.1:{self.port}"

    def set_required_token(self, token: Optional[str]) -> None:
        """Rotate the accepted bearer token (None disables auth)."""
        with self._auth_lock:
            self._required_token = token

    def shutdown(self) -> None:
        self.httpd.shutdown()

    # --------------------------------------------------------- watch cache
    # One rv parser for the whole stack — a second copy here would drift.
    _rv_of = staticmethod(InMemoryCluster._event_rv)

    def _ensure_history(self, collection: str) -> None:
        """Subscribe a ring-buffer appender for `collection` (a job kind,
        "pods", or "services") on first use. Events before the subscription
        are unavailable — a resume below the horizon gets 410, exactly a
        real apiserver's watch-cache semantics."""
        def appender(etype, obj):
            rv = self._rv_of(obj)
            with self._history_lock:
                dq = self._history[collection]
                if dq.maxlen and len(dq) == dq.maxlen:
                    # Ring rollover = compaction: advance the horizon past
                    # the event about to fall off.
                    self._history_start[collection] = max(
                        self._history_start[collection], dq[0][0]
                    )
                dq.append((rv, etype, obj))

        # One critical section for membership check, ring creation, horizon
        # read, and subscription — all under the mem write lock so no event
        # can commit in between (a commit in a gap would be in neither the
        # ring nor below the horizon: silently lost to resumers instead of
        # 410'd). Membership and horizon land under the SAME _history_lock
        # hold, so a racing second caller either sees both or neither —
        # never a ring whose horizon still reads 0. Lock order is
        # mem._lock -> _history_lock everywhere; no path holds
        # _history_lock while acquiring mem._lock.
        with self.mem._lock:
            with self._history_lock:
                if collection in self._history:
                    return
                self._history[collection] = deque(
                    maxlen=self.watch_history_depth)
                self._history_start[collection] = self.mem.latest_rv()
            self.mem.watch(collection, appender)

    def compact_watch_cache(self) -> None:
        """Test hook: drop all buffered watch history and expire every
        outstanding continue token — the storm a real apiserver produces
        after etcd compaction. Every in-flight resume/continue gets 410."""
        now = self.mem.latest_rv()
        with self._history_lock:
            for collection, dq in self._history.items():
                dq.clear()
                self._history_start[collection] = now
            self._continue_floor = now
            self._list_snapshots.clear()

    def expire_continue_tokens(self) -> None:
        """Test hook: 410 any continue token minted before this call."""
        with self._history_lock:
            self._continue_floor = self.mem.latest_rv()
            # Drop the pinned snapshots too: a token minted at exactly the
            # current rv passes the floor comparison (rv granularity cannot
            # distinguish "minted before" from "minted after" without a
            # write in between), but its snapshot being gone still 410s it
            # — matching the docstring's contract for every outstanding
            # token. New lists mint fresh snapshot ids.
            self._list_snapshots.clear()

    # ------------------------------------------------------------- routing
    def _route(self, handler, method: str) -> None:
        parsed = urlparse(handler.path)
        path, q = parsed.path, parse_qs(parsed.query)
        self.requests.append((method, path, {k: v[0] for k, v in q.items()}))
        watching = q.get("watch", ["false"])[0] == "true"
        labels = _selector(q)

        m = _JOB_RE.match(path)
        if m:
            return self._jobs(handler, method, m, watching, q)
        m = _JOB_ALL_RE.match(path)
        if m and method == "GET":
            kind = _PLURAL_TO_KIND[m["plural"]]
            return self._jobs_collection(handler, kind, watching, ns=None, q=q)
        m = _CORE_RE.match(path)
        if m:
            if method == "GET" and not m["name"] and m["resource"] in ("pods", "services"):
                return self._core_collection(
                    handler, m["resource"], watching, ns=m["ns"], labels=labels, q=q
                )
            return self._core(handler, method, m, q)
        m = _CORE_ALL_RE.match(path)
        if m:
            if m["resource"] == "events":
                return self._events_list(handler, q)
            return self._core_collection(
                handler, m["resource"], watching, ns=None, labels=labels, q=q
            )
        m = _PG_RE.match(path)
        if m:
            return self._podgroups(handler, method, m, labels=labels)
        if _PG_ALL_RE.match(path) and method == "GET":
            # Cluster-scoped listing (list_pod_groups with no namespace).
            return handler._json(
                200, {"items": self.mem.list_pod_groups(None, labels)}
            )
        m = _LEASE_RE.match(path)
        if m:
            return self._leases(handler, method, m, labels=labels)
        raise KeyError(path)

    def _jobs(self, handler, method, m, watching, q) -> None:
        kind = _PLURAL_TO_KIND[m["plural"]]
        ns, name = m["ns"], m["name"]
        if method == "GET" and not name:
            return self._jobs_collection(handler, kind, watching, ns=ns, q=q)
        if method == "GET":
            return handler._json(200, self.mem.get_job(kind, ns, name))
        if method == "POST":
            body = handler._body()
            validate_job_dict(body)
            # Status-subresource semantics: a main-resource write never
            # persists client-supplied .status (a re-applied exported CR
            # must not seed a stale Succeeded no controller wrote).
            body.pop("status", None)
            return handler._json(201, self.mem.create_job(body))
        if method == "PUT" and m["status"]:
            # Status subresource PUT: replace status, ignore spec changes.
            status = handler._body().get("status", {})
            return handler._json(200, self.mem.update_job_status(kind, ns, name, status))
        if method == "PUT":
            body = handler._body()
            validate_job_dict(body)
            # Status-subresource semantics on update (client-supplied
            # .status ignored) are enforced by mem.update_job itself.
            return handler._json(200, self.mem.update_job(body))
        if method == "PATCH" and m["status"]:
            # Merge-patch semantics: a null value deletes the key (the
            # coalescing writer nulls cleared optional fields explicitly,
            # KubeCluster.patch_job_status), everything else lands as
            # sent. Routed to the store's patch verb so the single-request
            # cost model matches a real apiserver's.
            status = {
                k: v
                for k, v in (handler._body().get("status") or {}).items()
                if v is not None
            }
            return handler._json(200, self.mem.patch_job_status(kind, ns, name, status))
        if method == "DELETE":
            self.mem.delete_job(kind, ns, name)
            return handler._json(200, {})
        raise KeyError(method)

    def _core(self, handler, method, m, q) -> None:
        ns, resource, name = m["ns"], m["resource"], m["name"]
        if resource == "pods":
            if method == "GET" and name and m["log"]:
                if q.get("follow", ["false"])[0] == "true":
                    return self._stream_log(handler, ns, name)
                log = self.mem.get_pod_log(ns, name)
                body = log.encode()
                handler.send_response(200)
                handler.send_header("Content-Length", str(len(body)))
                handler.end_headers()
                handler.wfile.write(body)
                return
            if method == "GET" and name:
                return handler._json(200, to_dict(self.mem.get_pod(ns, name)))
            if method == "POST":
                pod = from_dict(Pod, handler._body())
                return handler._json(201, to_dict(self.mem.create_pod(pod)))
            if method == "PUT":
                pod = from_dict(Pod, handler._body())
                return handler._json(200, to_dict(self.mem.update_pod(pod)))
            if method == "DELETE":
                # DeleteOptions-as-query-params: gracePeriodSeconds=0 is
                # the force-delete wire form KubeCluster emits.
                force = q.get("gracePeriodSeconds", [None])[0] == "0"
                self.mem.delete_pod(ns, name, force=force)
                return handler._json(200, {})
        if resource == "services":
            if method == "GET" and name:
                return handler._json(200, to_dict(self.mem.get_service(ns, name)))
            if method == "POST":
                svc = from_dict(Service, handler._body())
                return handler._json(201, to_dict(self.mem.create_service(svc)))
            if method == "PUT":
                svc = from_dict(Service, handler._body())
                return handler._json(200, to_dict(self.mem.update_service(svc)))
            if method == "DELETE":
                self.mem.delete_service(ns, name)
                return handler._json(200, {})
        if resource == "events":
            if method == "POST":
                body = handler._body()
                inv = body.get("involvedObject", {})
                self.mem.record_event(Event(
                    type=body.get("type", ""), reason=body.get("reason", ""),
                    message=body.get("message", ""),
                    involved_object=f"{inv.get('kind')}/{inv.get('namespace')}/{inv.get('name')}",
                ))
                return handler._json(201, {})
            if method == "GET":
                return self._events_list(handler, q, ns=ns)
        raise KeyError(resource)

    def _stream_log(self, handler, ns: str, name: str) -> None:
        """`pods/log?follow=true`: chunked streaming over the backend's own
        follow generator (single-sourced semantics — growth tracking,
        terminal flush, replacement-pod cutoff all live in
        Cluster.stream_pod_log). A client hangup is noticed at the next
        chunk write, like a real apiserver's log stream."""
        handler.send_response(200)
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()
        handler.wfile.flush()  # quiet pod: headers must not sit in the buffer
        try:
            for text in self.mem.stream_pod_log(ns, name, follow=True,
                                                poll_interval=0.05):
                data = text.encode()
                handler.wfile.write(
                    f"{len(data):x}\r\n".encode() + data + b"\r\n")
                handler.wfile.flush()
        except Exception:  # noqa: BLE001 — client hung up / pod vanished
            pass
        finally:
            try:
                handler.wfile.write(b"0\r\n\r\n")
                handler.wfile.flush()
            except Exception:  # noqa: BLE001 — client hung up
                pass

    def _events_list(self, handler, q, ns: Optional[str] = None) -> None:
        # fieldSelector narrowing (involvedObject.kind/name), the server-side
        # filter KubeCluster.list_events relies on.
        selector = {}
        raw = q.get("fieldSelector", [None])[0]
        if raw:
            for part in raw.split(","):
                k, _, v = part.partition("=")
                selector[k] = v
        items = []
        for e in self.mem.list_events():
            kind, namespace, name = (e.involved_object.split("/") + ["", "", ""])[:3]
            if ns and namespace != ns:
                continue
            if selector.get("involvedObject.kind") not in (None, kind):
                continue
            if selector.get("involvedObject.name") not in (None, name):
                continue
            items.append({
                "type": e.type, "reason": e.reason, "message": e.message,
                "involvedObject": {"kind": kind, "namespace": namespace, "name": name},
            })
        handler._json(200, {"items": items})

    def _podgroups(self, handler, method, m, labels=None) -> None:
        ns, name = m["ns"], m["name"]
        if method == "POST":
            return handler._json(201, self.mem.create_pod_group(handler._body()))
        if method == "GET" and not name:
            return handler._json(
                200, {"items": self.mem.list_pod_groups(ns, labels)}
            )
        if method == "GET":
            return handler._json(200, self.mem.get_pod_group(ns, name))
        if method == "DELETE":
            self.mem.delete_pod_group(ns, name)
            return handler._json(200, {})
        raise KeyError(method)

    def _leases(self, handler, method, m, labels=None) -> None:
        ns, name = m["ns"], m["name"]
        if method == "GET" and not name:
            # Collection list (the shard coordinator's member discovery).
            # labelSelector is honored SERVER-side: the response must not
            # scale with the fleet-wide lease count (per-job heartbeat
            # leases share this namespace) when the client selects on the
            # member-lease label.
            return handler._json(
                200, {"items": self.mem.list_leases(ns, labels=labels)}
            )
        if method == "GET":
            return handler._json(200, self.mem.get_lease(ns, name))
        if method == "POST":
            return handler._json(201, self.mem.create_lease(handler._body()))
        if method == "PUT":
            return handler._json(200, self.mem.update_lease(handler._body()))
        if method == "DELETE":
            self.mem.delete_lease(ns, name)
            return handler._json(200, {})
        raise KeyError(method)

    # -------------------------------------------------------------- watches
    def _jobs_collection(self, handler, kind: str, watching: bool,
                         ns: Optional[str], q: dict) -> None:
        def keep(obj: dict) -> bool:
            meta = obj.get("metadata") or {}
            return ns is None or meta.get("namespace", "default") == ns

        self._serve(
            handler, kind, lambda: self.mem.list_jobs(kind, ns),
            lambda o: o, keep, watching, q,
        )

    def _core_collection(self, handler, resource: str, watching: bool,
                         ns: Optional[str], labels: Optional[dict],
                         q: dict) -> None:
        lister = self.mem.list_pods if resource == "pods" else self.mem.list_services

        def keep(obj) -> bool:
            if ns is not None and obj.metadata.namespace != ns:
                return False
            if labels and any(
                obj.metadata.labels.get(k) != v for k, v in labels.items()
            ):
                return False
            return True

        self._serve(
            handler, resource,
            lambda: [to_dict(o) for o in lister(ns, labels=labels)],
            to_dict, keep, watching, q,
        )

    def _serve(self, handler, kind, items_fn, convert, keep, watching,
               q: dict) -> None:
        # Start buffering on LIST, not first watch: the reflector pattern
        # is list(rv=L) then watch(resourceVersion=L), and a history ring
        # born after the list (global rv moved past L in between) would
        # 410 that very first resume.
        self._ensure_history(kind)
        if not watching:
            return self._list(handler, items_fn, q)
        return self._watch_stream(handler, kind, items_fn, convert, keep, q)

    def _list(self, handler, items_fn, q: dict) -> None:
        """LIST with apiserver pagination semantics: `limit` returns one
        page plus an opaque `continue` token; a token minted before the
        continue-floor (compaction) answers 410 Expired, which a reflector
        handles by restarting the list from scratch."""
        limit = int(q.get("limit", ["0"])[0] or 0)
        cont = q.get("continue", [None])[0]
        expired = {
            "kind": "Status", "code": 410, "reason": "Expired",
            "message": "The provided continue parameter is too old to "
                       "display a consistent list"}
        if cont:
            try:
                tok = json.loads(base64.urlsafe_b64decode(cont.encode()).decode())
                offset, rv, sid = int(tok["o"]), str(tok["rv"]), tok["sid"]
            except Exception:
                return handler._json(
                    400, {"kind": "Status", "code": 400,
                          "message": "invalid continue token"})
            with self._history_lock:
                floor = self._continue_floor
                snapshot = self._list_snapshots.get(sid)
            if int(rv) < floor or snapshot is None:
                # Compacted or evicted: the consistent snapshot is gone.
                return handler._json(410, expired)
            items = snapshot
        else:
            # First page: pin the sorted item list so every continue pages
            # the same consistent snapshot regardless of concurrent writes.
            # rv is read BEFORE the snapshot: advertising an rv that
            # postdates the items would let a resumed watch skip the
            # in-between event forever; an rv slightly older than the
            # items only costs a duplicate replay the informer dedups.
            rv = str(self.mem.latest_rv())
            items = items_fn()
            items.sort(key=lambda o: (
                (o.get("metadata") or {}).get("namespace", ""),
                (o.get("metadata") or {}).get("name", "")))
            offset = 0
            sid = None
            if limit and limit < len(items):
                with self._history_lock:
                    self._snapshot_seq += 1
                    sid = f"s{self._snapshot_seq}"
                    self._list_snapshots[sid] = items
                    while len(self._list_snapshots) > 32:
                        self._list_snapshots.pop(
                            next(iter(self._list_snapshots)))
        meta = {"resourceVersion": rv}
        page = items[offset:offset + limit] if limit else items[offset:]
        if limit and offset + limit < len(items):
            meta["continue"] = base64.urlsafe_b64encode(
                json.dumps({"o": offset + limit, "rv": rv,
                            "sid": sid}).encode()
            ).decode()
            meta["remainingItemCount"] = len(items) - offset - limit
        handler._json(200, {"items": page, "metadata": meta})

    def _watch_stream(self, handler, kind, items_fn, convert, keep,
                      q: dict) -> None:
        """One streaming watch. Without a resourceVersion the current state
        replays as synthetic ADDED (subscribe FIRST, then list — an object
        created in between appears in both and the client's informer dedups
        by rv). WITH a resourceVersion the stream resumes from the watch
        cache: only buffered events newer than the client's rv replay, or
        an in-stream 410 Expired Status if the rv predates the ring —
        exactly a real apiserver's watch-cache contract. BOOKMARK events
        carry the storage rv forward on quiet streams; `timeoutSeconds`
        closes the stream cleanly (client resumes from its last rv).

        The `dead` flag neuters the subscription after disconnect:
        InMemoryCluster has no unsubscribe, and a leaked live queue would
        grow forever."""
        client_rv_raw = q.get("resourceVersion", [""])[0]
        bookmarks = q.get("allowWatchBookmarks", ["false"])[0] == "true"
        timeout_s = float(q.get("timeoutSeconds", ["0"])[0] or 0)
        resume = client_rv_raw not in ("", "0")

        events: "queue.Queue" = queue.Queue()
        dead = threading.Event()

        def relay(etype, obj):
            if not dead.is_set():
                events.put((etype, obj))

        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()
        # Buffered wfile (wbufsize=-1): push the headers out NOW — a watch
        # on an empty collection blocks before its first chunk, and the
        # client would otherwise sit in getresponse() with nothing on the
        # wire until the first event.
        handler.wfile.flush()

        def send(payload: dict) -> None:
            line = (json.dumps(payload) + "\n").encode()
            handler.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            handler.wfile.flush()

        def close_stream() -> None:
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()

        replay: List[Tuple[str, object]] = []
        floor = 0
        try:
            if resume:
                try:
                    client_rv = int(client_rv_raw)
                except ValueError:
                    send({"type": "ERROR", "object": {
                        "kind": "Status", "code": 400,
                        "message": f"invalid resourceVersion {client_rv_raw!r}"}})
                    return close_stream()
                self._ensure_history(kind)
                self.mem.watch(kind, relay)  # subscribe before reading history
                with self._history_lock:
                    start = self._history_start.get(kind, 0)
                    if client_rv < start:
                        backlog = None  # compacted away: too old
                    else:
                        backlog = [e for e in self._history[kind]
                                   if e[0] > client_rv]
                if backlog is None:
                    # In-stream 410: real apiservers deliver rv expiry as an
                    # ERROR Status object on an established stream.
                    send({"type": "ERROR", "object": {
                        "kind": "Status", "apiVersion": "v1", "code": 410,
                        "reason": "Expired",
                        "message": f"too old resource version: "
                                   f"{client_rv} ({start})"}})
                    return close_stream()
                # keep() filters raw objects (typed for core collections);
                # floor tracks ALL backlog rvs, filtered or not, so queued
                # duplicates of filtered events are dropped too.
                replay = [(etype, obj) for (_, etype, obj) in backlog
                          if keep(obj)]
                floor = max((rv for rv, _, _ in backlog), default=client_rv)
            else:
                self._ensure_history(kind)
                self.mem.watch(kind, relay)
                # items_fn is already namespace/label-filtered; no keep().
                snapshot = items_fn()
                replay = [("ADDED", s) for s in snapshot]
                # Anything the queue already holds at-or-below the snapshot
                # max is reflected in the snapshot itself.
                floor = max((self._rv_of(s) for s in snapshot), default=0)

            for etype, obj in replay:
                body = obj if isinstance(obj, dict) else convert(obj)
                send({"type": etype, "object": body})

            deadline = time.monotonic() + timeout_s if timeout_s else None
            next_bookmark = time.monotonic() + self.bookmark_interval
            while True:
                now = time.monotonic()
                wait = next_bookmark - now if bookmarks else 3600.0
                if deadline is not None:
                    wait = min(wait, deadline - now)
                # Watermark read BEFORE the blocking get: an event fully
                # dispatched (and so counted by delivered_rv) before this
                # point is already in our queue, so an Empty get proves
                # everything at-or-below `wm` was sent on this stream —
                # the bookmark contract. Reading the watermark after the
                # Empty would race an event enqueued in between, putting
                # BOOKMARK(rv) ahead of event rv on the wire and letting a
                # resume-at-bookmark skip it. (latest_rv is never safe
                # here: it can be ahead of an event still in the publish
                # log.)
                wm = self.mem.delivered_rv()
                try:
                    etype, obj = events.get(timeout=max(wait, 0.0))
                except queue.Empty:
                    now = time.monotonic()
                    if deadline is not None and now >= deadline:
                        return close_stream()  # clean close: client resumes
                    if bookmarks and now >= next_bookmark:
                        send({"type": "BOOKMARK", "object": {
                            "kind": kind, "metadata": {
                                "resourceVersion": str(wm)}}})
                        next_bookmark = now + self.bookmark_interval
                    continue
                rv = self._rv_of(obj)
                if rv and rv <= floor:
                    continue  # already covered by the replay
                if not keep(obj):
                    continue
                body = obj if isinstance(obj, dict) else convert(obj)
                send({"type": etype, "object": body})
        except (BrokenPipeError, ConnectionResetError, OSError):
            return
        finally:
            dead.set()


def _selector(q) -> Optional[dict]:
    raw = q.get("labelSelector", [None])[0]
    if not raw:
        return None
    out = {}
    for part in raw.split(","):
        k, _, v = part.partition("=")
        out[k] = v
    return out
