"""Fleet digital twin: a trace-driven discrete-event simulator that runs
the REAL control plane — admission arbiter, gang autoscaler, shard
coordinator, workqueue, expectations, tracer — over the in-memory
cluster at 100k-job / 1k-tenant scale with zero wall-clock sleeps.

The whole design rests on one property the repo built deliberately:
every decision maker is a pure function of an injected clock plus an
immutable snapshot (core/policies.py, core/autoscaler.py decide(),
core/sharding.py, WorkQueue timers, expectations). So the simulator
owns ONE virtual clock (:class:`SimClock`), threads it into every
clock-accepting component, and advances it event by event — a year of
diurnal waves replays in seconds, and the same seed replays the same
trace, the same decision logs, and the same fault log byte-for-byte.

Layers:

- :class:`SimClock` + :func:`audit_sim_clocks` — the virtual-clock
  contract. The audit walks every sim-hosted component and asserts its
  clock attribute IS the sim clock object; a component that silently
  fell back to ``time.time`` fails loudly before the run starts.
- :func:`generate_trace` — seeded workload-trace generator producing
  tenant mixes (diurnal, bursty, mixed-generation, preemption-heavy,
  serving-trough backfill) as a list of :class:`JobArrival` records.
- :class:`Scenario` — the JSON-round-trippable scenario DSL: trace
  parameters, capacity/quota/policy/autoscaler config, and a storm
  layer composing the existing fault levers (capacity revocation,
  slice preemption, lease steals/renew delays, crash points, restore
  faults) into named fleet storms.
- :class:`FleetSim` — the engine: a heapq event loop (arrivals,
  modeled step progress feeding heartbeat ``tokens_per_sec`` /
  checkpoint riders, fault firings, periodic admission resyncs,
  autoscaler + shard-coordinator ticks) with ``testing/invariants.py``
  sweeps plus the new fleet-level invariants between epochs.

Surfaced as ``scripts/measure_control_plane.py --mode fleet-sim`` with
the smoke gate ratcheted via ``build/fleetsim_smoke_last.json``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import json
import os
import random
import time
from dataclasses import dataclass, field
from fractions import Fraction
from typing import Callable, Dict, List, Optional, Tuple

from ..core import constants

# ----------------------------------------------------------------- clock


class ClockAuditError(AssertionError):
    """A sim-hosted component is not running on the sim clock."""


class SimClock:
    """The single virtual clock of a fleet simulation. Callable (every
    component in this repo takes ``clock=`` as a zero-arg callable) and
    monotone: events may only advance it. One instance serves as both
    the wall-style clock (``time.time`` slots) and the monotonic clock
    (``time.monotonic`` slots) — in virtual time they are the same
    axis, which is exactly what makes replays exact."""

    __slots__ = ("_now",)

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        if t < self._now - 1e-9:
            raise ValueError(
                f"virtual clock may not rewind: {t} < {self._now}"
            )
        self._now = max(self._now, float(t))


#: Attribute names under which this repo's components store their
#: injected clocks (core/admission.py ``clock``, WorkQueue/expectations
#: ``_clock``, sharding/leaderelection ``_clock``+``_mono``, …).
_CLOCK_ATTRS = ("clock", "_clock", "_mono")


def audit_sim_clocks(clock, components: Dict[str, object]) -> None:
    """Assert every component's clock attribute IS `clock` (object
    identity, not equality — a lambda wrapping ``time.time`` would
    compare unequal anyway, but identity also rejects a *copy* of the
    sim clock, which would silently stop advancing). Raises
    :class:`ClockAuditError` naming every offender, so a refactor that
    re-defaults one constructor to the wall clock fails the whole
    fleet tier loudly instead of corrupting timers quietly."""
    failures: List[str] = []
    for name, obj in sorted(components.items()):
        found = False
        for attr in _CLOCK_ATTRS:
            probe = obj.__dict__.get(attr) if hasattr(obj, "__dict__") else None
            if probe is None:
                continue
            found = True
            if probe is not clock:
                failures.append(
                    f"{name}.{attr} is not the sim clock "
                    f"({getattr(probe, '__name__', type(probe).__name__)}"
                    " — wall-clock fallback)"
                )
        if not found:
            failures.append(f"{name}: no injected clock attribute found")
    if failures:
        raise ClockAuditError(
            "clock-injection audit failed:\n  " + "\n  ".join(failures)
        )


# ----------------------------------------------------------- trace layer

PROFILES = (
    "diurnal",
    "bursty",
    "mixed-generation",
    "preemption-heavy",
    "serving-trough",
)


@dataclass(frozen=True)
class JobArrival:
    """One job in the workload trace. Everything downstream (manifest,
    admission demand, completion model) derives from these fields, so
    the trace line is the replay artifact for the arrival layer."""

    t: float
    name: str
    namespace: str
    workers: int
    work_seconds: float
    priority: str = ""
    throughput_ratios: Dict[str, float] = field(default_factory=dict)
    elastic: bool = False
    num_slices: int = 1
    min_slices: int = 1
    max_slices: int = 4

    def line(self) -> str:
        return json.dumps(
            dataclasses.asdict(self), sort_keys=True, separators=(",", ":")
        )


def _tenant(rng: random.Random, tenants: int) -> str:
    """Zipf-flavored tenant pick: a few namespaces dominate (the real
    multi-tenant shape), the long tail stays busy."""
    r = rng.random()
    skew = int(tenants * (r ** 2.2))
    return f"tenant-{min(skew, tenants - 1):04d}"


def _diurnal_accept(rng: random.Random, t: float, horizon: float) -> bool:
    import math

    period = max(horizon / 3.0, 1.0)  # three "days" per run
    rate = 0.5 + 0.5 * (0.5 * (1 + math.sin(2 * math.pi * t / period)))
    return rng.random() < rate


def generate_trace(scenario: "Scenario") -> List[JobArrival]:
    """The seeded workload-trace generator. Pure function of the
    scenario (all entropy from ``random.Random(seed)``): same scenario,
    same bytes — the foundation of the 3-run replay gate."""
    sc = scenario
    rng = random.Random(sc.seed)
    arrivals: List[JobArrival] = []
    elastic_budget = sc.elastic_jobs
    sizes = (1, 1, 2, 2, 2, 4, 4, 8)

    def arrival_time(i: int) -> float:
        if sc.profile == "diurnal" or sc.profile == "serving-trough":
            while True:
                t = rng.random() * sc.horizon
                if _diurnal_accept(rng, t, sc.horizon):
                    return t
        if sc.profile == "bursty":
            # 1-in-3 jobs ride a burst: a handful of storm instants
            # each concentrating a wave of near-simultaneous arrivals.
            if rng.random() < 0.34:
                burst = rng.randrange(max(1, sc.jobs // 64))
                center = (burst + 0.5) * sc.horizon / max(
                    1, sc.jobs // 64)
                return min(sc.horizon, center + rng.random() * 5.0)
            return rng.random() * sc.horizon
        if sc.profile == "preemption-heavy":
            # Low-band carpet early, high-band storm in the middle
            # third — the arbiter must preempt its way through it.
            if i % 3 == 0:
                return sc.horizon * (0.33 + 0.34 * rng.random())
            return rng.random() * sc.horizon * 0.9
        return rng.random() * sc.horizon

    for i in range(sc.jobs):
        t = arrival_time(i)
        ns = _tenant(rng, sc.tenants)
        workers = rng.choice(sizes)
        work = 30.0 + rng.random() * 270.0
        priority = ""
        ratios: Dict[str, float] = {}
        elastic = False
        num_slices = 1
        if sc.profile == "preemption-heavy":
            priority = "high" if i % 3 == 0 else "low"
        elif sc.profile == "serving-trough":
            # Serving gangs: high-band, long-lived, diurnal; batch
            # training backfills the troughs at the default band.
            if i % 4 == 0:
                priority = "high"
                work = 120.0 + rng.random() * 240.0
            else:
                priority = "low"
        elif sc.profile == "mixed-generation":
            gens = sorted(sc.generations) or ["v4", "v5e"]
            ratios = {
                gen: round(0.5 + 0.5 * rng.random(), 3) for gen in gens
            }
            ratios[gens[i % len(gens)]] = 1.0
        if elastic_budget > 0 and i % max(1, sc.jobs // max(
                1, sc.elastic_jobs)) == 0:
            elastic_budget -= 1
            elastic = True
            num_slices = rng.choice((1, 2))
            workers = num_slices * sc.hosts_per_slice
            work = 120.0 + rng.random() * 240.0
        arrivals.append(JobArrival(
            t=round(t, 3),
            name=f"fleet-{i:06d}",
            namespace=ns,
            workers=workers,
            work_seconds=round(work, 3),
            priority=priority,
            throughput_ratios=ratios,
            elastic=elastic,
            num_slices=num_slices,
            min_slices=1,
            max_slices=4,
        ))
    arrivals.sort(key=lambda a: (a.t, a.name))
    return arrivals


# -------------------------------------------------------- scenario layer


@dataclass
class StormEvent:
    """One virtual-time-keyed storm firing. Counter-keyed levers (lease
    steals, renew delays, crash points, restore faults) live in the
    scenario's chaos plan instead — they key on deterministic call
    counters, the contract chaos.py already guarantees."""

    t: float
    kind: str  # revoke-capacity | preempt-slice | freeze-heartbeats | thaw-heartbeats
    capacity: Optional[Dict[str, str]] = None
    slice_index: int = 0
    name_contains: str = ""


STORM_KINDS = (
    "revoke-capacity", "preempt-slice", "freeze-heartbeats",
    "thaw-heartbeats",
)


@dataclass
class Scenario:
    """The fleet-storm DSL: everything a run depends on, JSON-round-
    trippable (``--scenario file.json``). ``from_dict(to_dict(s)) == s``
    is a regression test — a field that doesn't survive the round trip
    silently forks checked-in corpus scenarios from their replays."""

    name: str
    seed: int = 0
    profile: str = "bursty"
    jobs: int = 200
    tenants: int = 8
    horizon: float = 3600.0
    capacity_pods: int = 64
    generations: Dict[str, Dict[str, str]] = field(default_factory=dict)
    policy: str = "priority"
    quotas: Dict[str, Dict[str, str]] = field(default_factory=dict)
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    backfill_max_members: int = 8
    aging_seconds: float = 600.0
    autoscaler: bool = False
    autoscaler_config: Dict[str, float] = field(default_factory=dict)
    # Checkpoint-free elastic warm start (EngineOptions.warm_start): the
    # autoscaler's grows charge the WARM restart penalty
    # (warm_start_restore_seconds — peer pull, no storage round-trip)
    # instead of the cold one (grow_restore_seconds). Both penalties
    # default 0.0 and the flag defaults False, so every pre-existing
    # corpus scenario replays byte-identically (from_dict would reject
    # the fields if they weren't declared; defaults make them no-ops).
    warm_start: bool = False
    # Incremental admissibility index (EngineOptions.admission_index):
    # ON, the arbiter's pumps are O(newly-fittable) — provably schedule-
    # equivalent, so a scenario's digest must NOT change with the flag
    # (the smoke gate asserts exactly that). Default OFF keeps every
    # pre-existing corpus scenario on the full-scan path byte-
    # identically.
    admission_index: bool = False
    grow_restore_seconds: float = 0.0
    warm_start_restore_seconds: float = 0.0
    elastic_jobs: int = 0
    hosts_per_slice: int = 2
    shards: int = 1
    storm: List[StormEvent] = field(default_factory=list)
    lease_steals: List[Dict] = field(default_factory=list)
    renew_delays: List[Dict] = field(default_factory=list)
    crash_points: List[Dict] = field(default_factory=list)
    restore_faults: List[Dict] = field(default_factory=list)
    # Engine cadence (virtual seconds).
    resync_period: float = 60.0
    autoscaler_tick: float = 15.0
    coordinator_tick: float = 10.0
    heartbeat_period: float = 10.0
    epoch_seconds: float = 600.0

    def __post_init__(self):
        if self.profile not in PROFILES:
            raise ValueError(
                f"unknown trace profile {self.profile!r} "
                f"(known: {', '.join(PROFILES)})"
            )
        for ev in self.storm:
            if ev.kind not in STORM_KINDS:
                raise ValueError(
                    f"unknown storm kind {ev.kind!r} "
                    f"(known: {', '.join(STORM_KINDS)})"
                )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "Scenario":
        data = dict(data)
        data["storm"] = [
            StormEvent(**ev) for ev in data.get("storm") or []
        ]
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(data) - known)
        if unknown:
            raise ValueError(f"unknown scenario fields: {unknown}")
        return cls(**data)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, indent=2) + "\n"

    @classmethod
    def from_json(cls, text: str) -> "Scenario":
        return cls.from_dict(json.loads(text))


#: Checked-in storm corpus directory (tf_operator_tpu/testing/scenarios).
SCENARIO_DIR = os.path.join(os.path.dirname(__file__), "scenarios")


def load_scenario(path: str) -> Scenario:
    with open(path) as f:
        return Scenario.from_json(f.read())


def named_scenarios() -> List[str]:
    """The corpus, sorted — stable iteration order for the replay tier."""
    if not os.path.isdir(SCENARIO_DIR):
        return []
    return sorted(
        os.path.splitext(p)[0]
        for p in os.listdir(SCENARIO_DIR)
        if p.endswith(".json")
    )


def load_named(name: str) -> Scenario:
    return load_scenario(os.path.join(SCENARIO_DIR, f"{name}.json"))


# --------------------------------------------------------------- engine


@dataclass
class _SimJob:
    """Sim-side job state: the workload model's view (accrued work,
    completion-event versioning) beside what lives in the cluster."""

    arrival: JobArrival
    key: str
    uid: str = ""
    phase: str = "queued"  # queued | running | completed
    workers: int = 0
    num_slices: int = 1
    done: float = 0.0        # accrued work-seconds
    ran_since: Optional[float] = None
    queued_since: float = 0.0
    completion_version: int = 0
    ckpt_step: int = 0
    preemptions: int = 0
    completed_at: Optional[float] = None
    disruptions: int = 0
    slice_restarts: int = 0
    # Live pods the sim itself created, replica index -> pod name, in
    # creation order (mirrors the backend's insertion order). The sim is
    # the only pod writer, so this ledger replaces per-sync list_pods
    # round-trips — which deep-copy every pod and dominated the wall
    # clock at 100k jobs.
    live: Dict[int, str] = field(default_factory=dict)


class FleetSim:
    """The discrete-event engine. Single-threaded by construction: the
    heap orders everything, components are called inline, and the only
    concurrency the real stack's locks ever see is re-entrant kicks —
    so per-method chaos call indices are a pure function of the event
    sequence and every run replays byte-identically from its seed."""

    def __init__(self, scenario: Scenario):
        from ..cluster.chaos import (
            ChaosCluster, ChaosSpec, CrashPoint, ScheduledLeaseSteal,
            ScheduledRenewDelay, ScheduledRestoreFault,
        )
        from ..cluster.memory import InMemoryCluster
        from ..cluster.watchcache import SharedWatchCache
        from ..core.admission import AdmissionController
        from ..core.autoscaler import AutoscalerConfig, GangAutoscaler
        from ..core.expectations import ControllerExpectations
        from ..core.sharding import ShardCoordinator
        from ..core.tracing import Tracer
        from ..core.workqueue import WorkQueue
        from ..metrics import Metrics

        self.scenario = scenario
        self.clock = SimClock()
        self.rng = random.Random(scenario.seed ^ 0x5EED)
        self.metrics = Metrics()
        self.tracer = Tracer(max_traces=64, max_spans=256, clock=self.clock)

        self.mem = InMemoryCluster(clock=self.clock)
        self.mem.set_schedulable_capacity(
            {"pods": str(scenario.capacity_pods)},
            generations={
                gen: dict(res) for gen, res in scenario.generations.items()
            } or None,
        )
        spec = ChaosSpec(
            seed=scenario.seed,
            lease_steals=tuple(
                ScheduledLeaseSteal(**d) for d in scenario.lease_steals
            ),
            renew_delays=tuple(
                ScheduledRenewDelay(**d) for d in scenario.renew_delays
            ),
            crash_points=tuple(
                CrashPoint(**d) for d in scenario.crash_points
            ),
            restore_faults=tuple(
                ScheduledRestoreFault(**d) for d in scenario.restore_faults
            ),
        )
        self.chaos = ChaosCluster(self.mem, spec)
        # Observation-only watch cache on the backend: the resident-
        # object hot-path column at fleet scale (the ChaosCluster pins
        # its own serving cache off; this one never serves reads).
        self.watch_cache = SharedWatchCache(
            self.mem, namespace=None, metrics=self.metrics)
        self.watch_cache.register_kind("JAXJob")

        self.admission = AdmissionController(
            quotas={ns: dict(q) for ns, q in scenario.quotas.items()} or None,
            backfill_max_members=scenario.backfill_max_members,
            aging_seconds=scenario.aging_seconds,
            clock=self.clock,
            metrics=self.metrics,
            capacity_fn=self.mem.schedulable_capacity,
            generations=scenario.generations or None,
            policy=scenario.policy,
            tenant_weights=scenario.tenant_weights or None,
            seed=scenario.seed,
            admission_index=scenario.admission_index,
            capacity_version_fn=self.mem.schedulable_capacity_version,
        )
        self.queue = WorkQueue(clock=self.clock)
        self.expectations = ControllerExpectations(clock=self.clock)
        self.autoscaler = None
        if scenario.autoscaler:
            cfg = AutoscalerConfig(seed=scenario.seed)
            for knob, value in scenario.autoscaler_config.items():
                if not hasattr(cfg, knob):
                    raise ValueError(f"unknown autoscaler knob {knob!r}")
                setattr(cfg, knob, value)
            if scenario.warm_start:
                cfg.warm_start = True
            self.autoscaler = GangAutoscaler(
                self.chaos, self.admission, cfg,
                clock=self.clock, metrics=self.metrics,
            )
        self.coordinator = None
        if scenario.shards > 1:
            self.coordinator = ShardCoordinator(
                self.chaos, shards=scenario.shards,
                identity="fleetsim-replica-0", namespace="fleet-sim",
                duration=30.0, clock=self.clock, mono=self.clock,
            )

        self._audit_clocks()

        self.trace = generate_trace(scenario)
        self.jobs: Dict[str, _SimJob] = {}
        # Non-terminal jobs only (arrival order). Periodic scans —
        # resync, storms, epoch sweeps — walk this instead of the
        # all-jobs dict, which keeps them O(live fleet) instead of
        # O(every job that ever arrived).
        self.active: Dict[str, _SimJob] = {}
        self._heap: List[tuple] = []
        self._seq = 0
        self._arrived = 0
        self._completed = 0
        self._preempt_marks = 0
        self._preempt_acks = 0
        self._admits_in_window = 0
        self._deferred_syncs = 0
        self._grows = 0
        self._warm_start_grows = 0
        self._sweeps = 0
        self._sweep_violations: List[str] = []
        self._util_area = 0.0
        self._running_pods = 0
        self._last_util_t = 0.0
        self._first_arrival_t: Optional[float] = None
        self._last_completion_t = 0.0
        self._frozen_slices: Dict[str, float] = {}
        self._resident_peak = 0
        self._resident_bytes_peak = 0
        self._per_tenant_done: Dict[str, int] = {}
        self._end_t = 0.0
        self.report: Optional[dict] = None

    # ------------------------------------------------------------ audit
    def _audit_clocks(self) -> None:
        components: Dict[str, object] = {
            "admission": self.admission,
            "workqueue": self.queue,
            "expectations": self.expectations,
            "tracer": self.tracer,
            "cluster": self.mem,
        }
        if self.autoscaler is not None:
            components["autoscaler"] = self.autoscaler
        if self.coordinator is not None:
            components["shard_coordinator"] = self.coordinator
        audit_sim_clocks(self.clock, components)

    # ------------------------------------------------------- event heap
    def _push(self, t: float, kind: str, data=None) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, kind, data))

    # --------------------------------------------------------- manifest
    def _manifest(self, a: JobArrival) -> dict:
        spec: dict = {
            "jaxReplicaSpecs": {
                "Worker": {
                    "replicas": a.workers,
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "fleetsim:1"}]}},
                }
            },
        }
        if a.elastic:
            spec["numSlices"] = a.num_slices
            spec["elastic"] = {
                "minSlices": a.min_slices, "maxSlices": a.max_slices,
            }
        sp: dict = {}
        if a.priority:
            sp["priorityClass"] = a.priority
        if a.throughput_ratios:
            sp["throughputRatios"] = dict(a.throughput_ratios)
        if sp:
            spec["runPolicy"] = {"schedulingPolicy": sp}
        return {
            "apiVersion": "kubeflow.org/v1",
            "kind": "JAXJob",
            "metadata": {"name": a.name, "namespace": a.namespace},
            "spec": spec,
        }

    # ----------------------------------------------------- progress math
    def _rate(self, job: _SimJob) -> float:
        """Work-seconds accrued per virtual second: rigid gangs run at
        1x; elastic gangs scale with their CURRENT world relative to
        the arrival-time world (what a resize buys)."""
        if not job.arrival.elastic:
            return 1.0
        base = max(1, job.arrival.workers)
        return max(1, job.workers) / base

    def _accrue(self, job: _SimJob) -> None:
        if job.phase == "running" and job.ran_since is not None:
            job.done += (self.clock.now - job.ran_since) * self._rate(job)
            job.ran_since = self.clock.now

    def _schedule_completion(self, job: _SimJob) -> None:
        job.completion_version += 1
        remaining = max(0.0, job.arrival.work_seconds - job.done)
        eta = self.clock.now + remaining / max(self._rate(job), 1e-9)
        self._push(eta, "complete", (job.key, job.completion_version))

    # ------------------------------------------------------- utilization
    def _note_util(self) -> None:
        now = self.clock.now
        self._util_area += self._running_pods * (now - self._last_util_t)
        self._last_util_t = now

    def _set_running_pods(self, delta: int) -> None:
        self._note_util()
        self._running_pods += delta

    # ------------------------------------------------------------- pods
    def _owner_ref(self, job: _SimJob):
        from ..api.k8s import OwnerReference

        return OwnerReference(
            api_version="kubeflow.org/v1", kind="JAXJob",
            name=job.arrival.name, uid=job.uid, controller=True,
        )

    def _make_pod(self, job: _SimJob, index: int):
        from ..api.k8s import Container, ObjectMeta, Pod, PodSpec

        a = job.arrival
        hosts = max(1, self.scenario.hosts_per_slice)
        labels = {
            constants.LABEL_GROUP_NAME: constants.GROUP_NAME,
            constants.LABEL_JOB_NAME: a.name,
            constants.LABEL_REPLICA_TYPE: "worker",
            constants.LABEL_REPLICA_INDEX: str(index),
        }
        if a.elastic:
            labels[constants.LABEL_SLICE_INDEX] = str(index // hosts)
        pod = Pod()
        pod.metadata = ObjectMeta(
            name=f"{a.name}-worker-{index}", namespace=a.namespace,
            labels=labels, owner_references=[self._owner_ref(job)],
        )
        pod.spec = PodSpec(containers=[Container(name="jax", image="fleetsim:1")])
        return pod

    def _reconcile_pods(self, job: _SimJob) -> None:
        """Create/delete pods so the live set matches the CURRENT world
        (job.workers) — the sim's stand-in for the engine's replica
        reconcile, with expectations armed around the writes."""
        from ..cluster.base import NotFound

        before = len(job.live)
        want = set(range(job.workers))
        extra = sorted(set(job.live) - want)
        missing = sorted(want - set(job.live))
        if extra:
            self.expectations.expect_deletions(job.key, "pod", len(extra))
            for idx in extra:
                try:
                    self.chaos.delete_pod(
                        job.arrival.namespace, job.live[idx])
                except NotFound:
                    pass
                del job.live[idx]
                self.expectations.deletion_observed(job.key, "pod")
        if missing:
            self.expectations.expect_creations(job.key, "pod", len(missing))
            for idx in missing:
                pod = self._make_pod(job, idx)
                self.chaos.create_pod(pod)
                job.live[idx] = pod.metadata.name
                self.expectations.creation_observed(job.key, "pod")
        if extra or missing:
            self.mem.step()  # bind fresh pods (gang-blind: no pod groups)
        self._set_running_pods(job.workers - before)

    def _delete_pods(self, job: _SimJob) -> int:
        from ..cluster.base import NotFound

        dead = len(job.live)
        if job.live:
            self.expectations.expect_deletions(job.key, "pod", dead)
        for name in job.live.values():
            try:
                self.chaos.delete_pod(job.arrival.namespace, name)
            except NotFound:
                pass
            self.expectations.deletion_observed(job.key, "pod")
        job.live.clear()
        self._set_running_pods(-dead)
        return dead

    # --------------------------------------------------------- lifecycle
    def _arrive(self, a: JobArrival) -> None:
        created = self.chaos.create_job(self._manifest(a))
        key = f"JAXJob:{a.namespace}/{a.name}"
        job = _SimJob(
            arrival=a, key=key, uid=created["metadata"]["uid"],
            workers=a.workers, num_slices=a.num_slices,
            queued_since=self.clock.now,
        )
        self.jobs[key] = job
        self.active[key] = job
        self._arrived += 1
        if self._first_arrival_t is None:
            self._first_arrival_t = self.clock.now
        self.queue.add(key)

    def _shard_owned(self, job: _SimJob) -> bool:
        if self.coordinator is None:
            return True
        from ..core.sharding import shard_for_key

        shard = shard_for_key(
            job.arrival.namespace, job.arrival.name, self.coordinator.shards)
        return shard in self.coordinator.owned_shards()

    def _sync(self, key: str) -> None:
        job = self.jobs.get(key)
        if job is None or job.phase == "completed":
            return
        if not self._shard_owned(job):
            # Shard lost (lease steal in flight): defer, exactly as the
            # sharded engine defers foreign keys; the claim-back resync
            # (or the periodic resync) picks it up.
            self._deferred_syncs += 1
            delay = self.scenario.coordinator_tick
            self.queue.add_after(key, delay)
            self._push(self.clock.now + delay, "drain", None)
            return
        cause = self.admission.preemption_requested(key)
        if cause is not None and job.phase == "running":
            self._preempt_teardown(job, cause)
            return
        a = job.arrival
        result = self.admission.try_admit(
            key=key, kind="JAXJob", namespace=a.namespace, name=a.name,
            uid=job.uid, priority_class=a.priority,
            demand={"pods": Fraction(job.workers)}, members=job.workers,
            has_pods=bool(job.phase == "running" and job.live),
            kick=lambda k=key: self.queue.add(k),
            throughput_ratios=a.throughput_ratios or None,
            victim_rank=job.preemptions,
        )
        if result.admitted and job.phase == "queued":
            self._start_running(job)

    def _start_running(self, job: _SimJob) -> None:
        job.phase = "running"
        job.ran_since = self.clock.now
        self._admits_in_window += 1
        self._reconcile_pods(job)
        self._schedule_completion(job)
        if job.arrival.elastic:
            self._push(
                self.clock.now + self.scenario.heartbeat_period,
                "heartbeat", job.key)

    def _patch_status(self, job: _SimJob, mutate: Callable[[dict], None]) -> None:
        from ..cluster.base import NotFound

        try:
            current = self.mem.get_job(
                "JAXJob", job.arrival.namespace, job.arrival.name)
        except NotFound:
            return
        status = current.get("status") or {}
        mutate(status)
        self.chaos.patch_job_status(
            "JAXJob", job.arrival.namespace, job.arrival.name, status)

    def _preempt_teardown(self, job: _SimJob, cause: str) -> None:
        """The counted-disruption protocol in sim form: accrue progress
        (resume-from-checkpoint), count the disruption restart BEFORE
        acknowledging (the admission invariant's ordering), tear the
        pods down, ack exactly once, and re-queue."""
        self._accrue(job)
        job.ran_since = None
        self._preempt_marks += 1
        job.disruptions += 1
        job.preemptions += 1

        def bump(status: dict) -> None:
            counts = status.setdefault("disruptionCounts", {})
            counts["Worker"] = int(counts.get("Worker") or 0) + 1

        self._patch_status(job, bump)
        self._delete_pods(job)
        if self.admission.note_preempted(job.key, job.uid, cause):
            self._preempt_acks += 1
        job.phase = "queued"
        job.queued_since = self.clock.now
        job.completion_version += 1  # invalidate the scheduled completion
        self.queue.add(job.key)

    def _complete(self, key: str, version: int) -> None:
        job = self.jobs.get(key)
        if job is None or job.phase != "running":
            return
        if version != job.completion_version:
            return  # resized/preempted since scheduled: stale event
        self._accrue(job)
        job.phase = "completed"
        self.active.pop(key, None)
        job.completed_at = self.clock.now
        self._last_completion_t = self.clock.now
        self._completed += 1
        ns = job.arrival.namespace
        self._per_tenant_done[ns] = self._per_tenant_done.get(ns, 0) + 1

        def succeed(status: dict) -> None:
            conds = [
                c for c in status.get("conditions") or []
                if c.get("type") != "Succeeded"
            ]
            conds.append({
                "type": "Succeeded", "status": "True",
                "reason": "FleetSimCompleted",
            })
            status["conditions"] = conds

        self._patch_status(job, succeed)
        self.admission.release(key)
        self._delete_pods(job)
        # Reap the terminal job so the live set (and every O(live)
        # control-plane scan) stays bounded at fleet scale — the GC
        # sweep a real cluster runs, compressed to the completion event.
        from ..cluster.base import NotFound

        try:
            self.chaos.delete_job(
                "JAXJob", job.arrival.namespace, job.arrival.name)
        except NotFound:
            pass
        self.expectations.delete_expectations(job.key, "pod")

    # -------------------------------------------------------- heartbeats
    def _heartbeat(self, key: str) -> None:
        job = self.jobs.get(key)
        if job is None or job.phase != "running":
            return
        self._accrue(job)
        job.ckpt_step = int(job.done)
        tps = 1000.0 * max(1, job.workers)
        pod_name = f"{job.arrival.name}-worker-0"
        lease_name = constants.heartbeat_lease_name(pod_name)
        lease = {
            "metadata": {
                "namespace": job.arrival.namespace,
                "name": lease_name,
                "annotations": {
                    constants.ANNOTATION_HEARTBEAT_TPS: f"{tps:.1f}",
                    constants.ANNOTATION_HEARTBEAT_STEP: str(job.ckpt_step),
                    constants.ANNOTATION_HEARTBEAT_CKPT: str(job.ckpt_step),
                },
            },
            "spec": {
                "holderIdentity": pod_name,
                "renewTime": self.clock.now,
            },
        }
        from ..cluster.base import NotFound

        try:
            self.mem.get_lease(job.arrival.namespace, lease_name)
            self.chaos.update_lease(lease)
        except NotFound:
            self.chaos.create_lease(lease)
        self._push(
            self.clock.now + self.scenario.heartbeat_period,
            "heartbeat", key)

    # ------------------------------------------------------------ storms
    def _fire_storm(self, ev: StormEvent) -> None:
        if ev.kind == "revoke-capacity":
            self.chaos.revoke_capacity(dict(ev.capacity or {}))
            # The arbiter only notices at its next pump: nudge every
            # admitted job through a sync, exactly as the engine's
            # resync would — the revocation sweep preempts to fit.
            for key in sorted(self.active):
                if self.active[key].phase == "running":
                    self.queue.add(key)
        elif ev.kind == "preempt-slice":
            target = self._slice_target(ev.slice_index)
            if target is not None:
                self.chaos.preempt_slice(
                    target.arrival.name, ev.slice_index,
                    namespace=target.arrival.namespace)
                self._slice_restart(target, ev.slice_index)
        elif ev.kind == "freeze-heartbeats":
            self.chaos.freeze_heartbeats(name_contains=ev.name_contains)
        elif ev.kind == "thaw-heartbeats":
            self.chaos.thaw_heartbeats()

    def _slice_target(self, slice_index: int) -> Optional[_SimJob]:
        for key in sorted(self.active):
            job = self.active[key]
            if (job.phase == "running" and job.arrival.elastic
                    and job.num_slices > slice_index):
                return job
        return None

    def _slice_restart(self, job: _SimJob, slice_index: int) -> None:
        """Slice-scoped counted restart (PR 11's failure domain): the
        reclaimed slice's pods died; count it, replace ONLY those pods
        (survivor UIDs stable), and charge a restart penalty to the
        completion model."""
        self._accrue(job)
        job.slice_restarts += 1

        def bump(status: dict) -> None:
            counts = status.setdefault("sliceRestartCounts", {})
            counts["Worker"] = int(counts.get("Worker") or 0) + 1

        self._patch_status(job, bump)
        hosts = max(1, self.scenario.hosts_per_slice)
        from ..cluster.base import NotFound

        base = slice_index * hosts
        dead = 0
        for idx, name in [
                (i, n) for i, n in job.live.items()
                if base <= i < base + hosts]:
            try:
                self.chaos.delete_pod(job.arrival.namespace, name)
                dead += 1
            except NotFound:
                pass
            del job.live[idx]
        self._set_running_pods(-dead)
        if job.phase == "running":
            self.expectations.expect_creations(job.key, "pod", hosts)
            for idx in range(base, base + hosts):
                pod = self._make_pod(job, idx)
                self.chaos.create_pod(pod)
                job.live[idx] = pod.metadata.name
                self.expectations.creation_observed(job.key, "pod")
            self.mem.step()
            self._set_running_pods(hosts)
            job.done = max(0.0, job.done - 10.0)  # restart-window loss
            self._schedule_completion(job)

    # --------------------------------------------------------- resyncs
    def _resync(self) -> None:
        """Periodic backstop. ONE pump evaluates the whole waiting set
        and its admit-kicks requeue every newly admitted gang, so the
        resync pokes only the oldest queued gang (O(queued), not
        O(queued^2) pumps) — plus any running gang with a pending
        preemption mark, whose counted teardown the engine owes."""
        oldest: Optional[Tuple[float, str]] = None
        marked: List[str] = []
        for key, job in self.active.items():
            if job.phase == "queued":
                if oldest is None or (job.queued_since, key) < oldest:
                    oldest = (job.queued_since, key)
            elif job.phase == "running" and (
                    self.admission.preemption_requested(key) is not None):
                marked.append(key)
        for key in sorted(marked):
            self.queue.add(key)
        if oldest is not None:
            self.queue.add(oldest[1])

    def _autoscaler_tick(self) -> None:
        if self.autoscaler is None:
            return
        applied = self.autoscaler.tick()
        for resize in applied:
            job = self.jobs.get(resize.key)
            if job is None or job.phase != "running":
                continue
            self._accrue(job)
            hosts = max(1, self.scenario.hosts_per_slice)
            job.num_slices = resize.to_slices
            job.workers = resize.to_slices * hosts
            # Re-ask the gate at the new demand BEFORE touching pods
            # (grow must re-grant in place or cap; shrink releases).
            self._sync(resize.key)
            if job.phase == "running":
                self._reconcile_pods(job)
                if resize.direction == "grow":
                    # The grow's restore penalty (the _slice_restart
                    # charging pattern): a warm start pulls from live
                    # peers, a cold one round-trips storage. Both knobs
                    # default 0.0 — pre-existing corpus digests hold.
                    sc = self.scenario
                    penalty = (sc.warm_start_restore_seconds if sc.warm_start
                               else sc.grow_restore_seconds)
                    if penalty:
                        job.done = max(0.0, job.done - penalty)
                    self._grows += 1
                    if sc.warm_start:
                        self._warm_start_grows += 1
                self._schedule_completion(job)

    def _coordinator_tick(self) -> None:
        if self.coordinator is not None:
            self.coordinator.tick()

    # ----------------------------------------------------- epoch sweeps
    def _queued_view(self) -> List[Tuple[str, float, int]]:
        return [
            (key, self.clock.now - j.queued_since, j.workers)
            for key, j in sorted(self.active.items())
            if j.phase == "queued"
        ]

    def _epoch_sweep(self, label: str) -> None:
        from .invariants import (
            check_admission_invariants, check_autoscaler_invariants,
            check_fleet_invariants, check_job_invariants,
        )

        self._sweeps += 1
        violations = check_job_invariants(self.mem, ("JAXJob",))
        violations.extend(check_admission_invariants(
            self.admission, cluster=self.mem, kinds=("JAXJob",)))
        if self.autoscaler is not None:
            violations.extend(check_autoscaler_invariants(
                self.autoscaler, cluster=self.mem, kinds=("JAXJob",)))
        running = sum(
            1 for j in self.active.values() if j.phase == "running")
        queued = self._queued_view()
        snap = self.admission.snapshot()
        violations.extend(check_fleet_invariants(
            arrivals=self._arrived,
            completed=self._completed,
            running=running,
            queued=len(queued),
            preempt_marks=self._preempt_marks,
            preempt_acks=self._preempt_acks,
            queued_waits=queued,
            aging_seconds=self.scenario.aging_seconds,
            resync_period=self.scenario.resync_period,
            admission_snapshot=snap,
            running_pods=self._running_pods,
            admits_in_window=self._admits_in_window,
        ))
        self._admits_in_window = 0
        if violations:
            self._sweep_violations.extend(
                f"[{label}] {v}" for v in violations)
        self._resident_peak = max(
            self._resident_peak, self.watch_cache.resident_objects())
        # Bytes approximation sampled at the same sweep cadence (an
        # O(resident set) walk — cheap per epoch, ruinous per sync);
        # also publishes the watch_cache_resident_bytes gauge.
        self._resident_bytes_peak = max(
            self._resident_bytes_peak, self.watch_cache.resident_bytes())

    # --------------------------------------------------------- draining
    def _drain_queue(self) -> None:
        while True:
            item = self.queue.get(timeout=0)
            if item is None:
                return
            try:
                self._sync(item)
            finally:
                self.queue.done(item)

    # --------------------------------------------------------------- run
    def run(self) -> dict:
        sc = self.scenario
        wall0 = time.perf_counter()
        for a in self.trace:
            self._push(a.t, "arrival", a)
        for ev in sc.storm:
            self._push(ev.t, "storm", ev)
        # Recurring ticks self-reschedule while the fleet is live (so a
        # storm backlog keeps getting resynced however long it takes to
        # drain), and stop once every arrival is accounted terminal —
        # the virtual horizon then reflects actual work. A wedged
        # scenario (a freeze with no thaw) is cut off at the hard cap
        # and fails its final invariant sweep loudly.
        self._hard_stop = sc.horizon * 10 + 86400.0
        self._push(sc.resync_period, "resync", None)
        if self.autoscaler is not None:
            self._push(sc.autoscaler_tick, "autoscaler", None)
        if self.coordinator is not None:
            self._push(0.0, "coordinator", None)
        self._push(sc.epoch_seconds, "epoch", None)

        recurring = {
            "resync": (sc.resync_period, lambda d: self._resync()),
            "autoscaler": (
                sc.autoscaler_tick, lambda d: self._autoscaler_tick()),
            "coordinator": (
                sc.coordinator_tick, lambda d: self._coordinator_tick()),
            "epoch": (
                sc.epoch_seconds,
                lambda d: self._epoch_sweep(f"epoch@{self.clock.now:g}")),
        }
        handlers = {
            "arrival": lambda d: self._arrive(d),
            "storm": lambda d: self._fire_storm(d),
            "heartbeat": lambda d: self._heartbeat(d),
            "complete": lambda d: self._complete(*d),
            "drain": lambda d: None,
        }
        while self._heap:
            t, _, kind, data = heapq.heappop(self._heap)
            drained = self._completed >= len(self.trace)
            if kind in recurring:
                if drained:
                    continue
                self.clock.advance_to(t)
                period, handler = recurring[kind]
                handler(data)
                if t + period <= self._hard_stop:
                    self._push(t + period, kind, None)
                self._drain_queue()
                continue
            if drained and kind == "drain":
                continue
            self.clock.advance_to(t)
            handlers[kind](data)
            self._drain_queue()
        self._end_t = self.clock.now
        self._note_util()
        self._epoch_sweep("final")
        wall = time.perf_counter() - wall0
        self.report = self._build_report(wall)
        return self.report

    # ------------------------------------------------------------ report
    def _hot_paths(self) -> dict:
        pump_count, pump_sum = self.metrics.labeled_histogram_stats(
            "training_operator_admission_pump_seconds")
        decide_count, decide_sum = self.metrics.labeled_histogram_stats(
            "training_operator_autoscaler_decide_seconds")
        return {
            "pump_calls": pump_count,
            "pump_seconds_total": round(pump_sum, 6),
            "pump_seconds_per_call": round(
                pump_sum / pump_count, 9) if pump_count else None,
            "autoscaler_decide_calls": decide_count,
            "autoscaler_decide_seconds_per_call": round(
                decide_sum / decide_count, 9) if decide_count else None,
            "watch_cache_resident_objects_peak": self._resident_peak,
            "watch_cache_resident_bytes_peak": self._resident_bytes_peak,
            "decision_log_entries": (
                len(self.admission.decision_log)
                + (len(self.autoscaler.decision_log)
                   if self.autoscaler else 0)
            ),
            # Admissibility-index observability (all zero with the
            # index OFF): elided pump triggers by reason, plus full-
            # scan fallbacks for the active policy.
            "pump_skipped_no_capacity_delta": int(
                self.metrics.labeled_counter_value(
                    "training_operator_admission_pump_skipped_total",
                    "no-capacity-delta")),
            "pump_skipped_band_watermark": int(
                self.metrics.labeled_counter_value(
                    "training_operator_admission_pump_skipped_total",
                    "band-watermark")),
            "index_fallback_pumps": int(
                self.metrics.labeled_counter_value(
                    "training_operator_admission_index_fallback_total",
                    self.scenario.policy)),
        }

    def digest(self) -> str:
        """The byte-equality artifact: trace lines + both decision logs
        + the chaos fault log + the completion order, hashed. Two runs
        of one scenario must agree on every byte here."""
        h = hashlib.sha256()
        for a in self.trace:
            h.update(a.line().encode())
            h.update(b"\n")
        for line in self.admission.decision_log_lines():
            h.update(line.encode())
            h.update(b"\n")
        if self.autoscaler is not None:
            for line in self.autoscaler.decision_log_lines():
                h.update(line.encode())
                h.update(b"\n")
        for entry in self.chaos.fault_log:
            h.update(entry.encode())
            h.update(b"\n")
        for key, job in sorted(self.jobs.items()):
            h.update(
                f"{key}:{job.phase}:{job.completed_at}:{job.disruptions}:"
                f"{job.slice_restarts}".encode())
            h.update(b"\n")
        return h.hexdigest()

    def _build_report(self, wall: float) -> dict:
        sc = self.scenario
        horizon = max(self._end_t, 1e-9)
        makespan = None
        if self._first_arrival_t is not None and self._last_completion_t:
            makespan = round(
                self._last_completion_t - self._first_arrival_t, 3)
        capacity_area = sc.capacity_pods * horizon
        tenants_done = dict(sorted(self._per_tenant_done.items()))
        shares = [
            n / max(1, self._completed) for n in tenants_done.values()
        ]
        jain = (
            round(sum(shares) ** 2 / (len(shares) * sum(
                s * s for s in shares)), 4)
            if shares and sum(s * s for s in shares) > 0 else None
        )
        return {
            "scenario": sc.name,
            "seed": sc.seed,
            "profile": sc.profile,
            "jobs": len(self.trace),
            "tenants": sc.tenants,
            "virtual_horizon_s": round(horizon, 3),
            "wall_s": round(wall, 3),
            "compression_x": round(horizon / max(wall, 1e-9), 1),
            "completed": self._completed,
            "makespan_s": makespan,
            "utilization": round(
                self._util_area / capacity_area, 4) if capacity_area else None,
            "fairness_jain": jain,
            "preemptions": self._preempt_acks,
            "slice_restarts": sum(
                j.slice_restarts for j in self.jobs.values()),
            "resizes": (
                len(self.autoscaler.resize_ledger)
                if self.autoscaler else 0),
            "grows": self._grows,
            "warm_start_grows": self._warm_start_grows,
            "deferred_syncs": self._deferred_syncs,
            "fault_log_entries": len(self.chaos.fault_log),
            "invariant_sweeps": self._sweeps,
            "invariant_violations": list(self._sweep_violations),
            "hot_paths": self._hot_paths(),
            "digest": self.digest(),
        }


def run_scenario(scenario: Scenario) -> dict:
    """One seeded fleet-sim run: build, run, report."""
    return FleetSim(scenario).run()


# ------------------------------------------------------- builtin corpus


def smoke_scenario() -> Scenario:
    """The CI smoke gate's composed storm: 5k jobs / 64 tenants with
    capacity revocation + slice preemption + a lease steal landing on a
    4-shard ring, sized to clear the >=100x compression gate well inside
    the existing CI step budgets."""
    return Scenario(
        name="smoke-composed", seed=2026, profile="bursty", jobs=5000,
        tenants=64, horizon=14400.0, capacity_pods=192, policy="priority",
        autoscaler=True, elastic_jobs=24, hosts_per_slice=2, shards=4,
        aging_seconds=600.0,
        storm=[
            StormEvent(t=3600.0, kind="revoke-capacity",
                       capacity={"pods": "128"}),
            StormEvent(t=4200.0, kind="preempt-slice", slice_index=0),
            StormEvent(t=5400.0, kind="revoke-capacity",
                       capacity={"pods": "192"}),
            StormEvent(t=6000.0, kind="preempt-slice", slice_index=0),
            StormEvent(t=9000.0, kind="preempt-slice", slice_index=1),
            StormEvent(t=10800.0, kind="preempt-slice", slice_index=0),
        ],
        lease_steals=[
            {"at_renew": 12, "name_contains": "-shard-1",
             "rival": "phantom"},
        ],
    )


def builtin_scenarios() -> Dict[str, Scenario]:
    """The storm corpus, generated in code so the checked-in JSON files
    (tf_operator_tpu/testing/scenarios/*.json) can be regression-tested
    against their generators: a drive-by edit to a corpus file that
    changes replay bytes fails the fleet tier, not a user's run."""
    return {
        "burst-storm": Scenario(
            name="burst-storm", seed=1701, profile="bursty",
            jobs=600, tenants=16, horizon=3600.0, capacity_pods=48,
            policy="priority", aging_seconds=600.0, shards=1,
            storm=[
                StormEvent(t=900.0, kind="revoke-capacity",
                           capacity={"pods": "24"}),
                StormEvent(t=1800.0, kind="revoke-capacity",
                           capacity={"pods": "48"}),
            ],
        ),
        "capacity-churn-slices": Scenario(
            name="capacity-churn-slices", seed=1702, profile="bursty",
            jobs=400, tenants=12, horizon=3600.0, capacity_pods=48,
            policy="priority", autoscaler=True, elastic_jobs=6,
            hosts_per_slice=2, aging_seconds=600.0,
            storm=[
                StormEvent(t=600.0, kind="revoke-capacity",
                           capacity={"pods": "28"}),
                StormEvent(t=1200.0, kind="preempt-slice", slice_index=0),
                StormEvent(t=2000.0, kind="revoke-capacity",
                           capacity={"pods": "48"}),
                StormEvent(t=2600.0, kind="preempt-slice", slice_index=1),
            ],
        ),
        "lease-steal-flap": Scenario(
            name="lease-steal-flap", seed=1703, profile="diurnal",
            jobs=400, tenants=12, horizon=3600.0, capacity_pods=40,
            policy="priority", shards=4, aging_seconds=600.0,
            lease_steals=[
                {"at_renew": 6, "name_contains": "-shard-0",
                 "rival": "phantom-a"},
                {"at_renew": 14, "name_contains": "-shard-2",
                 "rival": "phantom-b"},
            ],
            renew_delays=[
                {"after_renews": 20, "drop_renews": 2,
                 "name_contains": "-shard-1"},
            ],
        ),
        "warm-start-grow-churn": Scenario(
            name="warm-start-grow-churn", seed=1705, profile="bursty",
            jobs=400, tenants=12, horizon=3600.0, capacity_pods=48,
            policy="priority", autoscaler=True, elastic_jobs=8,
            hosts_per_slice=2, aging_seconds=600.0,
            # Checkpoint-free grows landing DURING capacity churn: the
            # revoke/restore cycle frees and re-frees surplus, so grows
            # fire into the same windows slice preemptions are tearing
            # ranks down — the storm the warm-start plane exists for.
            # The asymmetric penalties (cold 30s storage round-trip vs
            # 5s peer pull) make the warm path's effect visible in the
            # completion model, not just the attribution columns.
            warm_start=True,
            grow_restore_seconds=30.0,
            warm_start_restore_seconds=5.0,
            storm=[
                StormEvent(t=600.0, kind="revoke-capacity",
                           capacity={"pods": "28"}),
                StormEvent(t=1000.0, kind="preempt-slice", slice_index=0),
                StormEvent(t=1400.0, kind="revoke-capacity",
                           capacity={"pods": "48"}),
                StormEvent(t=1900.0, kind="preempt-slice", slice_index=1),
                StormEvent(t=2400.0, kind="revoke-capacity",
                           capacity={"pods": "32"}),
                StormEvent(t=2900.0, kind="revoke-capacity",
                           capacity={"pods": "48"}),
            ],
        ),
        "diurnal-trough-backfill": Scenario(
            name="diurnal-trough-backfill", seed=1704,
            profile="serving-trough", jobs=600, tenants=16,
            horizon=7200.0, capacity_pods=48, policy="drf",
            tenant_weights={"tenant-0000": 2.0},
            aging_seconds=600.0,
            storm=[
                StormEvent(t=2400.0, kind="revoke-capacity",
                           capacity={"pods": "32"}),
                StormEvent(t=4800.0, kind="revoke-capacity",
                           capacity={"pods": "48"}),
            ],
        ),
    }
