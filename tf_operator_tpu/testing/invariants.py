"""Reusable structural invariants for chaos/crash/stall tiers.

Everything a crash-consistent control plane must leave true of the
CLUSTER — checkable from persisted state alone, with no knowledge of the
schedule that battered it:

- exactly-once ledgers: the three restart ledgers (`restartCounts` /
  `disruptionCounts` / `stallCounts`) are non-negative, and when the test
  knows the physical incident count it can pin them exactly
  (`expect_ledgers`) — "disjoint and never doubled across a failover" is
  asserted by passing the per-cause expectation;
- no orphans: every pod/service carrying a controller ownerRef points at
  a LIVE job uid (a crashed teardown must not strand dependents);
- no duplicate indices: at most one non-terminating pod (and one
  service) per (job, replica-type, index) slot — the expectations race's
  signature corpse — and, for a live unsuspended job, no non-terminating
  pod at an index beyond the declared replica count;
- well-formed conditions: at most one entry per type, legal status
  values, and the mutual-exclusion pairs (Succeeded/Failed,
  Running/Restarting) never both True;
- span ordering (`check_span_invariants`, over a core/tracing.py export):
  inside every COUNTED gang-restart span, the successful status write
  that made the count durable precedes every teardown pod delete in span
  order — the count-before-teardown protocol, audited from the trace
  alone. Resume spans (counted=False: the write landed in a previous
  sync/incarnation) carry no ordering obligation.

`check_job_invariants` returns violations as strings (so a tier can
aggregate); `assert_invariants` raises with the full list. The chaos and
stall tiers run these after every scenario, the crash tier after every
failover-and-converge. Passing `tracer=` folds the span invariants in
AND, on any violation, dumps the full trace export into build/ for
post-mortem (`dump_trace`).
"""

from __future__ import annotations

import os
import re
from fractions import Fraction
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import constants

# Condition pairs that may never be simultaneously True.
_EXCLUSIVE = (("Succeeded", "Failed"), ("Running", "Restarting"))

_LEDGERS = (
    "restartCounts", "disruptionCounts", "stallCounts",
    # Per-slice restart attribution (slice-scoped failure domains): purely
    # attributive — no budget draws from it — but still exactly-once, so
    # tests pin it with expect_ledgers like the cause ledgers.
    "sliceRestartCounts",
)


def _conditions(status: dict) -> List[dict]:
    return list((status or {}).get("conditions") or [])


def check_condition_invariants(job: dict) -> List[str]:
    name = (job.get("metadata") or {}).get("name", "?")
    violations: List[str] = []
    conds = _conditions(job.get("status") or {})
    seen: Dict[str, dict] = {}
    for c in conds:
        ctype = c.get("type")
        if not ctype:
            violations.append(f"{name}: condition with empty type: {c}")
            continue
        if ctype in seen:
            violations.append(f"{name}: duplicate condition type {ctype}")
        seen[ctype] = c
        if c.get("status") not in ("True", "False"):
            violations.append(
                f"{name}: condition {ctype} has malformed status "
                f"{c.get('status')!r}"
            )
    for a, b in _EXCLUSIVE:
        if (
            seen.get(a, {}).get("status") == "True"
            and seen.get(b, {}).get("status") == "True"
        ):
            violations.append(f"{name}: conditions {a} and {b} both True")
    return violations


def check_ledger_invariants(
    job: dict, expect_ledgers: Optional[Dict[str, Dict[str, int]]] = None
) -> List[str]:
    """Structural ledger checks, plus exact-count pinning when the caller
    knows the physical incident tally. `expect_ledgers` maps ledger name
    -> expected per-replica-type dict; a named ledger must match EXACTLY
    (pass {} to assert it stayed untouched — the disjointness half)."""
    name = (job.get("metadata") or {}).get("name", "?")
    status = job.get("status") or {}
    violations: List[str] = []
    for ledger in _LEDGERS:
        counts = status.get(ledger) or {}
        for rtype, value in counts.items():
            if not isinstance(value, int) or value < 0:
                violations.append(
                    f"{name}: {ledger}[{rtype}] malformed: {value!r}"
                )
    if expect_ledgers:
        for ledger, expected in expect_ledgers.items():
            actual = status.get(ledger) or {}
            if actual != expected:
                violations.append(
                    f"{name}: {ledger} == {actual!r}, expected {expected!r} "
                    "(a crash/failover doubled or lost a count)"
                )
    return violations


def _slot(obj) -> Optional[tuple]:
    labels = obj.metadata.labels
    jn = labels.get(constants.LABEL_JOB_NAME)
    rt = labels.get(constants.LABEL_REPLICA_TYPE)
    idx = labels.get(constants.LABEL_REPLICA_INDEX)
    if not jn or rt is None or idx is None:
        return None
    return (obj.metadata.namespace, jn, rt, idx)


def check_dependents_invariants(
    cluster, jobs: Sequence[dict], namespace: Optional[str] = None
) -> List[str]:
    """Orphan + duplicate-slot checks over the live pods/services against
    the given job set (pass every kind's jobs — an ownerRef match against
    ANY live job counts)."""
    violations: List[str] = []
    live_uids = {
        (j.get("metadata") or {}).get("uid") for j in jobs
    } - {None, ""}
    by_job = {
        (
            (j.get("metadata") or {}).get("namespace", "default"),
            (j.get("metadata") or {}).get("name", ""),
        ): j
        for j in jobs
    }

    def scan(objs, what: str) -> None:
        slots: Dict[tuple, int] = {}
        for obj in objs:
            ref = obj.metadata.controller_ref()
            if ref is not None and ref.uid and ref.uid not in live_uids:
                violations.append(
                    f"orphan {what} {obj.metadata.namespace}/"
                    f"{obj.metadata.name}: controller uid {ref.uid} matches "
                    "no live job"
                )
            if obj.metadata.deletion_timestamp is not None:
                continue  # a terminating object vacates its slot
            slot = _slot(obj)
            if slot is None:
                continue
            slots[slot] = slots.get(slot, 0) + 1
            if slots[slot] > 1:
                violations.append(
                    f"duplicate {what} for slot {slot} (expectations race "
                    "corpse: two live objects share one replica index)"
                )
        if what != "pod":
            return
        # Out-of-range live pods against the declared replica counts.
        for (ns, jname, rt, idx), _count in slots.items():
            job = by_job.get((ns, jname))
            if job is None:
                continue
            spec = job.get("spec") or {}
            replica_specs = next(
                (v for k, v in spec.items() if k.endswith("ReplicaSpecs")),
                {},
            ) or {}
            declared = next(
                (
                    v.get("replicas", 1)
                    for k, v in replica_specs.items()
                    if k.lower() == rt.lower()
                ),
                None,
            )
            try:
                index = int(idx)
            except ValueError:
                violations.append(
                    f"pod slot {(ns, jname, rt, idx)}: non-integer index"
                )
                continue
            if declared is not None and index >= int(declared or 0):
                violations.append(
                    f"live pod at out-of-range index {index} "
                    f"(declared {declared}) for {ns}/{jname}/{rt}"
                )

    scan(cluster.list_pods(namespace=namespace), "pod")
    scan(cluster.list_services(namespace=namespace), "service")
    return violations


def check_span_invariants(traces: Sequence[dict]) -> List[str]:
    """Span-order invariants over a `Tracer.export()` payload. The one
    hard rule today: a counted gang restart's successful status write
    (`api.update` or `api.patch` child, resource=status, code=200)
    precedes every
    teardown pod delete (`api.delete` child, resource=pods) in span-id
    order — span ids are assigned at record time under one lock, so id
    order IS causal order. A counted span with deletes but no successful
    write is the lost-count crash window the protocol exists to close."""
    violations: List[str] = []
    for trace in traces:
        spans = list(trace.get("spans") or [])
        by_parent: Dict[Optional[int], List[dict]] = {}
        for span in spans:
            by_parent.setdefault(span.get("parent"), []).append(span)
        for span in spans:
            if span.get("name") != "gang.restart":
                continue
            attrs = span.get("attrs") or {}
            children = by_parent.get(span.get("id"), [])
            # api.update = the legacy full-object status write; api.patch
            # = the coalescing writer's single-request apply. Either one
            # satisfies the protocol — counted writes bypass coalescing's
            # deferral but still flow through the patch verb when the
            # capability is on, and the invariant must hold in both modes.
            status_writes = [
                c["id"] for c in children
                if c.get("name") in ("api.update", "api.patch")
                and (c.get("attrs") or {}).get("resource") == "status"
                and (c.get("attrs") or {}).get("code") == "200"
            ]
            deletes = [
                c["id"] for c in children
                if c.get("name") == "api.delete"
                and (c.get("attrs") or {}).get("resource") == "pods"
            ]
            where = f"{trace.get('trace_id')}: gang.restart span {span.get('id')}"
            # Slice-scope audit (slice-scoped failure domains): an
            # escalation (coordinator/quorum loss) may never record a
            # slice-scoped span, and a slice-scoped span's teardown may
            # only target ITS slice's pods — checked from the span's own
            # target_names/slice/hosts_per_slice attrs, so the trace
            # alone proves the teardown never crossed a domain boundary.
            if attrs.get("escalated") and attrs.get("scope") == "slice":
                violations.append(
                    f"{where}: escalated (quorum/coordinator loss) but "
                    "scope is 'slice' — an escalation must restart the "
                    "whole world"
                )
            if attrs.get("scope") == "slice":
                violations.extend(
                    _check_slice_targets(where, attrs, len(deletes))
                )
            if not attrs.get("counted") or not deletes:
                # Resume span (count already durable), or phase 1 aborted
                # before anything died — nothing to order.
                continue
            if not status_writes:
                violations.append(
                    f"{where} deleted {len(deletes)} pod(s) with no "
                    "successful counted status write in the span (count-"
                    "before-teardown violated: a crash here loses the count)"
                )
            elif min(deletes) < min(status_writes):
                violations.append(
                    f"{where}: teardown delete (span {min(deletes)}) "
                    f"precedes the counted status write (span "
                    f"{min(status_writes)})"
                )
    return violations


def _check_slice_targets(where: str, attrs: dict, deletes: int) -> List[str]:
    """Target-set half of the slice-scope audit: every pod the slice
    restart declares as a teardown target must live inside the span's
    slice (replica index in [slice*h, (slice+1)*h)), and the span may
    not issue more pod deletes than it declared targets — together, a
    counted slice restart provably never deletes a surviving slice's
    pod."""
    violations: List[str] = []
    slice_index = attrs.get("slice")
    hosts = attrs.get("hosts_per_slice")
    names = [n for n in str(attrs.get("target_names") or "").split(",") if n]
    if slice_index is None or not hosts:
        violations.append(
            f"{where}: slice-scoped span missing slice/hosts_per_slice "
            "attrs (the audit has nothing to check against)"
        )
        return violations
    lo, hi = slice_index * hosts, (slice_index + 1) * hosts
    for name in names:
        tail = name.rsplit("-", 1)[-1]
        if not tail.isdigit():
            violations.append(
                f"{where}: target {name!r} has no parseable replica index"
            )
            continue
        index = int(tail)
        if not lo <= index < hi:
            violations.append(
                f"{where}: slice-{slice_index} restart targets {name!r} "
                f"(index {index} outside [{lo}, {hi})) — the teardown "
                "crossed a slice boundary"
            )
    if deletes > len(names):
        violations.append(
            f"{where}: slice restart issued {deletes} pod delete(s) for "
            f"{len(names)} declared target(s) — an undeclared pod died "
            "inside the slice teardown span"
        )
    return violations


def count_gang_restarts(
    traces: Sequence[dict], scope: Optional[str] = None,
    counted_only: bool = True,
) -> int:
    """Counted gang.restart spans across an export, optionally filtered
    by restart-domain scope ('slice' | 'world') — the trace-side tally a
    scenario pins against its ledger expectation (e.g. quorum escalation
    produces exactly ONE counted world-restart span)."""
    total = 0
    for trace in traces:
        for span in trace.get("spans") or []:
            if span.get("name") != "gang.restart":
                continue
            attrs = span.get("attrs") or {}
            if counted_only and not attrs.get("counted"):
                continue
            if scope is not None and attrs.get("scope") != scope:
                continue
            total += 1
    return total


def check_admission_invariants(
    admission, cluster=None, kinds: Sequence[str] = (),
    namespace: Optional[str] = None,
) -> List[str]:
    """Admission-layer invariants (core/admission.py), over the arbiter's
    snapshot + ledgers and (when a cluster is given) the live state:

    - capacity never exceeded at a converged state: admitted usage fits
      the effective pool (a transient overshoot exists only between a
      revocation and the preempt-to-fit teardown — call this after
      settling);
    - quota never exceeded: per-namespace admitted usage within the
      declared quota (hard — admission enforces it at admit time, and
      revocations never change quotas);
    - no partially-admitted gang: a WAITING job owns zero live
      (non-terminating) pods — its pods are held unborn, so a partial
      gang cannot exist by construction;
    - backfill never starves the head-of-line: every backfill admit in
      the admit log happened while the head's wait was under the aging
      bound;
    - preemption counted exactly once: the ledger holds one entry per
      acknowledged preemption, and every ledgered job's disruption
      ledger covers at least its admission preemptions (the counted
      write precedes the acknowledgment by protocol)."""
    from ..core.job_controller import parse_quantity

    violations: List[str] = []
    snap = admission.snapshot()
    cap = snap.get("capacity")
    usage = snap.get("usage") or {}
    if cap is not None:
        for resource, bound in cap.items():
            used = usage.get(resource)
            if used is not None and parse_quantity(used) > parse_quantity(bound):
                violations.append(
                    f"admission: usage of {resource} ({used}) exceeds "
                    f"capacity ({bound}) at a converged state"
                )
    # Device-generation sub-pools (the gavel placement unit): each
    # generation's placed usage must fit ITS bound — the flat pool
    # fitting while one generation is oversubscribed means a policy
    # placed a gang on chips that aren't there.
    for gen, pools in (snap.get("generations") or {}).items():
        gen_cap = pools.get("capacity") or {}
        gen_used = pools.get("usage") or {}
        for resource, bound in gen_cap.items():
            used = gen_used.get(resource)
            if used is not None and parse_quantity(used) > parse_quantity(bound):
                violations.append(
                    f"admission: generation {gen} usage of {resource} "
                    f"({used}) exceeds its sub-pool ({bound})"
                )
    for ns, quota in (snap.get("quotas") or {}).items():
        ns_usage = (snap.get("namespace_usage") or {}).get(ns) or {}
        for resource, bound in quota.items():
            used = ns_usage.get(resource)
            if used is not None and parse_quantity(used) > parse_quantity(bound):
                violations.append(
                    f"admission: namespace {ns} usage of {resource} ({used}) "
                    f"exceeds its quota ({bound})"
                )
    # No-bypass rule (elastic grow × admission): an admitted gang's live
    # demand may never exceed what the gate granted — a grow either
    # re-granted in place (both sides move together) or re-queued through
    # the gate; a mismatch means a spec refresh inflated usage past the
    # admitted charge without a decision.
    for entry in snap.get("admitted") or []:
        granted = entry.get("admitted_demand")
        if granted is None:
            continue
        for resource, qty in (entry.get("demand") or {}).items():
            bound = granted.get(resource)
            if bound is None or parse_quantity(qty) > parse_quantity(bound):
                violations.append(
                    f"admission: {entry.get('key')} holds {qty} {resource} "
                    f"but the gate granted {bound} — an elastic grow "
                    "bypassed the admission gate"
                )
    aging = snap.get("aging_seconds")
    for entry in snap.get("admit_log") or []:
        head_wait = entry.get("head_wait_at_admit")
        if entry.get("backfill") and head_wait is not None and aging is not None:
            if head_wait >= aging:
                violations.append(
                    f"admission: {entry.get('key')} was backfilled while the "
                    f"head-of-line had waited {head_wait:.1f}s >= the aging "
                    f"bound {aging:.1f}s (backfill starved the head)"
                )
    ledger = [tuple(t) for t in snap.get("preemption_ledger") or []]
    if cluster is not None:
        preempted_by_uid: Dict[str, int] = {}
        for _key, uid, _cause in ledger:
            preempted_by_uid[uid] = preempted_by_uid.get(uid, 0) + 1
        jobs_by_uid = {}
        for kind in kinds:
            for job in cluster.list_jobs(kind, namespace):
                jobs_by_uid[(job.get("metadata") or {}).get("uid")] = job
        for uid, count in preempted_by_uid.items():
            job = jobs_by_uid.get(uid)
            if job is None:
                continue  # job since deleted; nothing left to cross-check
            status = job.get("status") or {}
            if any(
                c.get("type") == "Suspended"
                for c in status.get("conditions") or []
            ):
                # Resume deliberately resets the disruption ledger (a
                # fresh lifecycle window) while the arbiter's ledger is
                # append-only — the cross-check would report a false
                # "acknowledged before counted" for a healthy job.
                continue
            disruptions = sum(
                (status.get("disruptionCounts") or {}).values()
            )
            if disruptions < count:
                violations.append(
                    f"admission: job uid {uid} has {count} ledgered "
                    f"preemption(s) but only {disruptions} counted "
                    "disruption restart(s) — a preemption was acknowledged "
                    "before its counted write"
                )
        for waiter in snap.get("waiting") or []:
            kind, _, rest = str(waiter.get("key", "")).partition(":")
            ns, _, name = rest.partition("/")
            if not name:
                continue
            # Slice-granular keys ("<ns>/<name>#slice-<s>"): the waiting
            # unit is ONE slice, so only that slice's pods (stamped with
            # the tpu-slice-index label) count — its admitted sibling
            # slices legitimately own live pods.
            name, _, slice_suffix = name.partition("#slice-")
            selector = {
                constants.LABEL_GROUP_NAME: constants.GROUP_NAME,
                constants.LABEL_JOB_NAME: name,
            }
            if slice_suffix:
                selector[constants.LABEL_SLICE_INDEX] = slice_suffix
            live = [
                p for p in cluster.list_pods(namespace=ns, labels=selector)
                if p.metadata.deletion_timestamp is None
            ]
            if live:
                violations.append(
                    f"admission: waiting gang {waiter.get('key')} owns "
                    f"{len(live)} live pod(s) — a partially-admitted gang"
                )
    return violations


def check_autoscaler_invariants(
    autoscaler, cluster=None, kinds: Sequence[str] = ("JAXJob",),
    namespace: Optional[str] = None,
) -> List[str]:
    """Autoscaler-layer invariants (core/autoscaler.py), auditable from
    the resize ledger + live specs alone:

    - bounds: no applied resize ever targeted below minSlices or above
      maxSlices, and every live elastic job's numSlices sits inside its
      declared bounds;
    - checkpoint-coordinated shrink: every ledgered shrink credits a
      checkpoint step (a shrink applied without one is the data-loss
      window the protocol exists to close);
    - hysteresis: no resize landed inside the job's cooldown window, and
      consecutive resizes of one job are at least the dwell apart."""
    violations: List[str] = []
    snap = autoscaler.snapshot()
    ledger = snap.get("resize_ledger") or []
    for entry in ledger:
        key = entry.get("key")
        direction = entry.get("direction")
        to_slices = entry.get("to")
        lo = entry.get("min_slices")
        hi = entry.get("max_slices")
        if lo is not None and to_slices is not None and to_slices < lo:
            violations.append(
                f"autoscaler: {key} resized to {to_slices} below "
                f"minSlices {lo}"
            )
        if hi is not None and to_slices is not None and to_slices > hi:
            violations.append(
                f"autoscaler: {key} resized to {to_slices} above "
                f"maxSlices {hi}"
            )
        if direction == "shrink" and entry.get("credited_checkpoint") is None:
            violations.append(
                f"autoscaler: {key} shrunk to {to_slices} without a "
                "credited fresh checkpoint"
            )
        at = entry.get("at")
        cooldown_until = entry.get("cooldown_until")
        if (
            at is not None and cooldown_until is not None
            and at < cooldown_until
        ):
            violations.append(
                f"autoscaler: {key} resized at {at:.3f} inside its "
                f"cooldown window (until {cooldown_until:.3f})"
            )
        prev = entry.get("prev_resize_at")
        dwell = entry.get("dwell_seconds")
        if (
            at is not None and prev is not None and dwell is not None
            and (at - prev) < dwell - 1e-9
        ):
            violations.append(
                f"autoscaler: {key} resized {at - prev:.3f}s after its "
                f"previous resize (< dwell {dwell}s)"
            )
    if cluster is not None:
        for kind in kinds:
            for job in cluster.list_jobs(kind, namespace):
                spec = job.get("spec") or {}
                elastic = spec.get("elastic")
                if elastic is None:
                    continue
                name = (job.get("metadata") or {}).get("name", "?")
                num_slices = int(spec.get("numSlices") or 1)
                lo = int(elastic.get("minSlices") or 1)
                hi = elastic.get("maxSlices")
                if num_slices < lo:
                    violations.append(
                        f"autoscaler: live job {name} has numSlices "
                        f"{num_slices} below minSlices {lo}"
                    )
                if hi is not None and num_slices > int(hi):
                    violations.append(
                        f"autoscaler: live job {name} has numSlices "
                        f"{num_slices} above maxSlices {hi}"
                    )
    return violations


def check_fleet_invariants(
    *,
    arrivals: int,
    completed: int,
    running: int,
    queued: int,
    preempt_marks: int,
    preempt_acks: int,
    queued_waits: Sequence[Tuple[str, float, int]] = (),
    aging_seconds: float = 300.0,
    resync_period: float = 60.0,
    admission_snapshot: Optional[dict] = None,
    running_pods: Optional[int] = None,
    admits_in_window: Optional[int] = None,
) -> List[str]:
    """Fleet-level invariants — aggregate properties the per-job and
    per-arbiter checkers cannot see, audited from the fleet-sim engine's
    own counters plus the admission snapshot:

    - conservation: no job is ever lost — every arrival is exactly one
      of completed / running / queued at all times;
    - ledger exactly-once in aggregate: every counted preemption mark
      was acknowledged exactly once (marks == acks across the fleet);
    - no lost wakeups: every gang the ENGINE considers queued is
      registered waiting (or pending-preempt) in the arbiter — a queued
      job the arbiter has forgotten can never be admitted again, which
      is exactly the "stuck QUEUED" failure this invariant hunts (a
      backlogged-but-draining fleet is NOT stuck: long waits under
      contention are the scheduler working);
    - progress: when the oldest waiter is past its aging bound AND fits
      the free pool, the window since the last sweep must have admitted
      something — aging guarantees escalation, so a whole sweep window
      with free capacity, an aged head, and zero admissions means the
      pump stopped being driven;
    - fleet-wide capacity: the engine's live pod count never exceeds
      the declared schedulable pool (`queued_waits` carries each queued
      gang's (key, wait_seconds, member_count)).
    """
    violations: List[str] = []
    accounted = completed + running + queued
    if accounted != arrivals:
        violations.append(
            f"fleet: conservation broken — {arrivals} arrivals but "
            f"{accounted} accounted (completed={completed} "
            f"running={running} queued={queued}); jobs were lost or "
            "double-counted"
        )
    if preempt_acks != preempt_marks:
        violations.append(
            f"fleet: preemption ledger not exactly-once in aggregate — "
            f"{preempt_marks} counted marks vs {preempt_acks} acks"
        )
    snap = admission_snapshot or {}
    capacity = snap.get("capacity") or {}
    pod_capacity: Optional[float] = None
    if "pods" in capacity:
        try:
            pod_capacity = float(Fraction(str(capacity["pods"])))
        except (ValueError, ZeroDivisionError):
            pod_capacity = None
    if pod_capacity is not None and running_pods is not None:
        if running_pods > pod_capacity + 1e-9:
            violations.append(
                f"fleet: capacity exceeded — {running_pods} live pods "
                f"against a schedulable pool of {pod_capacity:g}"
            )
    usage = snap.get("usage") or {}
    if pod_capacity is not None and "pods" in usage:
        try:
            used = float(Fraction(str(usage["pods"])))
        except (ValueError, ZeroDivisionError):
            used = 0.0
        if used > pod_capacity + 1e-9:
            violations.append(
                f"fleet: admission usage {used:g} pods exceeds "
                f"capacity {pod_capacity:g}"
            )
        free = pod_capacity - used
    else:
        free = None
    if admission_snapshot is not None and queued_waits:
        registered = {
            entry.get("key") for entry in snap.get("waiting") or []
        }
        admitted_keys = {
            entry.get("key") for entry in snap.get("admitted") or []
        }
        for key, waited, _members in queued_waits:
            if waited <= 2.0 * resync_period:
                continue  # redelivery slack: a fresh requeue may not have synced
            if key not in registered and key not in admitted_keys:
                violations.append(
                    f"fleet: {key} is QUEUED in the engine but unknown "
                    f"to the arbiter after {waited:.0f}s — lost wakeup"
                )
    if queued_waits and admits_in_window == 0:
        stuck_bound = aging_seconds + 2.0 * resync_period
        oldest_key, oldest_wait, oldest_members = max(
            queued_waits, key=lambda q: q[1]
        )
        if oldest_wait > stuck_bound and (
                free is None or oldest_members <= free + 1e-9):
            violations.append(
                f"fleet: no admissions for a whole sweep window while "
                f"{oldest_key} has waited {oldest_wait:.0f}s (> aging "
                f"{aging_seconds:g}s + 2x resync {resync_period:g}s) and "
                f"its {oldest_members} pods fit the free pool — the pump "
                "is not being driven"
            )
    return violations


def dump_trace(tracer, label: str) -> Optional[str]:
    """Write the tracer's full export into build/ (override the directory
    with TRACE_DUMP_DIR) for post-mortem; returns the path, or None
    without a tracer / on any write failure — a dump must never mask the
    assertion it decorates."""
    if tracer is None:
        return None
    try:
        directory = os.environ.get("TRACE_DUMP_DIR", "build")
        os.makedirs(directory, exist_ok=True)
        slug = re.sub(r"[^A-Za-z0-9_.-]+", "-", label).strip("-") or "trace"
        path = os.path.join(directory, f"trace_{slug}.json")
        with open(path, "w") as f:
            f.write(tracer.export_json())
        return path
    except Exception:  # noqa: BLE001 — best-effort post-mortem artifact
        return None


def check_job_invariants(
    cluster,
    kinds: Sequence[str],
    namespace: Optional[str] = None,
    expect_ledgers: Optional[Dict[str, Dict[str, int]]] = None,
) -> List[str]:
    """Run every invariant over all jobs of `kinds` (plus their
    dependents) and return the violations."""
    jobs: List[dict] = []
    for kind in kinds:
        jobs.extend(cluster.list_jobs(kind, namespace))
    violations: List[str] = []
    for job in jobs:
        violations.extend(check_condition_invariants(job))
        violations.extend(check_ledger_invariants(job, expect_ledgers))
    violations.extend(
        check_dependents_invariants(cluster, jobs, namespace=namespace)
    )
    return violations


def assert_invariants(
    cluster,
    kinds: Sequence[str],
    namespace: Optional[str] = None,
    expect_ledgers: Optional[Dict[str, Dict[str, int]]] = None,
    tracer=None,
    label: str = "invariants",
    admission=None,
    autoscaler=None,
) -> None:
    violations = check_job_invariants(
        cluster, kinds, namespace=namespace, expect_ledgers=expect_ledgers
    )
    if tracer is not None:
        violations.extend(check_span_invariants(tracer.export()))
    if admission is not None:
        violations.extend(
            check_admission_invariants(
                admission, cluster=cluster, kinds=kinds, namespace=namespace
            )
        )
    if autoscaler is not None:
        violations.extend(
            check_autoscaler_invariants(
                autoscaler, cluster=cluster, kinds=kinds, namespace=namespace
            )
        )
    if not violations:
        return
    message = "invariant violations:\n  " + "\n  ".join(violations)
    path = dump_trace(tracer, label)
    if path:
        message += f"\n  trace dump: {path}"
    raise AssertionError(message)
