"""On-demand g++ build + ctypes load for the native components."""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

_log = logging.getLogger(__name__)
_lock = threading.Lock()
_cache: dict = {}

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))


def _build_dir() -> str:
    """Per-user, 0700 cache dir. The .so here gets dlopen'd into the
    process: a world-shared predictable path would let any local user
    pre-place a library at the (computable) digest name. Ownership is
    verified too, in case the path predates us with another owner."""
    d = os.environ.get("TPU_OPERATOR_NATIVE_CACHE") or os.path.join(
        tempfile.gettempdir(), f"tf-operator-tpu-native-{os.getuid()}"
    )
    os.makedirs(d, mode=0o700, exist_ok=True)
    st = os.stat(d)
    # Ownership AND permissions: exist_ok skips the mode on a pre-existing
    # dir, so a user-owned but group/world-writable path would still let
    # another local user pre-place the .so at its computable digest name.
    if st.st_uid != os.getuid() or (st.st_mode & 0o022):
        d = tempfile.mkdtemp(prefix="tf-operator-tpu-native-")
    return d


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """Compile native/<name>.cc (cached by source hash) and dlopen it.
    Returns None when the toolchain or compile fails — callers fall back
    to their Python implementation."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_SRC_DIR, f"{name}.cc")
        try:
            with open(src, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
            out = os.path.join(_build_dir(), f"{name}-{digest}.so")
            if not os.path.exists(out):
                tmp = f"{out}.build-{os.getpid()}"
                cmd = [
                    "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                    "-pthread", src, "-o", tmp,
                ]
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                os.replace(tmp, out)  # atomic: concurrent builders race safely
            lib = ctypes.CDLL(out)
        except (OSError, subprocess.SubprocessError) as exc:
            _log.warning("native %s unavailable (%s); using Python fallback", name, exc)
            lib = None
        _cache[name] = lib
        return lib
