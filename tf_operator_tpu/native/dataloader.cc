// Host-side token data loader: mmap'd shard files + a background prefetch
// ring, exposed through a C ABI consumed via ctypes (native/loader.py).
//
// Why native: the operator's compute path is JAX/XLA, but keeping the MXU
// fed is a HOST problem — batch assembly from disk must overlap with the
// device step and never block the Python thread that dispatches it. The
// reference (a Go control plane) has no data path at all (SURVEY.md §2:
// workloads own IO); this is the TPU framework's equivalent of the
// framework-owned native input pipelines its workloads would bring.
//
// File format ("tokens v1"): raw little-endian token ids, dtype int32 or
// uint16, no header — the Python side passes dtype and the file length
// defines the token count. Readers slice fixed windows of seq+1 tokens:
// window w starts at ((w * stride + offset) % usable) where usable =
// n_tokens - (seq+1); stride is a large odd constant so successive windows
// decorrelate without an index shuffle allocation.
//
// Distributed: each process opens the same file with (process_id,
// num_processes); window ids advance by num_processes so shards are
// disjoint and the union covers the stream.
//
// Threading: one producer thread fills a ring of `depth` batch buffers;
// next() blocks only when the producer is behind. No locks on the hot
// path beyond the ring's mutex/condvar handoff.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Ring {
  std::vector<std::vector<int32_t>> slots;
  std::vector<bool> full;
  size_t head = 0;  // next slot the consumer reads
  size_t tail = 0;  // next slot the producer fills
  std::mutex mu;
  std::condition_variable can_produce;
  std::condition_variable can_consume;
};

struct Loader {
  int fd = -1;
  const uint8_t* data = nullptr;
  size_t file_bytes = 0;
  int64_t n_tokens = 0;
  int dtype_bytes = 4;  // 4 = int32, 2 = uint16
  int64_t batch = 0;
  int64_t seq = 0;        // window = seq + 1 tokens
  int64_t process_id = 0;
  int64_t num_processes = 1;
  std::atomic<int64_t> window{0};
  Ring ring;
  std::thread producer;
  std::atomic<bool> stop{false};
};

constexpr int64_t kStride = 1000003;  // large odd prime: decorrelated windows

int64_t usable(const Loader* l) {
  int64_t u = l->n_tokens - (l->seq + 1);
  // Degenerate stride cycle: if u divides kStride's multiples exactly
  // ((w*kStride) mod u visits only u/kStride offsets), nudge u so the
  // prime stride is coprime again. Mirrored in train/data.py.
  if (u % kStride == 0) --u;
  return u;
}

void fill_batch(Loader* l, int32_t* out) {
  const int64_t win = l->seq + 1;
  for (int64_t b = 0; b < l->batch; ++b) {
    const int64_t w = l->window.fetch_add(1) * l->num_processes + l->process_id;
    const int64_t start = ((w * kStride) % usable(l) + usable(l)) % usable(l);
    if (l->dtype_bytes == 4) {
      std::memcpy(out + b * win,
                  reinterpret_cast<const int32_t*>(l->data) + start,
                  win * sizeof(int32_t));
    } else {
      const uint16_t* src = reinterpret_cast<const uint16_t*>(l->data) + start;
      int32_t* dst = out + b * win;
      for (int64_t i = 0; i < win; ++i) dst[i] = static_cast<int32_t>(src[i]);
    }
  }
}

void producer_loop(Loader* l) {
  for (;;) {
    std::unique_lock<std::mutex> lk(l->ring.mu);
    l->ring.can_produce.wait(
        lk, [l] { return l->stop.load() || !l->ring.full[l->ring.tail]; });
    if (l->stop.load()) return;
    const size_t slot = l->ring.tail;
    lk.unlock();
    fill_batch(l, l->ring.slots[slot].data());
    lk.lock();
    l->ring.full[slot] = true;
    l->ring.tail = (slot + 1) % l->ring.slots.size();
    l->ring.can_consume.notify_one();
  }
}

}  // namespace

extern "C" {

// Returns an opaque handle, or null on failure. `skip_windows` pre-advances
// this process's window counter (checkpoint resume: windows already
// consumed must not replay).
void* tl_open(const char* path, int64_t batch, int64_t seq, int dtype_bytes,
              int64_t process_id, int64_t num_processes, int64_t depth,
              int64_t skip_windows) {
  if (dtype_bytes != 2 && dtype_bytes != 4) return nullptr;
  if (batch <= 0 || seq <= 0 || depth <= 0 || num_processes <= 0) return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    ::close(fd);
    return nullptr;
  }
  void* data = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (data == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  ::madvise(data, st.st_size, MADV_WILLNEED);

  auto* l = new Loader();
  l->fd = fd;
  l->data = static_cast<const uint8_t*>(data);
  l->file_bytes = st.st_size;
  l->dtype_bytes = dtype_bytes;
  l->n_tokens = st.st_size / dtype_bytes;
  l->batch = batch;
  l->seq = seq;
  l->process_id = process_id;
  l->num_processes = num_processes;
  l->window.store(skip_windows);
  if (usable(l) <= 0) {
    ::munmap(data, st.st_size);
    ::close(fd);
    delete l;
    return nullptr;
  }
  const size_t batch_elems = static_cast<size_t>(batch) * (seq + 1);
  l->ring.slots.assign(depth, std::vector<int32_t>(batch_elems));
  l->ring.full.assign(depth, false);
  l->producer = std::thread(producer_loop, l);
  return l;
}

// Copies the next [batch, seq+1] int32 batch into `out`; returns 0 on
// success. Single-consumer contract: tl_close must NOT be called
// concurrently with tl_next (close frees the loader) — the nonzero return
// exists only as an internal shutdown guard for the producer handoff, not
// as a sanctioned call-after-close protocol.
int tl_next(void* handle, int32_t* out) {
  auto* l = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(l->ring.mu);
  l->ring.can_consume.wait(
      lk, [l] { return l->stop.load() || l->ring.full[l->ring.head]; });
  if (l->stop.load()) return 1;
  const size_t slot = l->ring.head;
  lk.unlock();
  std::memcpy(out, l->ring.slots[slot].data(),
              l->ring.slots[slot].size() * sizeof(int32_t));
  lk.lock();
  l->ring.full[slot] = false;
  l->ring.head = (slot + 1) % l->ring.slots.size();
  l->ring.can_produce.notify_one();
  return 0;
}

int64_t tl_token_count(void* handle) {
  return static_cast<Loader*>(handle)->n_tokens;
}

void tl_close(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(l->ring.mu);
    l->stop.store(true);
  }
  l->ring.can_produce.notify_all();
  l->ring.can_consume.notify_all();
  if (l->producer.joinable()) l->producer.join();
  ::munmap(const_cast<uint8_t*>(l->data), l->file_bytes);
  ::close(l->fd);
  delete l;
}

}  // extern "C"
