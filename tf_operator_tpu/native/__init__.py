"""Native (C++) host-side components, loaded via ctypes.

Built on demand with the system toolchain (g++ is in the base image; pip
installs are not) and cached next to the source keyed by a source hash, so
a source edit rebuilds and a cold cache is a one-time ~2s compile. Every
consumer has a pure-Python fallback — the native tier is a performance
floor-raiser, never a hard dependency.
"""

from .build import load_library  # noqa: F401
