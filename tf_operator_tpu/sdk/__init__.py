"""Client SDK (L7) — programmatic job submission and monitoring.

The analog of the reference's Python SDK (sdk/python/kubeflow/tfjob):
``TFJobClient`` and friends built on one generic ``JobClient``.
"""

from .client import (
    JAXJobClient,
    JobClient,
    MXJobClient,
    PyTorchJobClient,
    TFJobClient,
    TimeoutError,
    XGBoostJobClient,
    client_for,
)

__all__ = [
    "JobClient",
    "TFJobClient",
    "PyTorchJobClient",
    "MXJobClient",
    "XGBoostJobClient",
    "JAXJobClient",
    "client_for",
    "TimeoutError",
]
