"""Generic job client + per-kind conveniences.

Reference parity: sdk/python/kubeflow/tfjob/api/tf_job_client.py —
create/get/patch/delete (:77-222), wait_for_job/wait_for_condition polling
(:223-305), is_job_running/succeeded (:321-342), get_pod_names/get_logs
(:343-441). One generic implementation serves all five kinds instead of a
swagger-generated tree per kind.

The client talks to any `cluster.base.Cluster` backend — the in-repo runtime
in tests/dev, a kube-apiserver adapter in production — so SDK code is
identical in both worlds.
"""

from __future__ import annotations

import copy
import time
from typing import Callable, Dict, List, Optional

from ..api import KINDS
from ..cluster.base import Cluster, Conflict, NotFound
from ..core import constants

TERMINAL_CONDITIONS = ("Succeeded", "Failed")


class TimeoutError(Exception):  # noqa: A001 — mirrors the reference SDK name
    pass


def _conditions(job_dict: dict) -> List[dict]:
    return ((job_dict.get("status") or {}).get("conditions")) or []


def _merge_patch(dst: dict, src: dict) -> None:
    for key, value in src.items():
        if isinstance(value, dict) and isinstance(dst.get(key), dict):
            _merge_patch(dst[key], value)
        elif value is None:
            dst.pop(key, None)
        else:
            dst[key] = value


def _default_port_for(kind: str) -> int:
    """The kind's rendezvous port (TFJob 2222, PyTorchJob 23456, ...)."""
    import importlib

    module = importlib.import_module(f"..api.{kind.lower()}", __package__)
    return module.DEFAULT_PORT


def _first_container_port(job_dict: dict) -> Optional[int]:
    """First declared containerPort in any replica template, if any."""
    spec = job_dict.get("spec") or {}
    for key, value in spec.items():
        if not key.endswith("ReplicaSpecs") or not isinstance(value, dict):
            continue
        for rspec in value.values():
            containers = (
                ((rspec or {}).get("template") or {}).get("spec") or {}
            ).get("containers") or []
            for c in containers:
                for p in c.get("ports") or []:
                    if p.get("containerPort"):
                        return int(p["containerPort"])
    return None


def _has_condition(job_dict: dict, condition_type: str) -> bool:
    return any(
        c.get("type") == condition_type and c.get("status") == "True"
        for c in _conditions(job_dict)
    )


class JobClient:
    """Create/observe/delete jobs of one kind against a cluster backend."""

    kind: str = ""

    def __init__(self, cluster: Cluster, kind: Optional[str] = None):
        self.cluster = cluster
        if kind:
            self.kind = kind
        if self.kind not in KINDS:
            raise ValueError(f"unknown job kind {self.kind!r}; known: {list(KINDS)}")

    # ------------------------------------------------------------- CRUD
    def create(self, job: dict, namespace: Optional[str] = None) -> dict:
        """Submit a job manifest (dict form, kubectl-shape)."""
        job = copy.deepcopy(job)
        job.setdefault("apiVersion", "kubeflow.org/v1")
        job.setdefault("kind", self.kind)
        if job["kind"] != self.kind:
            raise ValueError(f"manifest kind {job['kind']} != client kind {self.kind}")
        if namespace:
            job.setdefault("metadata", {})["namespace"] = namespace
        return self.cluster.create_job(job)

    def get(self, name: str, namespace: str = "default") -> dict:
        return self.cluster.get_job(self.kind, namespace, name)

    def list(self, namespace: Optional[str] = None) -> List[dict]:
        return self.cluster.list_jobs(self.kind, namespace)

    def patch(self, name: str, patch: dict, namespace: str = "default") -> dict:
        """Strategic-merge-style patch of the spec (reference :150-183).
        Retries on write conflict (the GET-merge-PUT loop every k8s patch
        client runs under optimistic concurrency). The read is the
        AUTHORITATIVE one: on a cache-backed cluster (KubeCluster with
        watches primed) a cached read would hand every retry the same stale
        resourceVersion and the loop would exhaust on phantom conflicts."""
        last: Optional[Exception] = None
        for _ in range(5):
            job = self.cluster.get_job_uncached(self.kind, namespace, name)
            _merge_patch(job, patch)
            try:
                return self.cluster.update_job(job)
            except Conflict as exc:
                last = exc
        raise last  # type: ignore[misc]

    def delete(self, name: str, namespace: str = "default") -> None:
        self.cluster.delete_job(self.kind, namespace, name)

    def suspend(self, name: str, namespace: str = "default") -> dict:
        """Tear the job down (pods, services, gang groups — on TPU the whole
        slice) without failing it; resume() brings it back with a fresh
        lifecycle window."""
        return self.patch(name, {"spec": {"runPolicy": {"suspend": True}}}, namespace)

    def resume(self, name: str, namespace: str = "default") -> dict:
        return self.patch(name, {"spec": {"runPolicy": {"suspend": False}}}, namespace)

    def scale(
        self,
        name: str,
        num_slices: int,
        namespace: str = "default",
    ) -> dict:
        """Elastic resize of a JAXJob in whole-slice units: patches numSlices
        and the Worker replica count together (they must stay consistent —
        api/jaxjob.py validate). The controller restarts the gang with the
        new world env; the workload resumes from its checkpoint."""
        if self.kind != "JAXJob":
            raise ValueError(
                f"scale() resizes JAXJobs in slice units; this client is for "
                f"{self.kind} (patch replicas directly instead)"
            )
        last: Optional[Exception] = None
        for _ in range(5):
            try:
                return self._scale_once(name, num_slices, namespace)
            except Conflict as exc:
                last = exc
        raise last  # type: ignore[misc]

    def _scale_once(self, name: str, num_slices: int, namespace: str) -> dict:
        # Uncached read: same stale-resourceVersion hazard as patch().
        job = self.cluster.get_job_uncached(self.kind, namespace, name)
        spec = job.get("spec", {})
        # `is None`, not truthiness: `elastic: {}` is a valid declaration
        # (all-default bounds) and the controller treats it as elastic.
        if spec.get("elastic") is None:
            raise ValueError(
                f"JAXJob {namespace}/{name} is not elastic (spec.elastic unset); "
                "the controller will not restart a fixed-world job for a resize"
            )
        replicas = (
            (spec.get("jaxReplicaSpecs") or {}).get("Worker") or {}
        ).get("replicas")
        old_slices = spec.get("numSlices") or 1
        patch: dict = {"spec": {"numSlices": num_slices}}
        if replicas is not None:
            if replicas % max(1, old_slices) != 0:
                # A stored Worker count that is not slice-divisible means
                # hosts-per-slice is unknowable — silently skipping the
                # replicas patch (the old behavior) shipped a numSlices
                # that disagreed with the worker count and either failed
                # validation server-side or, worse, re-split the same
                # workers over a different slice count. Refuse with a
                # typed error BEFORE anything reaches the store.
                from ..api.defaulting import ValidationError

                raise ValidationError(
                    f"JAXJob {namespace}/{name} has {replicas} workers "
                    f"over {old_slices} slice(s) — not slice-divisible, "
                    "so scale() cannot derive hosts-per-slice; fix the "
                    "stored spec (workers must be a multiple of "
                    "numSlices) before resizing"
                )
            per_slice = replicas // max(1, old_slices)
            patch["spec"]["jaxReplicaSpecs"] = {
                "Worker": {"replicas": per_slice * num_slices}
            }
        mesh = spec.get("mesh") or {}
        if "slice" in mesh:
            # A global mesh carries the DCN axis explicitly; rescale it.
            # (A per-slice mesh — no slice axis — is resize-stable as-is.)
            patch["spec"]["mesh"] = {**mesh, "slice": num_slices}
        # Reject an invalid resize HERE, before it reaches the store — a
        # bad patch on a running job must not push it to a terminal Failed
        # (the controller marks any invalid live spec Failed, reference
        # issue-#561 semantics; the apiserver-side guard is this client).
        candidate = copy.deepcopy(job)
        _merge_patch(candidate, patch)
        cls, set_defaults, validate = KINDS[self.kind]
        parsed = cls.parse(candidate)
        set_defaults(parsed)
        validate(parsed.spec)
        # Write exactly the validated object (optimistic concurrency via
        # resourceVersion): a re-GET inside patch() could merge onto a spec
        # another writer changed after validation.
        return self.cluster.update_job(candidate)

    # ------------------------------------------------------------ waiting
    def wait_for_condition(
        self,
        name: str,
        expected_conditions: List[str],
        namespace: str = "default",
        timeout: float = 600,
        polling_interval: float = 0.1,
        status_callback: Optional[Callable[[dict], None]] = None,
    ) -> dict:
        """Poll until any expected condition is True (reference :223-270)."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                job = self.get(name, namespace)
            except NotFound:
                job = None
            if job is not None:
                if status_callback:
                    status_callback(job)
                for cond in expected_conditions:
                    if _has_condition(job, cond):
                        return job
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"timeout waiting for {self.kind} {namespace}/{name} to reach "
                    f"{expected_conditions}; last conditions: "
                    f"{[c.get('type') for c in _conditions(job or {})]}"
                )
            time.sleep(polling_interval)

    def wait_for_job(
        self,
        name: str,
        namespace: str = "default",
        timeout: float = 600,
        polling_interval: float = 0.1,
        status_callback: Optional[Callable[[dict], None]] = None,
    ) -> dict:
        """Wait until terminal (Succeeded or Failed; reference :271-305)."""
        return self.wait_for_condition(
            name,
            list(TERMINAL_CONDITIONS),
            namespace=namespace,
            timeout=timeout,
            polling_interval=polling_interval,
            status_callback=status_callback,
        )

    def wait_for_deletion(
        self, name: str, namespace: str = "default", timeout: float = 600,
        polling_interval: float = 0.05,
    ) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                self.get(name, namespace)
            except NotFound:
                return
            time.sleep(polling_interval)
        raise TimeoutError(f"timeout waiting for {namespace}/{name} deletion")

    def watch(
        self,
        name: str,
        namespace: str = "default",
        timeout: float = 600,
        polling_interval: float = 0.1,
    ):
        """Generator yielding the job dict on every condition transition,
        ending when the job is terminal or deleted (the reference's
        TFJobWatch / get-with-watch, tf_job_client.py:98-117)."""
        deadline = time.monotonic() + timeout
        seen: Optional[str] = None
        while time.monotonic() < deadline:
            try:
                job = self.get(name, namespace)
            except NotFound:
                return
            conds = _conditions(job)
            latest = conds[-1]["type"] if conds else None
            if latest != seen:
                seen = latest
                yield job
                if latest in TERMINAL_CONDITIONS:
                    return
            time.sleep(polling_interval)
        raise TimeoutError(f"watch timeout on {self.kind} {namespace}/{name}")

    # ------------------------------------------------------------- events
    def get_events(self, name: str, namespace: str = "default") -> List:
        """Cluster events recorded against this job."""
        return self.cluster.list_events(f"{self.kind}/{namespace}/{name}")

    def get_creation_failures(self, name: str, namespace: str = "default") -> List[str]:
        """Warning-event messages for failed pod/service creation (reference
        get_creation_failures_from_tfjob, tf_job_client.py:363-401)."""
        return [
            e.message
            for e in self.get_events(name, namespace)
            if e.type == "Warning" and "FailedCreate" in e.reason
        ]

    # ---------------------------------------------------- fault injection
    def terminate_replica(
        self,
        name: str,
        replica_type: str = "worker",
        replica_index: int = 0,
        exit_code: int = 0,
        port: int = 0,
        namespace: str = "default",
        timeout: float = 10.0,
    ) -> None:
        """Ask a replica running the controllable test-server to exit with
        `exit_code` via its /exit endpoint (the reference drives the same
        flask endpoint through the apiserver proxy, tf_job_client.py:301-351).
        Exercises shutdown-policy / restart-policy paths end-to-end."""
        import urllib.request

        resolve = getattr(self.cluster, "resolve", None)
        if resolve is None:
            raise NotImplementedError(
                "terminate_replica needs a cluster backend with service "
                "resolution (LocalProcessCluster or a real cluster)"
            )
        if not port:
            job = self.get(name, namespace)
            # Declared container port, else the kind's default port.
            port = _first_container_port(job) or _default_port_for(self.kind)
        # Canonical service-name builder (honors CUSTOM_CLUSTER_DOMAIN), the
        # same one the operator's env injection uses.
        from ..bootstrap.tf_config import replica_service_host

        host = replica_service_host(name, namespace, replica_type.lower(), replica_index)
        ip, p = resolve(host, port)
        deadline = time.monotonic() + timeout
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://{ip}:{p}/exit?exitCode={exit_code}", timeout=2
                ):
                    return
            except Exception as exc:  # noqa: BLE001 — replica may be booting
                last = exc
                time.sleep(0.1)
        raise TimeoutError(f"terminate_replica: {host}:{p} unreachable: {last}")

    # ------------------------------------------------------------- status
    def get_job_status(self, name: str, namespace: str = "default") -> Optional[str]:
        """Latest condition type (reference get_job_status :306-320)."""
        conds = _conditions(self.get(name, namespace))
        return conds[-1]["type"] if conds else None

    def is_job_running(self, name: str, namespace: str = "default") -> bool:
        return self.get_job_status(name, namespace) == "Running"

    def is_job_succeeded(self, name: str, namespace: str = "default") -> bool:
        return _has_condition(self.get(name, namespace), "Succeeded")

    def is_job_failed(self, name: str, namespace: str = "default") -> bool:
        return _has_condition(self.get(name, namespace), "Failed")

    # --------------------------------------------------------------- pods
    def get_pod_names(
        self,
        name: str,
        namespace: str = "default",
        master: bool = False,
        replica_type: Optional[str] = None,
        replica_index: Optional[int] = None,
    ) -> List[str]:
        """Names of this job's pods, optionally filtered (reference :343-402)."""
        labels: Dict[str, str] = {
            constants.LABEL_GROUP_NAME: constants.GROUP_NAME,
            constants.LABEL_JOB_NAME: name,
        }
        if master:
            labels[constants.LABEL_JOB_ROLE] = constants.JOB_ROLE_MASTER
        if replica_type:
            labels[constants.LABEL_REPLICA_TYPE] = replica_type.lower()
        if replica_index is not None:
            labels[constants.LABEL_REPLICA_INDEX] = str(replica_index)
        pods = self.cluster.list_pods(namespace, labels=labels)
        return sorted(p.metadata.name for p in pods)

    def get_logs(
        self,
        name: str,
        namespace: str = "default",
        master: bool = True,
        replica_type: Optional[str] = None,
        replica_index: Optional[int] = None,
        follow: bool = False,
        timeout: Optional[float] = None,
    ):
        """Pod name -> log text. Defaults to the master pod, falling back to
        all pods when no master exists (reference get_logs :403-441).

        With ``follow=True``, returns an iterator of ``(pod_name, line)``
        multiplexing every selected replica's live stream (the reference
        follows multiple pods' streams, tf_job_client.py:387-441); it ends
        when every followed pod terminates, or at ``timeout`` seconds."""
        pod_names = self.get_pod_names(
            name, namespace, master=master,
            replica_type=replica_type, replica_index=replica_index,
        )
        if not pod_names and master:
            pod_names = self.get_pod_names(
                name, namespace, replica_type=replica_type, replica_index=replica_index
            )
        if follow:
            return self._follow_logs(namespace, sorted(pod_names), timeout)
        return {p: self.cluster.get_pod_log(namespace, p) for p in pod_names}

    def _follow_logs(self, namespace: str, pod_names, timeout: Optional[float]):
        """One reader thread per pod feeding a shared bounded queue; lines
        yield in arrival order, tagged with their pod. When the consumer
        stops (timeout, break, GC of the generator), readers are signalled
        and wind down — no leaked connections or unbounded buffering. A pod
        that vanishes mid-follow ends its stream quietly (matching the
        polling backend); other stream errors are logged, never injected
        into the output as fake log lines."""
        import logging
        import queue as queue_mod
        import threading
        import time as time_mod

        out: queue_mod.Queue = queue_mod.Queue(maxsize=1024)
        stopped = threading.Event()
        sentinel = object()
        log = logging.getLogger(__name__)

        def emit(item) -> bool:
            while not stopped.is_set():
                try:
                    out.put(item, timeout=0.2)
                    return True
                except queue_mod.Full:
                    continue
            return False

        def reader(pod: str) -> None:
            from ..cluster.base import NotFound

            buf = ""
            try:
                for chunk in self.cluster.stream_pod_log(
                    namespace, pod, follow=True, stop=stopped
                ):
                    if stopped.is_set():
                        return
                    buf += chunk
                    while "\n" in buf:
                        line, buf = buf.split("\n", 1)
                        if not emit((pod, line)):
                            return
            except NotFound:
                pass  # pod vanished mid-follow: clean end of stream
            except Exception:  # noqa: BLE001 — log, don't fake pod output
                log.warning("log stream for %s/%s failed", namespace, pod,
                            exc_info=True)
            finally:
                if buf:
                    emit((pod, buf))
                emit((pod, sentinel))

        threads = [
            threading.Thread(target=reader, args=(p,), daemon=True,
                             name=f"log-follow-{p}")
            for p in pod_names
        ]
        for t in threads:
            t.start()
        alive = len(threads)
        deadline = (
            time_mod.monotonic() + timeout if timeout is not None else None
        )
        try:
            while alive:
                wait = 0.2
                if deadline is not None:
                    wait = min(wait, deadline - time_mod.monotonic())
                    if wait <= 0:
                        return
                try:
                    pod, item = out.get(timeout=wait)
                except queue_mod.Empty:
                    continue
                if item is sentinel:
                    alive -= 1
                    continue
                yield pod, item
        finally:
            stopped.set()


class TFJobClient(JobClient):
    kind = "TFJob"


class PyTorchJobClient(JobClient):
    kind = "PyTorchJob"


class MXJobClient(JobClient):
    kind = "MXJob"


class XGBoostJobClient(JobClient):
    kind = "XGBoostJob"


class JAXJobClient(JobClient):
    kind = "JAXJob"


_CLIENTS = {
    cls.kind: cls
    for cls in (TFJobClient, PyTorchJobClient, MXJobClient, XGBoostJobClient, JAXJobClient)
}


def client_for(kind: str, cluster: Cluster) -> JobClient:
    try:
        return _CLIENTS[kind](cluster)
    except KeyError:
        raise ValueError(f"unknown job kind {kind!r}; known: {list(_CLIENTS)}")
