"""Shared defaulting/validation helpers used by every job kind.

Reference parity: pkg/apis/*/v1/defaults.go (setDefaultPort,
setDefaultReplicas, setTypeNamesToCamelCase) and
pkg/apis/*/validation/validation.go.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from .common import ReplicaSpec, ReplicaType, RunPolicy
from .k8s import ContainerPort, PodSpec


class ValidationError(ValueError):
    """Raised when a job spec fails admission-style validation."""


def set_default_port(spec: PodSpec, container_name: str, port_name: str, port: int) -> None:
    """Inject the default rendezvous port into the framework container if the
    user did not declare one (reference defaults.go:setDefaultPort)."""
    if not spec.containers:
        return
    index = 0
    for i, container in enumerate(spec.containers):
        if container.name == container_name:
            index = i
            break
    for p in spec.containers[index].ports:
        if p.name == port_name:
            return
    spec.containers[index].ports.append(ContainerPort(name=port_name, container_port=port))


def set_default_replicas(spec: ReplicaSpec, default_restart_policy: str) -> None:
    """replicas -> 1, restart policy -> framework default
    (reference defaults.go:setDefaultReplicas)."""
    if spec.replicas is None:
        spec.replicas = 1
    if not spec.restart_policy:
        spec.restart_policy = default_restart_policy


def normalize_replica_type_names(
    specs: Dict[ReplicaType, ReplicaSpec], canonical: Iterable[ReplicaType]
) -> None:
    """Case-normalize user-supplied replica-type keys to their canonical
    camel-case spelling (reference defaults.go:setTypeNamesToCamelCase)."""
    for typ in canonical:
        for t in list(specs.keys()):
            if t != typ and t.lower() == typ.lower():
                specs[typ] = specs.pop(t)
                break


def _positive_int(value) -> bool:
    # bool is an int subclass; `progressDeadlineSeconds: true` must not
    # slip through as 1.
    return isinstance(value, int) and not isinstance(value, bool) and value > 0


def validate_scheduling_policy(
    run_policy: RunPolicy, kind: str,
    specs: Optional[Dict[ReplicaType, ReplicaSpec]] = None,
) -> None:
    """Admission validation of runPolicy.schedulingPolicy — previously
    these passed through silently and failed LATE in the controller (a
    minAvailable above the topology produced a gang no pod set can ever
    satisfy; an unknown priority class silently landed in the default
    band; a malformed minResources quantity crashed the PodGroup
    aggregation mid-reconcile). With the gang-admission layer these
    fields decide capacity arbitration, so they are typed errors at
    admission time:

    - minAvailable: positive integer, and (when the replica topology is
      known) at most the total declared replicas;
    - priorityClass: a known band name, a bare non-negative integer, or
      any legal PriorityClass name (which rides the default band —
      foreign class names keep flowing to the gang scheduler verbatim);
      rejected only when the value could never name a PriorityClass
      (negative, non-DNS-shaped — core/admission.py
      parse_priority_class). Deliberate upgrade note: a STORED job
      carrying a non-DNS value is failed on its next sync — such a
      value can never match a real PriorityClass object (k8s rejects
      the object name), so the job could never gang-schedule anyway;
      a typed early failure beats an eternal unschedulable Pending;
    - minResources: every quantity must parse as a Kubernetes
      resource.Quantity and be non-negative."""
    sp = run_policy.scheduling_policy
    if sp is None:
        return
    ma = sp.min_available
    if ma is not None:
        if not _positive_int(ma):
            raise ValidationError(
                f"{kind}Spec is not valid: schedulingPolicy.minAvailable "
                f"must be a positive integer, got {ma!r}"
            )
        if specs:
            total = sum(
                (s.replicas or 0) for s in specs.values() if s is not None
            )
            if total and ma > total:
                raise ValidationError(
                    f"{kind}Spec is not valid: schedulingPolicy.minAvailable "
                    f"({ma}) exceeds the declared replica topology ({total} "
                    "replica(s)) — the gang could never be satisfied"
                )
    if sp.priority_class:
        from ..core.admission import parse_priority_class

        try:
            parse_priority_class(sp.priority_class)
        except ValueError:
            raise ValidationError(
                f"{kind}Spec is not valid: schedulingPolicy.priorityClass "
                f"{sp.priority_class!r} is not a priority band, a "
                "non-negative integer, or a legal PriorityClass name"
            )
    for name, qty in (sp.min_resources or {}).items():
        from ..core.job_controller import parse_quantity

        try:
            value = parse_quantity(qty)
        except (ValueError, ZeroDivisionError, TypeError):
            raise ValidationError(
                f"{kind}Spec is not valid: schedulingPolicy.minResources"
                f"[{name}] = {qty!r} is not a valid resource quantity"
            )
        if value < 0:
            raise ValidationError(
                f"{kind}Spec is not valid: schedulingPolicy.minResources"
                f"[{name}] = {qty!r} must be non-negative"
            )
    # throughputRatios (the gavel placement input): generation ->
    # positive finite number. Zero is rejected — "this job cannot run on
    # that generation" is expressed by capacity (it will simply never be
    # placed there profitably), and a zero ratio would make the
    # effective-throughput objective divide the job out of existence;
    # negatives/NaN/inf could invert or wedge the greedy comparison.
    for gen, ratio in (sp.throughput_ratios or {}).items():
        if not isinstance(gen, str) or not gen.strip():
            raise ValidationError(
                f"{kind}Spec is not valid: schedulingPolicy."
                f"throughputRatios has a non-string generation key "
                f"{gen!r}"
            )
        if isinstance(ratio, bool) or not isinstance(ratio, (int, float)):
            raise ValidationError(
                f"{kind}Spec is not valid: schedulingPolicy."
                f"throughputRatios[{gen}] = {ratio!r} is not a number"
            )
        ratio = float(ratio)
        if not (0.0 < ratio < float("inf")) or ratio != ratio:
            raise ValidationError(
                f"{kind}Spec is not valid: schedulingPolicy."
                f"throughputRatios[{gen}] = {ratio!r} must be a positive "
                "finite number"
            )


def validate_run_policy(
    run_policy: RunPolicy, kind: str,
    specs: Optional[Dict[ReplicaType, ReplicaSpec]] = None,
) -> None:
    """Admission validation of the gang-liveness deadlines (the rest of
    RunPolicy predates this check and keeps its permissive parsing).

    Both deadlines default to unset (off): existing TF/PyTorch/MX/XGBoost
    jobs that never heartbeat can never stall-restart. Opt-in semantics:
    `rendezvousDeadlineSeconds` requires `progressDeadlineSeconds` — the
    rendezvous bound is meaningless for a job that has not opted into the
    heartbeat protocol, and accepting it alone would arm a deadline no
    heartbeat can ever satisfy."""
    pdl = run_policy.progress_deadline_seconds
    rdl = run_policy.rendezvous_deadline_seconds
    if pdl is not None and not _positive_int(pdl):
        raise ValidationError(
            f"{kind}Spec is not valid: runPolicy.progressDeadlineSeconds "
            f"must be a positive integer, got {pdl!r}"
        )
    if rdl is not None:
        if not _positive_int(rdl):
            raise ValidationError(
                f"{kind}Spec is not valid: runPolicy.rendezvousDeadlineSeconds "
                f"must be a positive integer, got {rdl!r}"
            )
        if pdl is None:
            raise ValidationError(
                f"{kind}Spec is not valid: runPolicy.rendezvousDeadlineSeconds "
                "requires runPolicy.progressDeadlineSeconds (the job must opt "
                "into heartbeat liveness as a whole)"
            )
    fda = run_policy.force_delete_after_seconds
    if fda is not None and not _positive_int(fda):
        # Same opt-in discipline as the liveness deadlines: unset = the
        # operator never force-deletes (k8s-safe default); set = a bound
        # on how long a stuck-Terminating pod may block gang recovery.
        raise ValidationError(
            f"{kind}Spec is not valid: runPolicy.forceDeleteAfterSeconds "
            f"must be a positive integer, got {fda!r}"
        )
    # Scheduling-policy hardening rides the same entry point every kind
    # already calls; `specs` is optional so legacy callers keep working
    # (they just skip the topology bound).
    validate_scheduling_policy(run_policy, kind, specs)


def validate_replica_specs(
    specs: Dict[ReplicaType, ReplicaSpec], container_name: str, kind: str
) -> None:
    """Common validation: specs present, containers defined, images set, and
    at least one container bearing the framework's canonical name
    (reference validation/validation.go:validateV1ReplicaSpecs)."""
    if not specs:
        raise ValidationError(f"{kind}Spec is not valid")
    for rtype, value in specs.items():
        if value is None or not value.template.spec.containers:
            raise ValidationError(
                f"{kind}Spec is not valid: containers definition expected in {rtype}"
            )
        num_named = 0
        for container in value.template.spec.containers:
            if not container.image:
                raise ValidationError(
                    f"{kind}Spec is not valid: Image is undefined in the container of {rtype}"
                )
            if container.name == container_name:
                num_named += 1
        if num_named == 0:
            raise ValidationError(
                f"{kind}Spec is not valid: There is no container named "
                f"{container_name} in {rtype}"
            )
