"""Shared defaulting/validation helpers used by every job kind.

Reference parity: pkg/apis/*/v1/defaults.go (setDefaultPort,
setDefaultReplicas, setTypeNamesToCamelCase) and
pkg/apis/*/validation/validation.go.
"""

from __future__ import annotations

from typing import Dict, Iterable

from .common import ReplicaSpec, ReplicaType, RunPolicy
from .k8s import ContainerPort, PodSpec


class ValidationError(ValueError):
    """Raised when a job spec fails admission-style validation."""


def set_default_port(spec: PodSpec, container_name: str, port_name: str, port: int) -> None:
    """Inject the default rendezvous port into the framework container if the
    user did not declare one (reference defaults.go:setDefaultPort)."""
    if not spec.containers:
        return
    index = 0
    for i, container in enumerate(spec.containers):
        if container.name == container_name:
            index = i
            break
    for p in spec.containers[index].ports:
        if p.name == port_name:
            return
    spec.containers[index].ports.append(ContainerPort(name=port_name, container_port=port))


def set_default_replicas(spec: ReplicaSpec, default_restart_policy: str) -> None:
    """replicas -> 1, restart policy -> framework default
    (reference defaults.go:setDefaultReplicas)."""
    if spec.replicas is None:
        spec.replicas = 1
    if not spec.restart_policy:
        spec.restart_policy = default_restart_policy


def normalize_replica_type_names(
    specs: Dict[ReplicaType, ReplicaSpec], canonical: Iterable[ReplicaType]
) -> None:
    """Case-normalize user-supplied replica-type keys to their canonical
    camel-case spelling (reference defaults.go:setTypeNamesToCamelCase)."""
    for typ in canonical:
        for t in list(specs.keys()):
            if t != typ and t.lower() == typ.lower():
                specs[typ] = specs.pop(t)
                break


def _positive_int(value) -> bool:
    # bool is an int subclass; `progressDeadlineSeconds: true` must not
    # slip through as 1.
    return isinstance(value, int) and not isinstance(value, bool) and value > 0


def validate_run_policy(run_policy: RunPolicy, kind: str) -> None:
    """Admission validation of the gang-liveness deadlines (the rest of
    RunPolicy predates this check and keeps its permissive parsing).

    Both deadlines default to unset (off): existing TF/PyTorch/MX/XGBoost
    jobs that never heartbeat can never stall-restart. Opt-in semantics:
    `rendezvousDeadlineSeconds` requires `progressDeadlineSeconds` — the
    rendezvous bound is meaningless for a job that has not opted into the
    heartbeat protocol, and accepting it alone would arm a deadline no
    heartbeat can ever satisfy."""
    pdl = run_policy.progress_deadline_seconds
    rdl = run_policy.rendezvous_deadline_seconds
    if pdl is not None and not _positive_int(pdl):
        raise ValidationError(
            f"{kind}Spec is not valid: runPolicy.progressDeadlineSeconds "
            f"must be a positive integer, got {pdl!r}"
        )
    if rdl is not None:
        if not _positive_int(rdl):
            raise ValidationError(
                f"{kind}Spec is not valid: runPolicy.rendezvousDeadlineSeconds "
                f"must be a positive integer, got {rdl!r}"
            )
        if pdl is None:
            raise ValidationError(
                f"{kind}Spec is not valid: runPolicy.rendezvousDeadlineSeconds "
                "requires runPolicy.progressDeadlineSeconds (the job must opt "
                "into heartbeat liveness as a whole)"
            )
    fda = run_policy.force_delete_after_seconds
    if fda is not None and not _positive_int(fda):
        # Same opt-in discipline as the liveness deadlines: unset = the
        # operator never force-deletes (k8s-safe default); set = a bound
        # on how long a stuck-Terminating pod may block gang recovery.
        raise ValidationError(
            f"{kind}Spec is not valid: runPolicy.forceDeleteAfterSeconds "
            f"must be a positive integer, got {fda!r}"
        )


def validate_replica_specs(
    specs: Dict[ReplicaType, ReplicaSpec], container_name: str, kind: str
) -> None:
    """Common validation: specs present, containers defined, images set, and
    at least one container bearing the framework's canonical name
    (reference validation/validation.go:validateV1ReplicaSpecs)."""
    if not specs:
        raise ValidationError(f"{kind}Spec is not valid")
    for rtype, value in specs.items():
        if value is None or not value.template.spec.containers:
            raise ValidationError(
                f"{kind}Spec is not valid: containers definition expected in {rtype}"
            )
        num_named = 0
        for container in value.template.spec.containers:
            if not container.image:
                raise ValidationError(
                    f"{kind}Spec is not valid: Image is undefined in the container of {rtype}"
                )
            if container.name == container_name:
                num_named += 1
        if num_named == 0:
            raise ValidationError(
                f"{kind}Spec is not valid: There is no container named "
                f"{container_name} in {rtype}"
            )
