"""XGBoostJob v1 API types, defaults and validation.

Reference parity: pkg/apis/xgboost/v1/{xgboostjob_types,constants,defaults}.go
and pkg/apis/xgboost/validation/validation.go. Also drives LightGBM jobs via
WORKER_ADDRS/WORKER_PORT env (reference xgboost.go:95-107).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from .common import (
    CLEAN_POD_POLICY_RUNNING,
    JobObject,
    ReplicaSpec,
    ReplicaType,
    RunPolicy,
)
from .defaulting import (
    ValidationError,
    normalize_replica_type_names,
    set_default_port,
    set_default_replicas,
    validate_replica_specs,
    validate_run_policy,
)

# Constants (reference pkg/apis/xgboost/v1/constants.go:20-27)
KIND = "XGBoostJob"
PLURAL = "xgboostjobs"
SINGULAR = "xgboostjob"
GROUP = "kubeflow.org"
VERSION = "v1"
DEFAULT_CONTAINER_NAME = "xgboost"
DEFAULT_PORT_NAME = "xgboostjob-port"
DEFAULT_PORT = 9999
DEFAULT_RESTART_POLICY = "Never"

# Replica types (reference xgboostjob_types.go:25-30)
REPLICA_TYPE_MASTER = "Master"
REPLICA_TYPE_WORKER = "Worker"

CANONICAL_REPLICA_TYPES = (REPLICA_TYPE_MASTER, REPLICA_TYPE_WORKER)


@dataclass
class XGBoostJobSpec:
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    xgb_replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)

    __schema_required__ = ("xgbReplicaSpecs",)


@dataclass
class XGBoostJob(JobObject):
    kind: str = KIND
    spec: XGBoostJobSpec = field(default_factory=XGBoostJobSpec)

    def replica_specs(self) -> Dict[ReplicaType, ReplicaSpec]:
        return self.spec.xgb_replica_specs

    def run_policy(self) -> RunPolicy:
        return self.spec.run_policy



def set_defaults(job: XGBoostJob) -> None:
    if job.spec.run_policy.clean_pod_policy is None:
        job.spec.run_policy.clean_pod_policy = CLEAN_POD_POLICY_RUNNING
    normalize_replica_type_names(job.spec.xgb_replica_specs, CANONICAL_REPLICA_TYPES)
    for spec in job.spec.xgb_replica_specs.values():
        set_default_replicas(spec, DEFAULT_RESTART_POLICY)
        set_default_port(spec.template.spec, DEFAULT_CONTAINER_NAME, DEFAULT_PORT_NAME, DEFAULT_PORT)


def validate(spec: XGBoostJobSpec) -> None:
    """reference pkg/apis/xgboost/validation/validation.go — valid replica
    types, images set, container named `xgboost`, exactly one Master with
    replicas == 1."""
    validate_run_policy(spec.run_policy, KIND, spec.xgb_replica_specs)
    if not spec.xgb_replica_specs:
        raise ValidationError("XGBoostJobSpec is not valid")
    for rtype in spec.xgb_replica_specs:
        if rtype not in CANONICAL_REPLICA_TYPES:
            raise ValidationError(
                f"XGBoostReplicaType is {rtype} but must be one of {list(CANONICAL_REPLICA_TYPES)}"
            )
    validate_replica_specs(spec.xgb_replica_specs, DEFAULT_CONTAINER_NAME, KIND)
    master = spec.xgb_replica_specs.get(REPLICA_TYPE_MASTER)
    if master is None:
        raise ValidationError("XGBoostJobSpec is not valid: Master ReplicaSpec must be present")
    if master.replicas is not None and master.replicas != 1:
        raise ValidationError("XGBoostJobSpec is not valid: There must be only 1 master replica")
