"""Minimal Kubernetes object model.

The reference operator manipulates core/v1 Pods, Services and metadata via
k8s.io/api structs. This module provides the slice of that object model the
operator needs, as plain dataclasses with camelCase (de)serialization so
specs round-trip through YAML/JSON exactly like real manifests.

Reference parity: k8s.io/api/core/v1 types as used by
pkg/controller.v1/*/ *_controller.go and pkg/common/util in the reference.
"""

from __future__ import annotations

import copy
import dataclasses
import functools
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_CAMEL_RE = re.compile(r"_([a-z0-9])")
_SNAKE_RE = re.compile(r"(?<!^)(?=[A-Z])")


def _to_camel(name: str) -> str:
    return _CAMEL_RE.sub(lambda m: m.group(1).upper(), name)


def _to_snake(name: str) -> str:
    return _SNAKE_RE.sub("_", name).lower()


def to_dict(obj: Any) -> Any:
    """Serialize a dataclass tree to a JSON-able dict with camelCase keys.

    ``None`` values and empty containers are dropped, matching the
    ``omitempty`` behaviour of the reference's Go JSON tags.
    """
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            val = to_dict(getattr(obj, f.name))
            if val is None or val == {} or val == []:
                continue
            key = f.metadata.get("json", _to_camel(f.name))
            out[key] = val
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items() if v is not None}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def from_dict(cls: type, data: Any) -> Any:
    """Deserialize camelCase dict ``data`` into dataclass ``cls``.

    Unknown keys are ignored (K8s API machinery drops unknown fields for
    structural schemas); nested dataclass/list/dict field types are resolved
    from type hints.
    """
    if data is None:
        return None
    if not dataclasses.is_dataclass(cls):
        return data
    hints, json_names = _class_schema(cls)
    kwargs = {}
    for key, val in dict(data).items():
        fname = json_names.get(key, _to_snake(key))
        if fname not in hints:
            continue
        if val is None and not _is_optional(hints[fname]):
            # Explicit YAML null on a non-Optional field (a trailing `env:`
            # or `command:`) keeps the dataclass default — assigning None
            # would crash far from here (Container.set_env) during
            # reconcile, past the ValidationError conversion boundary.
            continue
        kwargs[fname] = _coerce(hints[fname], val)
    return cls(**kwargs)


def _is_optional(hint: Any) -> bool:
    import types
    import typing

    return (
        typing.get_origin(hint) in (typing.Union, types.UnionType)
        and type(None) in typing.get_args(hint)
    )


@functools.lru_cache(maxsize=None)
def _class_schema(cls: type):
    """Cache type hints + json-name map per class; get_type_hints re-evaluates
    stringified annotations (PEP 563) on every call otherwise."""
    import typing

    hints = typing.get_type_hints(cls)
    json_names = {}
    for f in dataclasses.fields(cls):
        json_names[f.metadata.get("json", _to_camel(f.name))] = f.name
    return hints, json_names


def _coerce(hint: Any, val: Any) -> Any:
    """Coerce ``val`` toward ``hint``; raise ValueError on type-level garbage.

    A CR that reaches the operator may carry wrong *types* (``replicas:
    "two"``, ``containers: {}``) that a full structural schema would have
    rejected server-side. Failing here with a clear message lets the
    controller map it to a Failed condition instead of crashing deep in the
    engine and hot-requeueing forever (the reference's unstructured-informer
    tolerance, pkg/common/util/v1/unstructured/informer.go:41-80).
    Unambiguous coercions (``"2"`` -> 2) are accepted the way YAML users
    expect.
    """
    import types
    import typing

    if val is None:
        return None  # explicit null = unset; nullability is validation's job
    origin = typing.get_origin(hint)
    args = typing.get_args(hint)
    if origin in (typing.Union, types.UnionType):  # Optional[X] / X | None
        inner = [a for a in args if a is not type(None)]
        return _coerce(inner[0], val) if inner else val
    if origin in (list, List):
        if not isinstance(val, (list, tuple)):
            raise ValueError(f"expected a list, got {type(val).__name__}: {val!r}")
        return [_coerce(args[0], v) for v in val] if args else list(val)
    if origin in (dict, Dict):
        if not isinstance(val, dict):
            raise ValueError(f"expected an object, got {type(val).__name__}: {val!r}")
        if args and dataclasses.is_dataclass(args[1]):
            return {k: from_dict(args[1], v) for k, v in val.items()}
        return dict(val)
    if dataclasses.is_dataclass(hint):
        if isinstance(val, dict):
            return from_dict(hint, val)
        raise ValueError(
            f"expected a {getattr(hint, '__name__', hint)} object, "
            f"got {type(val).__name__}: {val!r}"
        )
    if hint is bool:
        if isinstance(val, bool):
            return val
        if isinstance(val, str) and val.lower() in ("true", "false"):
            return val.lower() == "true"
        raise ValueError(f"expected a boolean, got {type(val).__name__}: {val!r}")
    if hint is int:
        if isinstance(val, bool):
            raise ValueError(f"expected an integer, got boolean: {val!r}")
        try:
            out = int(val)
        except (TypeError, ValueError):
            raise ValueError(f"expected an integer, got {type(val).__name__}: {val!r}")
        if isinstance(val, float) and val != out:
            raise ValueError(f"expected an integer, got non-integral number: {val!r}")
        return out
    if hint is float:
        try:
            return float(val)
        except (TypeError, ValueError):
            raise ValueError(f"expected a number, got {type(val).__name__}: {val!r}")
    return val


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: Optional[bool] = None
    block_owner_deletion: Optional[bool] = None


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    uid: str = ""
    resource_version: str = ""
    creation_timestamp: Optional[float] = None
    # k8s semantics: deletionTimestamp is the time the graceful window
    # EXPIRES (delete-request time + grace), i.e. when the object is
    # expected GONE — not when the delete was requested. The
    # stuck-terminating escalation measures its patience from this point.
    deletion_timestamp: Optional[float] = None
    # Graceful-deletion window the apiserver granted (DeleteOptions
    # gracePeriodSeconds); informational beside deletion_timestamp.
    deletion_grace_period_seconds: Optional[float] = None
    owner_references: List[OwnerReference] = field(default_factory=list)

    def controller_ref(self) -> Optional[OwnerReference]:
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""
    # downward API / configMapKeyRef / secretKeyRef / fieldRef
    value_from: Optional[Dict[str, Any]] = None

    __schema_required__ = ("name",)


@dataclass
class ContainerPort:
    name: str = ""
    container_port: int = 0
    protocol: str = ""


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    sub_path: str = ""
    read_only: Optional[bool] = None

    __schema_required__ = ("name", "mountPath")


@dataclass
class Container:
    """The consumed subset of core/v1 Container, at the granularity the
    reference's flattened CRD schema validates (manifests/base/crds/
    kubeflow.org_tfjobs.yaml containers block). Fields beyond this subset
    survive round-trips via the template-level preserve-unknown schema."""

    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    env_from: List[Dict[str, Any]] = field(default_factory=list)
    ports: List[ContainerPort] = field(default_factory=list)
    # Values stay loose (Any): resource quantities are int-or-string in
    # core/v1 (cpu: 2 and cpu: "2" are both legal) and `claims` is a list —
    # a Dict[str, Dict[str, str]] schema would 422 valid manifests.
    resources: Dict[str, Any] = field(default_factory=dict)
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    working_dir: str = ""
    image_pull_policy: str = ""
    liveness_probe: Optional[Dict[str, Any]] = None
    readiness_probe: Optional[Dict[str, Any]] = None
    startup_probe: Optional[Dict[str, Any]] = None
    security_context: Optional[Dict[str, Any]] = None
    lifecycle: Optional[Dict[str, Any]] = None

    __schema_required__ = ("name",)

    def set_env(self, name: str, value: str) -> None:
        self.env.append(EnvVar(name=name, value=str(value)))

    def get_env(self, name: str) -> Optional[str]:
        for e in self.env:
            if e.name == name:
                return e.value
        return None


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    restart_policy: str = ""
    scheduler_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    host_network: Optional[bool] = None
    subdomain: str = ""
    hostname: str = ""
    service_account_name: str = ""
    priority_class_name: str = ""
    termination_grace_period_seconds: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    volumes: List[Dict[str, Any]] = field(default_factory=list)
    affinity: Optional[Dict[str, Any]] = None
    security_context: Optional[Dict[str, Any]] = None
    image_pull_secrets: List[Dict[str, Any]] = field(default_factory=list)
    # TPU-native: pod-slice topology request (maps to GKE's
    # cloud.google.com/gke-tpu-topology nodeSelector + google.com/tpu resource)
    tolerations: List[Dict[str, Any]] = field(default_factory=list)

    __schema_required__ = ("containers",)


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class ContainerStateTerminated:
    exit_code: int = 0
    reason: str = ""
    finished_at: Optional[float] = None


@dataclass
class ContainerState:
    terminated: Optional[ContainerStateTerminated] = None
    running: Optional[Dict[str, Any]] = None
    waiting: Optional[Dict[str, Any]] = None


@dataclass
class ContainerStatus:
    name: str = ""
    state: ContainerState = field(default_factory=ContainerState)
    restart_count: int = 0


# Pod phases (k8s.io/api/core/v1 PodPhase)
POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"
POD_UNKNOWN = "Unknown"

# Pod condition types the operator consumes. DisruptionTarget is the
# k8s >=1.26 marker the kubelet/scheduler/eviction-API stamp on a pod about
# to be terminated for infrastructure reasons (preemption, node drain,
# taint eviction) — the authoritative "this was not the workload's fault"
# signal the disruption classifier keys on.
POD_CONDITION_DISRUPTION_TARGET = "DisruptionTarget"


@dataclass
class PodCondition:
    """One entry in PodStatus.conditions (core/v1 PodCondition subset)."""

    type: str = ""
    status: str = "True"
    reason: str = ""
    message: str = ""


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    conditions: List[PodCondition] = field(default_factory=list)
    container_statuses: List[ContainerStatus] = field(default_factory=list)
    start_time: Optional[float] = None
    reason: str = ""
    message: str = ""


@dataclass
class Pod:
    api_version: str = "v1"
    kind: str = "Pod"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def deep_copy(self) -> "Pod":
        return copy.deepcopy(self)


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0


@dataclass
class ServiceSpec:
    # "None" => headless, as the reference creates. JSON key is the k8s
    # spelling "clusterIP", which snake->camel conversion cannot produce.
    cluster_ip: str = field(default="", metadata={"json": "clusterIP"})
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)


@dataclass
class Service:
    api_version: str = "v1"
    kind: str = "Service"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)

    def deep_copy(self) -> "Service":
        return copy.deepcopy(self)


@dataclass
class Event:
    """A lifecycle event recorded against a job object.

    The reference emits core/v1 Events via an EventRecorder
    (e.g. SuccessfulDeleteJob / ExitedWithCode / TFJobRestarting —
    pkg/controller.v1/tensorflow/{pod.go:45-55,status.go:34-45}).
    """

    type: str = "Normal"  # Normal | Warning
    reason: str = ""
    message: str = ""
    involved_object: str = ""  # "<kind>/<namespace>/<name>"
    timestamp: Optional[float] = None


def new_owner_reference(api_version: str, kind: str, name: str, uid: str) -> OwnerReference:
    return OwnerReference(
        api_version=api_version,
        kind=kind,
        name=name,
        uid=uid,
        controller=True,
        block_owner_deletion=True,
    )
