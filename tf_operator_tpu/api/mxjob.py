"""MXJob v1 API types, defaults and validation.

Reference parity: pkg/apis/mxnet/v1/{mxjob_types,constants,defaults}.go and
pkg/apis/mxnet/validation/validation.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from .common import (
    CLEAN_POD_POLICY_RUNNING,
    JobObject,
    ReplicaSpec,
    ReplicaType,
    RunPolicy,
)
from .defaulting import (
    ValidationError,
    normalize_replica_type_names,
    set_default_port,
    set_default_replicas,
    validate_run_policy,
)
from .tpu import (
    TPUSpec,
    default_host_replicas,
    validate_accelerator,
    validate_host_count,
)

# Constants (reference pkg/apis/mxnet/v1/constants.go:20-28)
KIND = "MXJob"
PLURAL = "mxjobs"
SINGULAR = "mxjob"
GROUP = "kubeflow.org"
VERSION = "v1"
DEFAULT_CONTAINER_NAME = "mxnet"
DEFAULT_PORT_NAME = "mxjob-port"
DEFAULT_PORT = 9091
DEFAULT_RESTART_POLICY = "Never"

# Job modes (reference mxjob_types.go:26-33)
JOB_MODE_TRAIN = "MXTrain"
JOB_MODE_TUNE = "MXTune"

# Replica types (reference mxjob_types.go:35-50). The Tuner* types support
# TVM auto-tuning topologies (examples/mxnet/tune in the reference).
REPLICA_TYPE_SCHEDULER = "Scheduler"
REPLICA_TYPE_SERVER = "Server"
REPLICA_TYPE_WORKER = "Worker"
REPLICA_TYPE_TUNER_TRACKER = "TunerTracker"
REPLICA_TYPE_TUNER_SERVER = "TunerServer"
REPLICA_TYPE_TUNER = "Tuner"

CANONICAL_REPLICA_TYPES = (
    REPLICA_TYPE_SCHEDULER,
    REPLICA_TYPE_SERVER,
    REPLICA_TYPE_WORKER,
    REPLICA_TYPE_TUNER_TRACKER,
    REPLICA_TYPE_TUNER_SERVER,
    REPLICA_TYPE_TUNER,
)

# Annotation consulted for TVM tuning labels (reference mxnet.go:31-32)
TUNER_SERVER_KEY = "tuner-server-key"


@dataclass
class MXJobSpec:
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    job_mode: str = JOB_MODE_TRAIN
    mx_replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    # TPU pod-slice provisioning (north star: extend the GPU-era CRDs).
    # The Worker group becomes the slice's host pods; Scheduler/Server
    # stay CPU pods and gang with slice 0.
    tpu: Optional[TPUSpec] = None

    __schema_required__ = ("mxReplicaSpecs",)


@dataclass
class MXJob(JobObject):
    kind: str = KIND
    spec: MXJobSpec = field(default_factory=MXJobSpec)

    def replica_specs(self) -> Dict[ReplicaType, ReplicaSpec]:
        return self.spec.mx_replica_specs

    def run_policy(self) -> RunPolicy:
        return self.spec.run_policy



def contains_scheduler_spec(job: MXJob) -> bool:
    """reference mxnet.go:ContainSchedulerSpec"""
    return REPLICA_TYPE_SCHEDULER in job.spec.mx_replica_specs


def set_defaults(job: MXJob) -> None:
    if job.spec.run_policy.clean_pod_policy is None:
        job.spec.run_policy.clean_pod_policy = CLEAN_POD_POLICY_RUNNING
    if not job.spec.job_mode:
        job.spec.job_mode = JOB_MODE_TRAIN
    normalize_replica_type_names(job.spec.mx_replica_specs, CANONICAL_REPLICA_TYPES)
    for rtype, spec in job.spec.mx_replica_specs.items():
        if spec.replicas is None and rtype == REPLICA_TYPE_WORKER:
            spec.replicas = default_host_replicas(job.spec.tpu)
        set_default_replicas(spec, DEFAULT_RESTART_POLICY)
        set_default_port(spec.template.spec, DEFAULT_CONTAINER_NAME, DEFAULT_PORT_NAME, DEFAULT_PORT)


def validate(spec: MXJobSpec) -> None:
    """reference pkg/apis/mxnet/validation/validation.go — containers and
    images present, container named `mxnet`, at most one Scheduler."""
    validate_run_policy(spec.run_policy, KIND, spec.mx_replica_specs)
    if not spec.mx_replica_specs:
        raise ValidationError("MXJobSpec is not valid")
    found_scheduler = 0
    for rtype, value in spec.mx_replica_specs.items():
        if value is None or not value.template.spec.containers:
            raise ValidationError("MXJobSpec is not valid")
        if rtype == REPLICA_TYPE_SCHEDULER:
            found_scheduler += 1
        num_named = 0
        for container in value.template.spec.containers:
            if not container.image:
                raise ValidationError("MXJobSpec is not valid")
            if container.name == DEFAULT_CONTAINER_NAME:
                num_named += 1
        if num_named == 0:
            raise ValidationError(
                f"MXJobSpec is not valid: There is no container named "
                f"{DEFAULT_CONTAINER_NAME} in {rtype}"
            )
    if found_scheduler > 1:
        raise ValidationError("more than 1 scheduler found")
    if spec.tpu is not None:
        validate_accelerator(spec.tpu, KIND)
        worker = spec.mx_replica_specs.get(REPLICA_TYPE_WORKER)
        if worker is None:
            raise ValidationError(
                "MXJobSpec is not valid: spec.tpu requires a Worker replica "
                "group (the slice's host pods)"
            )
        if worker.replicas is not None:
            validate_host_count(spec.tpu, KIND, worker.replicas)
