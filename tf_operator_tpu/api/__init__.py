"""API layer: shared job vocabulary + the five job kinds.

Mirrors the reference's pkg/apis tree (SURVEY.md §2.2) with the kubeflow/common
shared types owned in-repo (SURVEY.md §2.9), plus the TPU-native JAXJob.
"""

from . import common, jaxjob, k8s, mxjob, pytorchjob, tfjob, xgboostjob
from .common import (
    JobCondition,
    JobObject,
    JobStatus,
    ReplicaSpec,
    ReplicaStatus,
    RunPolicy,
    SchedulingPolicy,
)
from .defaulting import ValidationError
from .jaxjob import JAXJob
from .mxjob import MXJob
from .pytorchjob import PyTorchJob
from .tfjob import TFJob
from .xgboostjob import XGBoostJob

# Kind registry: kind name -> (class, set_defaults, validate)
KINDS = {
    tfjob.KIND: (TFJob, tfjob.set_defaults, tfjob.validate),
    pytorchjob.KIND: (PyTorchJob, pytorchjob.set_defaults, pytorchjob.validate),
    mxjob.KIND: (MXJob, mxjob.set_defaults, mxjob.validate),
    xgboostjob.KIND: (XGBoostJob, xgboostjob.set_defaults, xgboostjob.validate),
    jaxjob.KIND: (JAXJob, jaxjob.set_defaults, jaxjob.validate),
}


def parse_job(data: dict) -> JobObject:
    """Parse a manifest dict into its typed job object by `kind`."""
    kind = data.get("kind", "")
    if kind not in KINDS:
        raise ValidationError(f"unknown job kind {kind!r}")
    cls = KINDS[kind][0]
    return cls.parse(data)
