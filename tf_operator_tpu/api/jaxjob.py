"""JAXJob v1 API — the TPU-native job kind (new; no reference counterpart).

Where the reference's TFJob models a GPU/CPU parameter-server world
(pkg/apis/tensorflow/v1/types.go), JAXJob models the TPU world directly:

- A single ``Worker`` replica group; each worker is one TPU VM host of a
  pod-slice. Worker-0's headless service is the ``jax.distributed``
  coordinator (the analog of the reference's master/chief rendezvous —
  SURVEY.md §7 build plan, stage 2).
- ``tpu``: the pod-slice request (accelerator type, topology) — the
  all-or-nothing gang unit. Replicas defaults to the host count the
  topology implies, and gang minAvailable is pinned to it: a partial
  slice is useless, unlike a partial GPU worker set.
- ``numSlices`` > 1 declares a multislice (DCN-connected) job; each slice
  is its own gang and the mesh gains a leading ``slice`` (DCN) axis.
- ``mesh``: logical axis layout the workload tier materializes via
  ``tf_operator_tpu.runtime.tpu_init`` (published to pods as JAX_MESH_SPEC).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from .common import (
    CLEAN_POD_POLICY_RUNNING,
    JobObject,
    ReplicaSpec,
    ReplicaType,
    RunPolicy,
)
from .defaulting import (
    ValidationError,
    normalize_replica_type_names,
    set_default_port,
    set_default_replicas,
    validate_replica_specs,
    validate_run_policy,
)

KIND = "JAXJob"
PLURAL = "jaxjobs"
SINGULAR = "jaxjob"
GROUP = "kubeflow.org"
VERSION = "v1"
DEFAULT_CONTAINER_NAME = "jax"
DEFAULT_PORT_NAME = "jaxjob-port"
# Coordinator port for jax.distributed.initialize (worker-0 hosts it).
DEFAULT_PORT = 1234
# TPU interruptions (preemption/maintenance) surface as 128+ exit codes,
# which ExitCode policy treats as retryable; plain failures stay permanent.
DEFAULT_RESTART_POLICY = "ExitCode"

REPLICA_TYPE_WORKER = "Worker"
# Out-of-world sidecar replicas (the TFJob Evaluator analog,
# /root/reference/pkg/apis/tensorflow/v1/types.go: TFReplicaTypeEval): an
# Evaluator is NOT a member of the jax.distributed SPMD world — it runs its
# own single-process jax, follows the job's checkpoint stream, and neither
# gates job success nor participates in gang restart. Evaluator pods are
# spread round-robin across slice gangs for scheduling accounting only.
REPLICA_TYPE_EVALUATOR = "Evaluator"
CANONICAL_REPLICA_TYPES = (REPLICA_TYPE_WORKER, REPLICA_TYPE_EVALUATOR)

# The TPU vocabulary is shared across kinds (api/tpu.py, north star: TPU
# pod-slice provisioning on TFJob/PyTorchJob/MXJob too); re-exported here
# because JAXJob is where it originated.
from .tpu import (  # noqa: F401  (re-export)
    ACCELERATOR_TOPOLOGIES,
    TPUSpec,
    chips_for,
    hosts_for,
)


@dataclass
class ElasticPolicy:
    """Slice-granular elasticity bounds (the TPU generalization of the
    reference's EnableDynamicWorker, types.go:69-70). The unit of elasticity
    is a whole slice — partial slices are useless — so resizing means
    patching numSlices (and replicas with it; SDK `scale` does both). The
    controller then restarts the job as one gang with the new world env;
    the workload resumes from its checkpoint."""

    min_slices: int = 1
    max_slices: Optional[int] = None


@dataclass
class JAXJobSpec:
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    jax_replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    tpu: Optional[TPUSpec] = None
    # Declares intentional resizability in whole-slice units: bounds
    # numSlices in validation and gates the SDK scale() verb. Any
    # world-affecting spec patch restarts the gang regardless (k8s
    # convergence — controllers/jax.py stale_world_pods).
    elastic: Optional[ElasticPolicy] = None
    # Multislice: number of DCN-connected slices; each slice is one gang of
    # `hosts_for(tpu)` workers and the global mesh gains a leading DCN axis.
    num_slices: int = 1
    # Slice-restart quorum (slice-scoped failure domains): a retryable
    # loss of one slice restarts only that slice, UNLESS the healthy
    # slice count would drop below this bound within the restart window —
    # then the whole world restarts through the same counted protocol
    # (one ledger entry, reason SliceQuorumLost). None (the default)
    # disables the quorum rule: only the coordinator-slice rule (losing
    # slice 0 always escalates) applies. Distinct from elastic.minSlices,
    # which bounds INTENTIONAL resize — this bounds how much concurrent
    # FAILURE the running world is declared to tolerate.
    min_slices: Optional[int] = None
    # Logical mesh the workload should build, e.g. {"dp": 1, "fsdp": 8, "tp": 4}.
    # Published to every pod as JAX_MESH_SPEC (JSON); axes sizes must multiply
    # to the global chip count when both are known.
    mesh: Dict[str, int] = field(default_factory=dict)

    __schema_required__ = ("jaxReplicaSpecs",)


@dataclass
class JAXJob(JobObject):
    kind: str = KIND
    spec: JAXJobSpec = field(default_factory=JAXJobSpec)

    def replica_specs(self) -> Dict[ReplicaType, ReplicaSpec]:
        return self.spec.jax_replica_specs

    def run_policy(self) -> RunPolicy:
        return self.spec.run_policy



def set_defaults(job: JAXJob) -> None:
    if job.spec.run_policy.clean_pod_policy is None:
        job.spec.run_policy.clean_pod_policy = CLEAN_POD_POLICY_RUNNING
    if job.spec.num_slices <= 0:
        job.spec.num_slices = 1
    normalize_replica_type_names(job.spec.jax_replica_specs, CANONICAL_REPLICA_TYPES)
    for rtype, spec in job.spec.jax_replica_specs.items():
        # Worker replicas default: hosts implied by the slice topology ×
        # slices, falling back to 1 (single-process) when no TPU block is
        # given. Out-of-world types (Evaluator) are not slice-shaped and
        # default to 1 via set_default_replicas.
        if (
            rtype == REPLICA_TYPE_WORKER
            and spec.replicas is None
            and job.spec.tpu is not None
        ):
            hosts = hosts_for(job.spec.tpu)
            if hosts is not None:
                spec.replicas = hosts * job.spec.num_slices
        set_default_replicas(spec, DEFAULT_RESTART_POLICY)
        set_default_port(spec.template.spec, DEFAULT_CONTAINER_NAME, DEFAULT_PORT_NAME, DEFAULT_PORT)
    # Pin gang minAvailable to one slice's host count: a TPU slice is
    # all-or-nothing (SURVEY.md §2.5 "gang scheduling" row). Each slice of a
    # multislice job is its own gang — minAvailable stays per-slice so a free
    # slice can start while others are pending.
    rp = job.spec.run_policy
    worker = job.spec.jax_replica_specs.get(REPLICA_TYPE_WORKER)
    if worker is not None and worker.replicas:
        from .common import SchedulingPolicy

        per_slice = worker.replicas
        if job.spec.tpu is not None:
            per_slice = hosts_for(job.spec.tpu) or max(
                1, worker.replicas // max(1, job.spec.num_slices)
            )
        if rp.scheduling_policy is None:
            rp.scheduling_policy = SchedulingPolicy()
        if rp.scheduling_policy.min_available is None:
            rp.scheduling_policy.min_available = per_slice


def validate(spec: JAXJobSpec) -> None:
    validate_run_policy(spec.run_policy, KIND, spec.jax_replica_specs)
    validate_replica_specs(spec.jax_replica_specs, DEFAULT_CONTAINER_NAME, KIND)
    if spec.elastic is not None:
        el = spec.elastic
        if el.min_slices < 1:
            raise ValidationError(
                f"JAXJobSpec is not valid: elastic.minSlices must be >= 1, got {el.min_slices}"
            )
        if el.max_slices is not None and el.max_slices < el.min_slices:
            raise ValidationError(
                f"JAXJobSpec is not valid: elastic.maxSlices ({el.max_slices}) "
                f"< minSlices ({el.min_slices})"
            )
        if spec.num_slices < el.min_slices or (
            el.max_slices is not None and spec.num_slices > el.max_slices
        ):
            raise ValidationError(
                f"JAXJobSpec is not valid: numSlices {spec.num_slices} outside "
                f"elastic bounds [{el.min_slices}, {el.max_slices}]"
            )
    if spec.min_slices is not None:
        if spec.min_slices < 1:
            raise ValidationError(
                f"JAXJobSpec is not valid: minSlices must be >= 1, got "
                f"{spec.min_slices}"
            )
        if spec.min_slices > max(1, spec.num_slices):
            raise ValidationError(
                f"JAXJobSpec is not valid: minSlices ({spec.min_slices}) "
                f"exceeds numSlices ({max(1, spec.num_slices)}) — the quorum "
                "could never be met"
            )
        if spec.elastic is not None and spec.elastic.min_slices < spec.min_slices:
            # Declared inconsistency: elastic permits resizing BELOW the
            # failure quorum, so a perfectly legal scale() would produce
            # a spec this same validator must reject — bricking a live
            # job at its next sync. Refuse the combination up front.
            raise ValidationError(
                f"JAXJobSpec is not valid: elastic.minSlices "
                f"({spec.elastic.min_slices}) < minSlices "
                f"({spec.min_slices}) — a legal resize could drop below "
                "the restart quorum"
            )
    for rtype in spec.jax_replica_specs:
        if rtype not in CANONICAL_REPLICA_TYPES:
            raise ValidationError(
                f"JAXReplicaType is {rtype} but must be one of {list(CANONICAL_REPLICA_TYPES)}"
            )
    if REPLICA_TYPE_WORKER not in spec.jax_replica_specs:
        # Evaluators are sidecars to an SPMD world; there is nothing for
        # them to follow without one.
        raise ValidationError(
            "JAXJobSpec is not valid: a Worker replica spec is required"
        )
    worker = spec.jax_replica_specs.get(REPLICA_TYPE_WORKER)
    if (
        spec.num_slices > 1
        and worker is not None
        and worker.replicas is not None
        and worker.replicas % spec.num_slices != 0
    ):
        # Slice membership (gang groups, TPU_WORKER_ID, hostnames) is
        # index // hosts_per_slice; a non-divisible count would put pods in
        # a slice no gang group exists for.
        raise ValidationError(
            f"JAXJobSpec is not valid: {worker.replicas} workers cannot split "
            f"evenly over {spec.num_slices} slices"
        )
    if spec.tpu is not None and spec.tpu.num_slices != 1:
        raise ValidationError(
            "JAXJobSpec is not valid: use spec.numSlices (which also drives "
            "MEGASCALE env), not tpu.numSlices"
        )
    if spec.tpu is not None and spec.tpu.accelerator_type:
        if spec.tpu.accelerator_type not in ACCELERATOR_TOPOLOGIES:
            raise ValidationError(
                f"JAXJobSpec is not valid: unknown TPU accelerator type "
                f"{spec.tpu.accelerator_type!r}"
            )
        worker = spec.jax_replica_specs.get(REPLICA_TYPE_WORKER)
        hosts = hosts_for(spec.tpu)
        if worker is not None and worker.replicas is not None and hosts is not None:
            if worker.replicas != hosts * max(1, spec.num_slices):
                raise ValidationError(
                    f"JAXJobSpec is not valid: {spec.tpu.accelerator_type} × "
                    f"{spec.num_slices} slice(s) requires {hosts * max(1, spec.num_slices)} "
                    f"workers, got {worker.replicas}"
                )
    if spec.mesh and "slice" in spec.mesh and spec.mesh["slice"] != max(1, spec.num_slices):
        raise ValidationError(
            f"JAXJobSpec is not valid: mesh slice axis is {spec.mesh['slice']} "
            f"but numSlices is {spec.num_slices}"
        )
    if spec.mesh and spec.tpu is not None:
        chips = chips_for(spec.tpu)
        if chips is not None:
            total = 1
            for size in spec.mesh.values():
                total *= size
            num_slices = max(1, spec.num_slices)
            # Two accepted forms (runtime/tpu_init.py:161 auto-prepends the
            # DCN `slice` axis when absent): a global mesh covering all
            # chips, or a per-slice mesh covering one slice's chips. The
            # per-slice form is resize-stable — elastic scale() never has
            # to rewrite it.
            global_ok = total == chips * num_slices
            per_slice_ok = "slice" not in spec.mesh and total == chips
            if not global_ok and not per_slice_ok:
                raise ValidationError(
                    f"JAXJobSpec is not valid: mesh {spec.mesh} has {total} devices "
                    f"but the job provisions {chips * num_slices} chips "
                    f"({chips} per slice x {num_slices} slice(s))"
                )
