"""TFJob v1 API types, defaults and validation.

Reference parity: pkg/apis/tensorflow/v1/{types,common,constants,util,
defaults}.go and pkg/apis/tensorflow/validation/validation.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from .common import (
    CLEAN_POD_POLICY_RUNNING,
    JobObject,
    ReplicaSpec,
    ReplicaType,
    RunPolicy,
)
from .defaulting import (
    ValidationError,
    normalize_replica_type_names,
    set_default_port,
    set_default_replicas,
    validate_replica_specs,
    validate_run_policy,
)
from .tpu import (
    TPUSpec,
    default_host_replicas,
    validate_accelerator,
    validate_host_count,
)

# Constants (reference pkg/apis/tensorflow/v1/constants.go:21-39)
KIND = "TFJob"
PLURAL = "tfjobs"
SINGULAR = "tfjob"
GROUP = "kubeflow.org"
VERSION = "v1"
DEFAULT_CONTAINER_NAME = "tensorflow"
DEFAULT_PORT_NAME = "tfjob-port"
DEFAULT_PORT = 2222
DEFAULT_RESTART_POLICY = "Never"

# Replica types (reference types.go:77-95)
REPLICA_TYPE_PS = "PS"
REPLICA_TYPE_WORKER = "Worker"
REPLICA_TYPE_CHIEF = "Chief"
REPLICA_TYPE_MASTER = "Master"
REPLICA_TYPE_EVAL = "Evaluator"

CANONICAL_REPLICA_TYPES = (
    REPLICA_TYPE_PS,
    REPLICA_TYPE_WORKER,
    REPLICA_TYPE_CHIEF,
    REPLICA_TYPE_MASTER,
    REPLICA_TYPE_EVAL,
)

# Success policies (reference common.go:18-23)
SUCCESS_POLICY_DEFAULT = ""
SUCCESS_POLICY_ALL_WORKERS = "AllWorkers"


def is_chief_or_master(rtype: ReplicaType) -> bool:
    """reference util.go:22-26"""
    return rtype in (REPLICA_TYPE_CHIEF, REPLICA_TYPE_MASTER)


def is_worker(rtype: ReplicaType) -> bool:
    """reference util.go:28-30"""
    return rtype == REPLICA_TYPE_WORKER


def is_evaluator(rtype: ReplicaType) -> bool:
    """reference util.go:32-34"""
    return rtype == REPLICA_TYPE_EVAL


@dataclass
class TFJobSpec:
    """reference types.go:29-71"""

    run_policy: RunPolicy = field(default_factory=RunPolicy)
    success_policy: Optional[str] = None
    tf_replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    # EnableDynamicWorker => sparse TF_CONFIG so workers can join/leave
    # without restarting the world (reference types.go:69-70,
    # tensorflow.go:62-83).
    enable_dynamic_worker: bool = False
    # TPU pod-slice provisioning (north star: extend the GPU-era CRDs).
    # The Worker group becomes the slice's host pods — replicas default to
    # the topology's host count, each pod gets GKE selectors + google.com/
    # tpu chips + libtpu identity env (TPUStrategy reads the same libtpu
    # layer JAX does), and the job gangs all-or-nothing per slice.
    # Chief/Master/Evaluator stay CPU pods; PS is rejected (parameter
    # servers are a GPU/CPU-era topology — TPU training is all-reduce).
    tpu: Optional[TPUSpec] = None

    __schema_required__ = ("tfReplicaSpecs",)


@dataclass
class TFJob(JobObject):
    kind: str = KIND
    spec: TFJobSpec = field(default_factory=TFJobSpec)

    def replica_specs(self) -> Dict[ReplicaType, ReplicaSpec]:
        return self.spec.tf_replica_specs

    def run_policy(self) -> RunPolicy:
        return self.spec.run_policy



def set_defaults(tfjob: TFJob) -> None:
    """reference defaults.go:96-123 (SetDefaults_TFJob)"""
    if tfjob.spec.run_policy.clean_pod_policy is None:
        tfjob.spec.run_policy.clean_pod_policy = CLEAN_POD_POLICY_RUNNING
    if tfjob.spec.success_policy is None:
        tfjob.spec.success_policy = SUCCESS_POLICY_DEFAULT
    normalize_replica_type_names(tfjob.spec.tf_replica_specs, CANONICAL_REPLICA_TYPES)
    for rtype, spec in tfjob.spec.tf_replica_specs.items():
        # TPU jobs: the Worker group IS the slice — replicas default to the
        # host count the topology implies (x slices), like JAXJob.
        if spec.replicas is None and rtype == REPLICA_TYPE_WORKER:
            spec.replicas = default_host_replicas(tfjob.spec.tpu)
        set_default_replicas(spec, DEFAULT_RESTART_POLICY)
        set_default_port(spec.template.spec, DEFAULT_CONTAINER_NAME, DEFAULT_PORT_NAME, DEFAULT_PORT)


def validate(spec: TFJobSpec) -> None:
    """reference validation/validation.go:27-66 (ValidateV1TFJobSpec)"""
    validate_run_policy(spec.run_policy, KIND, spec.tf_replica_specs)
    validate_replica_specs(spec.tf_replica_specs, DEFAULT_CONTAINER_NAME, KIND)
    found_chief = sum(1 for rt in spec.tf_replica_specs if is_chief_or_master(rt))
    if found_chief > 1:
        raise ValidationError("TFJobSpec is not valid: more than 1 chief/master found")
    if spec.tpu is not None:
        validate_accelerator(spec.tpu, KIND)
        if REPLICA_TYPE_PS in spec.tf_replica_specs:
            raise ValidationError(
                "TFJobSpec is not valid: PS replicas cannot be combined with "
                "spec.tpu (TPU training is all-reduce, not parameter-server)"
            )
        worker = spec.tf_replica_specs.get(REPLICA_TYPE_WORKER)
        if worker is None:
            raise ValidationError(
                "TFJobSpec is not valid: spec.tpu requires a Worker replica "
                "group (the slice's host pods)"
            )
        if worker.replicas is not None:
            validate_host_count(spec.tpu, KIND, worker.replicas)
