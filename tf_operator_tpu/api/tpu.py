"""Shared TPU pod-slice vocabulary — `spec.tpu` on every training kind.

The north star extends the GPU-era CRDs (TFJob/PyTorchJob/MXJob) with TPU
pod-slice provisioning, not just the TPU-native JAXJob: a slice is the
all-or-nothing scheduling unit regardless of which framework runs on it.
This module owns the spec type and topology math; each kind's API module
wires it into its own defaults/validation, and `controllers/_tpu.py` turns
it into node selectors, chip resources, gangs, and libtpu identity env.

Reference anchor: the env-injection pattern the GPU-era reference applies
per framework (tensorflow.go:97-173) — here generalized so TPU provisioning
is one vocabulary across kinds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from .defaulting import ValidationError

# Known accelerator types -> (chips per slice, chips per host). Used to
# default replicas (hosts = chips/chips_per_host) and gang minAvailable.
ACCELERATOR_TOPOLOGIES: Dict[str, tuple] = {
    "v4-8": (4, 4),
    "v4-16": (8, 4),
    "v4-32": (16, 4),
    "v5e-1": (1, 1),
    "v5e-4": (4, 4),
    "v5e-8": (8, 8),
    "v5e-16": (16, 4),
    "v5e-32": (32, 4),
    "v5e-64": (64, 4),
    "v5e-128": (128, 4),
    "v5e-256": (256, 4),
    "v5p-8": (4, 4),
    "v5p-16": (8, 4),
    "v5p-32": (16, 4),
    "v6e-8": (8, 8),
    "v6e-16": (16, 4),
    "v6e-32": (32, 4),
    "v6e-64": (64, 4),
    "v6e-256": (256, 4),
}


@dataclass
class TPUSpec:
    """The pod-slice request attached to a job's compute replica group."""

    # e.g. "v5e-32" — see ACCELERATOR_TOPOLOGIES.
    accelerator_type: str = ""
    # Physical topology string, e.g. "4x8" (v5e-32) or "2x2x2" (v4-16);
    # published to pods and used as the GKE topology node selector.
    topology: str = ""
    # Chips handed to each worker pod (google.com/tpu resource).
    chips_per_host: Optional[int] = None
    # Multi-slice provisioning for the GPU-era kinds (TFJob/PyTorchJob/
    # MXJob): each slice is its own gang of hosts_for() pods. JAXJob keeps
    # its top-level spec.numSlices (which also drives MEGASCALE env) and
    # must leave this at 1.
    num_slices: int = 1


def hosts_for(tpu: TPUSpec) -> Optional[int]:
    """Host (pod) count a slice requires, or None when unknown."""
    info = ACCELERATOR_TOPOLOGIES.get(tpu.accelerator_type)
    if info is None:
        return None
    chips, default_chips_per_host = info
    per_host = tpu.chips_per_host or default_chips_per_host
    return max(1, chips // per_host)


def chips_for(tpu: TPUSpec) -> Optional[int]:
    info = ACCELERATOR_TOPOLOGIES.get(tpu.accelerator_type)
    return info[0] if info else None


def per_host_chips(tpu: TPUSpec) -> Optional[int]:
    """Chips each host pod should request (google.com/tpu)."""
    if tpu.chips_per_host:
        return tpu.chips_per_host
    info = ACCELERATOR_TOPOLOGIES.get(tpu.accelerator_type)
    return info[1] if info else None


def default_host_replicas(tpu: Optional[TPUSpec], reserve: int = 0) -> Optional[int]:
    """Default replica count for a kind's TPU host group: the topology's
    host count × slices, minus `reserve` hosts provided by another group
    (PyTorch's single master is host 0). None when unknowable."""
    if tpu is None:
        return None
    hosts = hosts_for(tpu)
    if hosts is None:
        return None
    return max(0, hosts * max(1, tpu.num_slices) - reserve)


def validate_accelerator(tpu: TPUSpec, kind: str) -> None:
    if tpu.accelerator_type and tpu.accelerator_type not in ACCELERATOR_TOPOLOGIES:
        raise ValidationError(
            f"{kind}Spec is not valid: unknown TPU accelerator type "
            f"{tpu.accelerator_type!r}"
        )
    if tpu.num_slices < 1:
        raise ValidationError(
            f"{kind}Spec is not valid: tpu.numSlices must be >= 1, "
            f"got {tpu.num_slices}"
        )


def validate_host_count(tpu: TPUSpec, kind: str, total_hosts: int) -> None:
    """The TPU replica groups must together provide exactly the pod count
    the slice topology implies — a partial slice is useless and an
    oversubscribed one cannot schedule."""
    hosts = hosts_for(tpu)
    if hosts is None:
        return
    want = hosts * max(1, tpu.num_slices)
    if total_hosts != want:
        raise ValidationError(
            f"{kind}Spec is not valid: {tpu.accelerator_type} × "
            f"{max(1, tpu.num_slices)} slice(s) requires {want} TPU host "
            f"pod(s), got {total_hosts}"
        )
