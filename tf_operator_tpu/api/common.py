"""Shared API vocabulary for all training job kinds.

Re-owns the types the reference imports from kubeflow/common v0.3.4
``apis/common/v1`` (ReplicaSpec, RestartPolicy, RunPolicy, JobStatus,
JobCondition, ReplicaStatus — consumed at 50+ sites in the reference,
SURVEY.md §2.2/§2.9). In this framework they are first-class and in-repo.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .k8s import (
    POD_CONDITION_DISRUPTION_TARGET,
    ObjectMeta,
    Pod,
    PodTemplateSpec,
    from_dict,
    to_dict,
)

# --- Replica types are plain strings; frameworks define their own constants.
ReplicaType = str

# --- Restart policies (commonv1.RestartPolicy)
RESTART_POLICY_ALWAYS = "Always"
RESTART_POLICY_ON_FAILURE = "OnFailure"
RESTART_POLICY_NEVER = "Never"
# ExitCode: retryability decided by the container exit code (1-127 permanent,
# 128+ retryable — reference docs/design/tf_job_design_doc.md:84 and
# tfjob_controller.go:717-719).
RESTART_POLICY_EXIT_CODE = "ExitCode"

RESTART_POLICIES = (
    RESTART_POLICY_ALWAYS,
    RESTART_POLICY_ON_FAILURE,
    RESTART_POLICY_NEVER,
    RESTART_POLICY_EXIT_CODE,
)

# --- Clean pod policies (commonv1.CleanPodPolicy)
CLEAN_POD_POLICY_ALL = "All"
CLEAN_POD_POLICY_RUNNING = "Running"
CLEAN_POD_POLICY_NONE = "None"

# --- Job condition types (commonv1.JobConditionType)
JOB_CREATED = "Created"
JOB_RUNNING = "Running"
JOB_RESTARTING = "Restarting"
JOB_SUCCEEDED = "Succeeded"
JOB_FAILED = "Failed"
# Suspension (training-operator RunPolicy.suspend): on TPU, a suspended
# job releases its whole pod-slice back to the scheduler.
JOB_SUSPENDED = "Suspended"
# Gang waiting for scheduler capacity (PodGroup phase Pending/Inqueue):
# makes a queued slice observable instead of indistinguishable from a
# stuck job. No reference counterpart (its PodGroup is fire-and-forget).
JOB_QUEUED = "Queued"

CONDITION_TRUE = "True"
CONDITION_FALSE = "False"


def is_retryable_exit_code(exit_code: int) -> bool:
    """Exit-code taxonomy: 1-127 are permanent errors (caller bugs, config),
    128+ are retryable (SIGKILL/SIGTERM from preemption, OOM kills).

    Reference: kubeflow/common train_util.IsRetryableExitCode, used at
    tfjob_controller.go:718; rationale docs/design/tf_job_design_doc.md:84.
    """
    return exit_code >= 128


# --- Restart-cause taxonomy (docs/design/disruption_handling.md) ---------
#
# Every operator-initiated restart is classified as one of two causes, and
# each cause draws from its own budget: application failures consume
# RunPolicy.backoffLimit (as they always have), infrastructure disruptions
# consume RunPolicy.maxDisruptionRetries (default unlimited). On TPU fleets
# preemption/maintenance is the dominant failure mode; letting it burn the
# application budget turns routine capacity churn into dead jobs.
RESTART_CAUSE_APPLICATION = "ApplicationFailure"
RESTART_CAUSE_DISRUPTION = "InfrastructureDisruption"
# A deliberate spec change (elastic resize / world-generation rollout):
# not a failure at all — consumes neither budget, but still labels the
# restarted-by-cause metric so dashboards see why a world churned.
RESTART_CAUSE_SPEC_CHANGE = "SpecChange"
# A gang-liveness verdict (docs/design/failure_modes.md §8): every pod
# reported Running but a replica's heartbeat went stale past
# RunPolicy.progressDeadlineSeconds (or never arrived within
# rendezvousDeadlineSeconds). Neither an application exit nor an
# infrastructure kill — its restarts land in the separate
# status.stallCounts ledger so the cause-labeled counters stay disjoint
# (a wedged collective must not burn backoffLimit, and a dead ICI link
# must not look like a preemption streak).
RESTART_CAUSE_STALL = "ProgressStall"

# Signal-kill exit codes: the process was terminated from OUTSIDE
# (137 = 128+SIGKILL: preemption/OOM-score eviction; 143 = 128+SIGTERM:
# node drain, graceful preemption). Other 128+ codes (134 SIGABRT,
# 139 SIGSEGV) are the process crashing on its own and stay
# application-classified even though they are retryable.
SIGKILL_CLASS_EXIT_CODES = (137, 143)

# PodStatus.reason values the kubelet/eviction machinery writes when the
# infrastructure (not the workload) killed the pod.
DISRUPTION_POD_REASONS = ("Preempted", "Evicted", "NodeShutdown", "Terminated")


def is_sigkill_class_exit_code(exit_code: int) -> bool:
    return exit_code in SIGKILL_CLASS_EXIT_CODES


def pod_disruption_signal(pod: Pod) -> Optional[str]:
    """The pod's explicit infrastructure-disruption marker, if any: the
    DisruptionTarget condition (k8s >=1.26 stamps it on preemption, node
    drain, taint eviction) or a disruption-class PodStatus.reason
    (Preempted/Evicted/NodeShutdown). Returns the reason string for
    events/metrics, or None when the pod carries no explicit marker."""
    for cond in pod.status.conditions:
        if (
            cond.type == POD_CONDITION_DISRUPTION_TARGET
            and cond.status == CONDITION_TRUE
        ):
            return cond.reason or POD_CONDITION_DISRUPTION_TARGET
    if pod.status.reason in DISRUPTION_POD_REASONS:
        return pod.status.reason
    return None


def classify_pod_failure(pod: Pod, exit_code: int, peers_healthy: bool = True) -> str:
    """Restart-cause classification for a retryably-failed pod:

    - an explicit marker (DisruptionTarget condition, Preempted/Evicted
      status reason) is always a disruption — the cluster told us so;
    - a container the kubelet reports as OOMKilled is the workload
      exceeding ITS OWN memory limit: exit code 137, but an application
      failure — without this check a leaking trainer would crash-loop
      budget-free forever instead of exhausting backoffLimit;
    - a SIGKILL-class exit (137/143) with no marker is a disruption only on
      an otherwise-healthy gang (`peers_healthy`): a lone host silently
      killed under healthy peers is preemption in practice, while the same
      code beside peers dying of application errors is the workload
      taking itself down;
    - everything else (1-127 permanent, 128+ self-inflicted crashes) is an
      application failure, exactly as before this taxonomy existed.
    """
    if pod_disruption_signal(pod) is not None:
        return RESTART_CAUSE_DISRUPTION
    for status in pod.status.container_statuses:
        if (
            status.state.terminated is not None
            and status.state.terminated.reason == "OOMKilled"
        ):
            return RESTART_CAUSE_APPLICATION
    if is_sigkill_class_exit_code(exit_code) and peers_healthy:
        return RESTART_CAUSE_DISRUPTION
    return RESTART_CAUSE_APPLICATION


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs (commonv1.SchedulingPolicy, visible in the
    flattened CRD manifests/base/crds/kubeflow.org_tfjobs.yaml runPolicy)."""

    min_available: Optional[int] = None
    queue: str = ""
    min_resources: Dict[str, str] = field(default_factory=dict)
    priority_class: str = ""
    # Per-device-generation normalized throughput (Gavel,
    # arXiv:2008.09213): generation name (as declared in the operator's
    # --capacity res@generation=qty pool) -> this job's relative
    # throughput there, e.g. {"v5lite": 0.25, "v6": 1.0}. Consumed by
    # --admission-policy gavel to place the gang where it maximizes
    # fleet-wide effective throughput; generations absent from the map
    # ride 1.0, and an empty map means generation-indifferent. Values
    # must be positive finite numbers (api/defaulting.py).
    throughput_ratios: Dict[str, float] = field(default_factory=dict)


@dataclass
class RunPolicy:
    """Policies that apply to the whole job (commonv1.RunPolicy)."""

    clean_pod_policy: Optional[str] = None
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: Optional[int] = None
    # Separate budget for infrastructure-disruption restarts (preemption,
    # eviction, node drain): None = unlimited — the Gavel/Podracer stance
    # that preemption-and-resume is a normal, budget-free operation the
    # substrate absorbs. Set a bound to fail jobs stuck in a preemption
    # loop (e.g. a reservation that keeps getting reclaimed).
    max_disruption_retries: Optional[int] = None
    # Gang-liveness deadlines (both opt-in, default off — a job that never
    # heartbeats can never stall-restart):
    #
    # progressDeadlineSeconds: once a replica has produced its FIRST
    # heartbeat, the operator restarts the gang with cause ProgressStall
    # if that replica's renewals go stale for this long — measured on the
    # operator's local clock from the moment a renewal is OBSERVED (the
    # leader-election skew rule; never remote timestamp vs. local now).
    # This is what lets the control plane tell "slow" from "stuck":
    # activeDeadlineSeconds kills healthy long jobs, this only fires when
    # a live-looking worker stopped proving liveness.
    progress_deadline_seconds: Optional[int] = None
    # rendezvousDeadlineSeconds: bound on reaching the first heartbeat
    # after gang-up (pod observed Running). Catches the worker wedged in
    # rendezvous forever — which progressDeadlineSeconds alone never
    # flags, because staleness is only measured once a first heartbeat
    # exists. Requires progressDeadlineSeconds to be set (validation):
    # a job must opt into the heartbeat protocol as a whole.
    rendezvous_deadline_seconds: Optional[int] = None
    # forceDeleteAfterSeconds (opt-in, default unset = never): how long a
    # pod may linger Terminating PAST its granted grace period
    # (deletionTimestamp + deletionGracePeriodSeconds) before the operator
    # escalates to a grace-period-0 force delete. The dead-host failure
    # mode (docs/design/failure_modes.md §9): a kubelet on a reclaimed TPU
    # host never acks termination, the pod object never goes away, and the
    # gang can never recreate that index — recovery blocked forever.
    # Unset keeps the k8s-safe default (never force-delete: the container
    # may still be running on a partitioned node); set it on fleets where
    # "node gone" is routine (TPU reclaims) and a stuck object costs a
    # whole slice's worth of idle accelerators.
    force_delete_after_seconds: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None
    # Suspend (training-operator v1.7 RunPolicy.suspend): true tears down
    # every pod (and gang groups — on TPU this releases the whole slice)
    # without failing the job; false/None resumes with a fresh startTime.
    suspend: Optional[bool] = None


@dataclass
class ReplicaSpec:
    """Spec of one replica group (commonv1.ReplicaSpec)."""

    replicas: Optional[int] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    restart_policy: str = ""

    __schema_required__ = ("template",)


@dataclass
class ReplicaStatus:
    """Per-replica-type counters (commonv1.ReplicaStatus)."""

    active: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class JobCondition:
    """One entry in JobStatus.conditions (commonv1.JobCondition)."""

    type: str = ""
    status: str = CONDITION_TRUE
    reason: str = ""
    message: str = ""
    last_update_time: Optional[float] = None
    last_transition_time: Optional[float] = None


@dataclass
class JobStatus:
    """Observed state of a training job (commonv1.JobStatus)."""

    conditions: List[JobCondition] = field(default_factory=list)
    replica_statuses: Dict[str, ReplicaStatus] = field(default_factory=dict)
    # Operator-initiated APPLICATION-failure restarts per replica type
    # (policy ExitCode deletes + recreates pods, so kubelet restartCounts
    # never see them; backoffLimit must still count them — persisted here
    # across pod generations).
    restart_counts: Dict[str, int] = field(default_factory=dict)
    # Operator-initiated INFRASTRUCTURE-disruption restarts per replica
    # type (preemption/eviction/drain). Deliberately a separate ledger:
    # these never count toward backoffLimit — they draw from
    # RunPolicy.maxDisruptionRetries instead.
    disruption_counts: Dict[str, int] = field(default_factory=dict)
    # Operator-initiated PROGRESS-STALL restarts per replica type (gang
    # liveness: heartbeats went stale past progressDeadlineSeconds, or
    # never arrived within rendezvousDeadlineSeconds). A third disjoint
    # ledger: stalls draw neither backoffLimit nor maxDisruptionRetries —
    # each stall restart is rate-limited by its own deadline window, and
    # activeDeadlineSeconds remains the hard wall-clock bound.
    stall_counts: Dict[str, int] = field(default_factory=dict)
    # Per-SLICE restart attribution (slice-scoped failure domains,
    # docs/design/failure_modes.md §12): counted restarts whose teardown
    # was scoped to one slice of a multislice job, keyed by the slice
    # index as a string ("3" -> 2 means slice 3 was restarted twice).
    # Escalated whole-world restarts (coordinator/quorum loss) do NOT
    # land here — they are visible in the three cause ledgers above and
    # in the SliceQuorumLost condition reason. Purely attributive: no
    # budget draws from this map (the cause ledgers keep that job), so
    # it can never disagree with them on totals — a slice restart
    # increments exactly one cause ledger AND its slice's entry here.
    slice_restart_counts: Dict[str, int] = field(default_factory=dict)
    # Consecutive disruption restarts since the job last reached Running:
    # drives the jittered exponential restart backoff (first disruption
    # restarts immediately; a preemption loop backs off). Reset on Running.
    disruption_streak: int = 0
    # Absolute clock time before which the engine defers pod recreation
    # (the restart-backoff window after a disruption). Cleared when it
    # elapses and on suspend/resume.
    restart_backoff_until: Optional[float] = None
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    last_reconcile_time: Optional[float] = None
    # The rendezvous-world hash the controller last acted on (JAXJob resize
    # — surfaced as status.worldGeneration for operators/debuggers).
    world_generation: Optional[str] = None
    # UIDs of every world pod present when the last gang teardown
    # completed+counted (all of them are being replaced by that restart).
    # Externally-deleted pods (eviction: Failed + Terminating) can linger
    # through their grace period beside the already-recreated world;
    # without this stamp every sync would re-read each one as a fresh
    # external deletion, tearing the new gang down again and burning one
    # backoffLimit count per evicted pod for a single maintenance event.
    # Replaced wholesale at each counted restart, so it stays gang-sized.
    gang_handled_uids: List[str] = field(default_factory=list)


# --- Condition helpers (kubeflow/common pkg/util/status.go equivalents) ---

def get_condition(status: JobStatus, cond_type: str) -> Optional[JobCondition]:
    for c in status.conditions:
        if c.type == cond_type:
            return c
    return None


def has_condition(status: JobStatus, cond_type: str) -> bool:
    c = get_condition(status, cond_type)
    return c is not None and c.status == CONDITION_TRUE


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, JOB_SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, JOB_FAILED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def is_running(status: JobStatus) -> bool:
    return has_condition(status, JOB_RUNNING)


def update_job_conditions(
    status: JobStatus, cond_type: str, reason: str, message: str, now: Optional[float] = None
) -> None:
    """Append/refresh a condition, maintaining the reference's invariants:

    - setting Running removes Restarting (and vice versa);
    - terminal conditions (Succeeded/Failed) flip Running to False;
    - re-setting an identical condition is a strict no-op (timestamps advance
      only on a transition or a message change), so steady-state syncs do not
      produce status diffs.

    Reference: kubeflow/common pkg/util/status.go setCondition/filterOutCondition
    semantics as exercised by the reference's status_test.go.
    """
    now = time.time() if now is None else now
    # One terminal verdict per job: the first of Succeeded/Failed to land
    # wins and the other can never overwrite it in a later (or even the
    # same) sync — e.g. a chief's success and a straggler worker's failure
    # observed together must resolve by replica-type precedence, not
    # last-writer-wins (reference fixed iteration order,
    # tfjob_controller.go:385-501).
    if cond_type == JOB_FAILED and has_condition(status, JOB_SUCCEEDED):
        return
    if cond_type == JOB_SUCCEEDED and has_condition(status, JOB_FAILED):
        return
    new_cond = JobCondition(
        type=cond_type,
        status=CONDITION_TRUE,
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )

    existing = get_condition(status, cond_type)
    if existing is not None and existing.status == new_cond.status and existing.reason == new_cond.reason:
        # No transition. An identical condition must be a strict no-op —
        # refreshing timestamps would make every sync look like a status
        # change and turn the watch->reconcile loop into a hot loop.
        if existing.message != message:
            existing.message = message
            existing.last_update_time = now
        return

    # Filter out: the same type; Restarting when setting Running; Running when
    # setting Restarting (mutually exclusive in the reference state machine).
    drop = {cond_type}
    if cond_type == JOB_RUNNING:
        drop.add(JOB_RESTARTING)
    if cond_type == JOB_RESTARTING:
        drop.add(JOB_RUNNING)
    if cond_type == JOB_SUSPENDED:
        drop.add(JOB_RESTARTING)
    kept = [c for c in status.conditions if c.type not in drop]

    # Flip (not drop) the mutually-exclusive observers so the history stays
    # visible: terminal conditions and Suspended set Running=False; Running
    # sets Suspended=False (the resumed record remains in conditions).
    def _flip(target: str) -> None:
        for c in kept:
            if c.type == target and c.status == CONDITION_TRUE:
                c.status = CONDITION_FALSE
                c.last_transition_time = now
                c.last_update_time = now

    if cond_type in (JOB_SUCCEEDED, JOB_FAILED, JOB_SUSPENDED):
        _flip(JOB_RUNNING)
        _flip(JOB_QUEUED)  # a terminal/suspended job is not waiting in queue
    if cond_type == JOB_RUNNING:
        _flip(JOB_SUSPENDED)
        _flip(JOB_QUEUED)  # the gang got capacity: queue record stays, False

    kept.append(new_cond)
    status.conditions = kept


def initialize_replica_statuses(status: JobStatus, rtype: ReplicaType) -> None:
    status.replica_statuses[rtype] = ReplicaStatus()


@dataclass
class JobObject:
    """Base class for all job kinds: metadata + status + (de)serialization.

    Concrete kinds (TFJob, PyTorchJob, MXJob, XGBoostJob, JAXJob) add their
    spec type and expose the generic accessors the reconciler engine needs.
    """

    api_version: str = "kubeflow.org/v1"
    kind: str = ""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: JobStatus = field(default_factory=JobStatus)

    # -- generic accessors the engine relies on; kinds override -------------
    def replica_specs(self) -> Dict[ReplicaType, ReplicaSpec]:
        raise NotImplementedError

    def run_policy(self) -> RunPolicy:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def to_dict(self) -> dict:
        return to_dict(self)

    @classmethod
    def parse(cls, data: dict) -> "JobObject":
        return from_dict(cls, data)
