"""PyTorchJob v1 API types, defaults and validation.

Reference parity: pkg/apis/pytorch/v1/{pytorchjob_types,constants,defaults}.go
and pkg/apis/pytorch/validation/validation.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from .common import (
    CLEAN_POD_POLICY_RUNNING,
    JobObject,
    ReplicaSpec,
    ReplicaType,
    RunPolicy,
)
from .defaulting import (
    ValidationError,
    normalize_replica_type_names,
    set_default_port,
    set_default_replicas,
    validate_replica_specs,
    validate_run_policy,
)
from .tpu import (
    TPUSpec,
    default_host_replicas,
    validate_accelerator,
    validate_host_count,
)

# Constants (reference pkg/apis/pytorch/v1/constants.go:22-30)
KIND = "PyTorchJob"
PLURAL = "pytorchjobs"
SINGULAR = "pytorchjob"
GROUP = "kubeflow.org"
VERSION = "v1"
DEFAULT_CONTAINER_NAME = "pytorch"
DEFAULT_PORT_NAME = "pytorchjob-port"
DEFAULT_PORT = 23456
DEFAULT_RESTART_POLICY = "OnFailure"

# Replica types (reference pytorchjob_types.go:61-67)
REPLICA_TYPE_MASTER = "Master"
REPLICA_TYPE_WORKER = "Worker"

CANONICAL_REPLICA_TYPES = (REPLICA_TYPE_MASTER, REPLICA_TYPE_WORKER)


@dataclass
class PyTorchJobSpec:
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    pytorch_replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    # TPU pod-slice provisioning (north star: extend the GPU-era CRDs).
    # Master + Workers together are the slice's host pods in rank order
    # (master = rank 0 host): Worker replicas default to hosts-1, every
    # host pod gets GKE selectors + google.com/tpu chips + libtpu identity
    # env + PJRT_DEVICE=TPU (the torch_xla PJRT contract), and the job
    # gangs all-or-nothing per slice.
    tpu: Optional[TPUSpec] = None

    __schema_required__ = ("pytorchReplicaSpecs",)


@dataclass
class PyTorchJob(JobObject):
    kind: str = KIND
    spec: PyTorchJobSpec = field(default_factory=PyTorchJobSpec)

    def replica_specs(self) -> Dict[ReplicaType, ReplicaSpec]:
        return self.spec.pytorch_replica_specs

    def run_policy(self) -> RunPolicy:
        return self.spec.run_policy



def set_defaults(job: PyTorchJob) -> None:
    if job.spec.run_policy.clean_pod_policy is None:
        job.spec.run_policy.clean_pod_policy = CLEAN_POD_POLICY_RUNNING
    normalize_replica_type_names(job.spec.pytorch_replica_specs, CANONICAL_REPLICA_TYPES)
    for rtype, spec in job.spec.pytorch_replica_specs.items():
        # TPU jobs: master + workers are the slice's hosts — workers
        # default to the remaining host count after the single master.
        if spec.replicas is None and rtype == REPLICA_TYPE_WORKER:
            masters = REPLICA_TYPE_MASTER in job.spec.pytorch_replica_specs
            spec.replicas = default_host_replicas(
                job.spec.tpu, reserve=1 if masters else 0
            )
        set_default_replicas(spec, DEFAULT_RESTART_POLICY)
        set_default_port(spec.template.spec, DEFAULT_CONTAINER_NAME, DEFAULT_PORT_NAME, DEFAULT_PORT)


def validate(spec: PyTorchJobSpec) -> None:
    """reference pkg/apis/pytorch/validation/validation.go:ValidateV1PyTorchJobSpec —
    valid replica types only, images set, container named `pytorch`, and
    exactly one Master with replicas == 1."""
    validate_run_policy(spec.run_policy, KIND, spec.pytorch_replica_specs)
    if not spec.pytorch_replica_specs:
        raise ValidationError("PyTorchJobSpec is not valid")
    for rtype in spec.pytorch_replica_specs:
        if rtype not in CANONICAL_REPLICA_TYPES:
            raise ValidationError(
                f"PyTorchReplicaType is {rtype} but must be one of {list(CANONICAL_REPLICA_TYPES)}"
            )
    validate_replica_specs(spec.pytorch_replica_specs, DEFAULT_CONTAINER_NAME, KIND)
    master = spec.pytorch_replica_specs.get(REPLICA_TYPE_MASTER)
    if master is None:
        raise ValidationError("PyTorchJobSpec is not valid: Master ReplicaSpec must be present")
    if master.replicas is not None and master.replicas != 1:
        raise ValidationError("PyTorchJobSpec is not valid: There must be only 1 master replica")
    if spec.tpu is not None:
        validate_accelerator(spec.tpu, KIND)
        worker = spec.pytorch_replica_specs.get(REPLICA_TYPE_WORKER)
        total = (master.replicas or 1) + (
            (worker.replicas or 0) if worker is not None else 0
        )
        if worker is None or worker.replicas is not None:
            validate_host_count(spec.tpu, KIND, total)
