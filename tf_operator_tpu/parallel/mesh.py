"""Device mesh construction from operator-published topology.

The canonical axes, outermost (DCN) to innermost (ICI minor):

- ``slice`` — across pod-slices (DCN); pure data parallelism.
- ``dp``    — data parallelism over ICI.
- ``fsdp``  — data parallelism with parameter/optimizer sharding (ZeRO-3).
- ``sp``    — sequence/context parallelism (ring attention over an ICI ring).
- ``tp``    — tensor parallelism (heads/ffn); innermost so its collectives
              ride the fastest ICI links.

`jax.experimental.mesh_utils.create_device_mesh` lays devices out so
neighboring mesh coordinates are ICI neighbors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

AXIS_ORDER = ("slice", "dp", "fsdp", "sp", "tp")


@dataclass
class MeshSpec:
    """Logical mesh layout, e.g. MeshSpec({"fsdp": 8, "tp": 4})."""

    axes: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        for name in self.axes:
            if name not in AXIS_ORDER:
                raise ValueError(f"unknown mesh axis {name!r}; known: {AXIS_ORDER}")

    def ordered(self) -> List[tuple]:
        return [(a, self.axes[a]) for a in AXIS_ORDER if a in self.axes]

    @property
    def size(self) -> int:
        total = 1
        for _, n in self.ordered():
            total *= n
        return total


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """Build a Mesh matching `spec` over `devices` (default: all)."""
    devices = list(devices if devices is not None else jax.devices())
    if not spec.axes:
        # Empty spec: pure data parallelism over every device.
        spec = MeshSpec({"dp": len(devices)})
    if spec.size != len(devices):
        raise ValueError(f"mesh {spec.axes} needs {spec.size} devices, have {len(devices)}")
    names = tuple(a for a, _ in spec.ordered())
    shape = tuple(n for _, n in spec.ordered())
    try:
        from jax.experimental import mesh_utils

        if devices == list(jax.devices()):
            device_array = mesh_utils.create_device_mesh(shape)
        else:
            device_array = np.array(devices).reshape(shape)
    except Exception:
        device_array = np.array(devices).reshape(shape)
    return jax.sharding.Mesh(device_array, names)


def standard_mesh(
    n_devices: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    dp: int = 1,
    num_slices: int = 1,
    devices: Optional[Sequence] = None,
) -> jax.sharding.Mesh:
    """Mesh with fsdp absorbing whatever the explicit axes don't cover —
    the right default for LLM training (FSDP-dominant, TP innermost)."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    denom = tp * sp * dp * num_slices
    if n % denom:
        raise ValueError(f"{n} devices not divisible by slice*dp*sp*tp={denom}")
    axes = {}
    if num_slices > 1:
        axes["slice"] = num_slices
    if dp > 1:
        axes["dp"] = dp
    axes["fsdp"] = n // denom
    if sp > 1:
        axes["sp"] = sp
    if tp > 1:
        axes["tp"] = tp
    return make_mesh(MeshSpec(axes), devices[:n])


def axis_size(mesh: jax.sharding.Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)
