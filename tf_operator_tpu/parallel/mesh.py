"""Device mesh construction from operator-published topology.

The canonical axes, outermost (DCN) to innermost (ICI minor):

- ``slice`` — across pod-slices (DCN); pure data parallelism.
- ``pp``    — pipeline parallelism (stage-to-stage ppermute; tolerates the
              slowest links, so it sits outermost after ``slice``).
- ``dp``    — data parallelism over ICI.
- ``fsdp``  — data parallelism with parameter/optimizer sharding (ZeRO-3).
- ``ep``    — expert parallelism (MoE all-to-all dispatch; doubles as a
              data axis for the non-expert parts of the model).
- ``sp``    — sequence/context parallelism (ring attention over an ICI ring).
- ``tp``    — tensor parallelism (heads/ffn); innermost so its collectives
              ride the fastest ICI links.

`jax.experimental.mesh_utils.create_device_mesh` lays devices out so
neighboring mesh coordinates are ICI neighbors.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

AXIS_ORDER = ("slice", "pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass
class MeshSpec:
    """Logical mesh layout, e.g. MeshSpec({"fsdp": 8, "tp": 4})."""

    axes: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        for name in self.axes:
            if name not in AXIS_ORDER:
                raise ValueError(f"unknown mesh axis {name!r}; known: {AXIS_ORDER}")

    def ordered(self) -> List[tuple]:
        return [(a, self.axes[a]) for a in AXIS_ORDER if a in self.axes]

    @property
    def size(self) -> int:
        total = 1
        for _, n in self.ordered():
            total *= n
        return total


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """Build a Mesh matching `spec` over `devices` (default: all)."""
    devices = list(devices if devices is not None else jax.devices())
    if not spec.axes:
        # Empty spec: pure data parallelism over every device.
        spec = MeshSpec({"dp": len(devices)})
    if spec.size != len(devices):
        raise ValueError(f"mesh {spec.axes} needs {spec.size} devices, have {len(devices)}")
    names = tuple(a for a, _ in spec.ordered())
    shape = tuple(n for _, n in spec.ordered())
    try:
        from jax.experimental import mesh_utils

        if devices == list(jax.devices()):
            device_array = mesh_utils.create_device_mesh(shape)
        else:
            device_array = np.array(devices).reshape(shape)
    except Exception:
        device_array = np.array(devices).reshape(shape)
    return jax.sharding.Mesh(device_array, names)


def standard_mesh(
    n_devices: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    dp: int = 1,
    ep: int = 1,
    pp: int = 1,
    num_slices: int = 1,
    devices: Optional[Sequence] = None,
) -> jax.sharding.Mesh:
    """Mesh with fsdp absorbing whatever the explicit axes don't cover —
    the right default for LLM training (FSDP-dominant, TP innermost)."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    denom = tp * sp * dp * ep * pp * num_slices
    if n % denom:
        raise ValueError(f"{n} devices not divisible by slice*pp*dp*ep*sp*tp={denom}")
    axes = {}
    if num_slices > 1:
        axes["slice"] = num_slices
    if pp > 1:
        axes["pp"] = pp
    if dp > 1:
        axes["dp"] = dp
    axes["fsdp"] = n // denom
    if ep > 1:
        axes["ep"] = ep
    if sp > 1:
        axes["sp"] = sp
    if tp > 1:
        axes["tp"] = tp
    return make_mesh(MeshSpec(axes), devices[:n])


def axis_size(mesh: jax.sharding.Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


# --- current-mesh context -------------------------------------------------
#
# Model code sometimes needs the active mesh at trace time (to wrap an op in
# shard_map — ring attention over `sp` — or to place a sharding constraint —
# MoE all-to-all over `ep`). The train step sets it; model code reads it.
# Thread-local so concurrent traces (tests) don't interfere.

_MESH_TLS = threading.local()


def set_current_mesh(mesh: Optional[jax.sharding.Mesh]) -> None:
    _MESH_TLS.mesh = mesh


def current_mesh() -> Optional[jax.sharding.Mesh]:
    return getattr(_MESH_TLS, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh: jax.sharding.Mesh):
    """Scope `mesh` as the current mesh (see `current_mesh`)."""
    prev = current_mesh()
    set_current_mesh(mesh)
    try:
        yield mesh
    finally:
        set_current_mesh(prev)


def mark_varying(x, axes):
    """Mark `x` varying over the given manual (shard_map) axes — the loop
    carries of collective schedules (ring attention, the pp pipeline) must
    match their body outputs' varying-axes type. Uses `jax.lax.pcast`
    (current API) with `pvary` fallback; NameError (axis not bound — an
    unmapped fallback path) leaves x unmarked. jax 0.4.x has NEITHER (no
    varying-axes type system at all) — nothing to mark, x passes through."""
    fn = getattr(jax.lax, "pcast", None)
    try:
        if fn is not None:
            return fn(x, tuple(axes), to="varying")
        fn = getattr(jax.lax, "pvary", None)
        if fn is not None:
            return fn(x, tuple(axes))
        return x
    except NameError:
        return x
