"""jax version compatibility shims for the sharding/shard_map surface.

One place for the 0.4.x-vs-0.5+ API drift every shard_map consumer needs
(ring attention, the pp pipeline, the dryrun entry, tests), instead of a
copy of the probe in each:

- ``shard_map``: top-level in jax >= 0.5, under ``jax.experimental`` in
  0.4.x.
- ``supports_partial_manual()``: 0.5+ spells partially-manual regions
  ``axis_names={...}``; 0.4.x spells them inversely (``auto=``) and its
  jaxlib then fails the lowering ("PartitionId instruction is not
  supported for SPMD partitioning") — so the feature is effectively
  absent there and callers gate/skip on this probe.
- ``rep_check_kwarg()``: the replication/varying-axes checker knob is
  ``check_vma`` in 0.5+ and ``check_rep`` in 0.4.x.
- ``is_legacy_shard_map()``: True on the 0.4.x experimental module —
  where the rep checker predates varying-axes typing and mis-types some
  control-flow carries (callers pass ``check_rep=False`` there, the
  upstream-suggested workaround).
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5 exposes it at top level; 0.4.x under experimental
    from jax import shard_map
except ImportError:  # pragma: no cover - exercised on 0.4.x containers
    from jax.experimental.shard_map import shard_map

__all__ = [
    "shard_map",
    "supports_partial_manual",
    "rep_check_kwarg",
    "is_legacy_shard_map",
]

_PARAMS = frozenset(inspect.signature(shard_map).parameters)


def supports_partial_manual() -> bool:
    """True when shard_map takes ``axis_names=`` (partial-manual mode)."""
    return "axis_names" in _PARAMS


def rep_check_kwarg() -> str:
    """Name of the replication-check knob on this jax."""
    return "check_vma" if "check_vma" in _PARAMS else "check_rep"


def is_legacy_shard_map() -> bool:
    """True on the jax 0.4.x experimental implementation."""
    return "experimental" in getattr(shard_map, "__module__", "")
