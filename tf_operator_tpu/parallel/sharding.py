"""Sharding rules: parameter-path -> PartitionSpec.

Rather than translating a torch-style device-placement scheme, shardings are
declared once as path rules and XLA inserts the collectives (all-gather for
FSDP params, reduce-scatter for grads, all-reduce for TP partials) — the
scaling-book recipe: pick a mesh, annotate, let the compiler work.

Conventions (megatron-style, FSDP on the long axis):
- embedding [vocab, d]           -> (fsdp, tp)
- attn qkv  [d, heads*head_dim]  -> (fsdp, tp)
- attn out  [heads*head_dim, d]  -> (tp, fsdp)
- mlp in/gate [d, ffn]           -> (fsdp, tp)
- mlp out  [ffn, d]              -> (tp, fsdp)
- norms / scalars                -> replicated
- activations [batch, seq, d]    -> ((slice, dp, fsdp), sp, tp)
"""

from __future__ import annotations

import re
from typing import Any, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _present(mesh: Mesh, *axes: str) -> Tuple:
    """Keep only axes that exist (size > 1 handled fine) in this mesh; a rule
    mentioning an absent axis must degrade to replication on that dim."""
    out = []
    for axis in axes:
        if isinstance(axis, (tuple, list)):
            sub = tuple(a for a in axis if a in mesh.shape)
            out.append(sub if sub else None)
        else:
            out.append(axis if axis in mesh.shape else None)
    return tuple(out)


# (path regex, spec axes per dim) — first match wins. Paths are joined with
# '/' and lowercased, e.g. "params/layers_0/attention/wq/kernel". A dict
# value selects by ndim (attention kernels are [d, heads, head_dim] when the
# head axes are kept separate, [d, h*hd] when merged).
#
# The "expert" pseudo-axis on MoE weights resolves per mesh (see
# _resolve_expert_axis): `ep` when the mesh has one; otherwise the leading
# expert dim shards over `fsdp` whenever the expert count divides it —
# each device then stores its experts WHOLE and the dispatch all-to-all
# moves tokens to them, instead of FSDP slicing every expert over `d` and
# all-gathering ALL e experts' weights every step (e× the dense FFN's
# weight traffic; the measured moe-125m killer on ep-less meshes).
_PARAM_RULES = [
    # MoE expert weights [experts, d, ffn] / [experts, ffn, d]: experts over
    # the resolved expert axis, then the usual megatron layout within each.
    (r"experts.*(w1|w3|gate|up).*", ("expert", "fsdp", "tp")),
    (r"experts.*(w2|down).*", ("expert", "tp", "fsdp")),
    (r"router.*kernel", (None, None)),
    # Embedding [vocab, d]: vocab over fsdp, d over tp. The reverse
    # (vocab/tp, d/fsdp) makes both the fwd token gather and the bwd
    # grad-scatter prefer d-over-fsdp activation layouts that clash with
    # the canonical batch-sharded layout — SPMD bridges the clash with an
    # involuntary full remat of the embedding boundary every step.
    (r"embed(ding)?s?.*(embedding|kernel)", ("fsdp", "tp")),
    (r"(wq|wk|wv|qkv|query|key|value).*kernel", {2: ("fsdp", "tp"), 3: ("fsdp", "tp", None)}),
    (r"(wo|out_proj|o_proj|attn_out).*kernel", {2: ("tp", "fsdp"), 3: ("tp", None, "fsdp")}),
    (r"(w1|w3|gate_proj|up_proj|gate|up).*kernel", ("fsdp", "tp")),
    (r"(w2|down_proj|down).*kernel", ("tp", "fsdp")),
    (r"(lm_head|output|logits).*kernel", ("fsdp", "tp")),
    (r"(norm|scale|bias|ln)", (None,)),
]


def _resolve_expert_axis(mesh: Mesh, n_experts: Optional[int]) -> Optional[str]:
    """Mesh axis carrying the MoE expert dim: `ep` when present, else
    `fsdp` when the expert count divides it (each device holds whole
    experts — expert parallelism riding the data axis), else None
    (replicated experts; an fsdp extent that doesn't divide e would leave
    devices idle during expert compute)."""
    if "ep" in mesh.shape:
        return "ep"
    fsdp = mesh.shape.get("fsdp", 0)
    if fsdp and fsdp > 1 and n_experts and n_experts % fsdp == 0:
        return "fsdp"
    return None


def moe_expert_axes(mesh: Optional[Mesh], n_experts: int):
    """(expert_axis, batch_axes) for the MoE dispatch/combine activation
    constraints ([e, b, cap, d] tensors): the expert dim rides the resolved
    expert axis, the batch dim the REMAINING data axes — the same
    resolution the expert-weight rules use, so dispatch output lands
    exactly on the layout the expert matmuls want."""
    if mesh is None:
        return None, DATA_AXES
    expert_ax = _resolve_expert_axis(mesh, n_experts)
    batch_axes = tuple(a for a in DATA_AXES if a != expert_ax and a != "ep")
    return expert_ax, batch_axes


def spec_for_param(path: str, ndim: int, mesh: Mesh, shape=None) -> P:
    path = path.lower()
    for pattern, axes in _PARAM_RULES:
        if re.search(pattern, path):
            if isinstance(axes, dict):
                axes = axes.get(ndim, axes[max(axes)])
            if "expert" in axes:
                # Resolve the expert pseudo-axis against the ACTUAL expert
                # count. Rules shorter than ndim are right-aligned (the
                # scanned stack prepends a [n_layers] dim), so the shape
                # element under the placeholder sits at pad_offset + index.
                i = tuple(axes).index("expert")
                offset = max(0, ndim - len(axes))
                n_experts = None
                if shape is not None and offset + i < len(shape):
                    n_experts = shape[offset + i]
                expert_ax = _resolve_expert_axis(mesh, n_experts)
                axes = tuple(
                    expert_ax if a == "expert"
                    else (None if a == expert_ax else a)
                    for a in axes
                )
            axes = _present(mesh, *axes)
            if len(axes) < ndim:
                pad = [None] * (ndim - len(axes))
                # The scanned stack's leading layer dim shards over pp when
                # pipelining: each stage stores only its own layers.
                if pad and "pp" in mesh.shape and "layers" in path:
                    pad[0] = "pp"
                axes = tuple(pad) + tuple(axes)
            return P(*axes[:ndim])
    return P()  # replicate by default


def shard_params_spec(params: Any, mesh: Mesh) -> Any:
    """PartitionSpec pytree for a param pytree, by path rules."""

    def walk(path_parts, node):
        if isinstance(node, dict):
            return {k: walk(path_parts + (k,), v) for k, v in node.items()}
        path = "/".join(str(p) for p in path_parts)
        return spec_for_param(
            path, getattr(node, "ndim", 0), mesh,
            shape=getattr(node, "shape", None),
        )

    return walk((), params)


def params_sharding(params: Any, mesh: Mesh) -> Any:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        shard_params_spec(params, mesh),
        is_leaf=lambda x: isinstance(x, P),
    )


DATA_AXES = ("slice", "dp", "fsdp", "ep")


def batch_sharding(mesh: Mesh, with_sp: bool = True) -> NamedSharding:
    """[batch, seq, ...] data sharding: batch over all data axes (ep doubles
    as a data axis outside expert compute), sequence over sp when present
    (ring-attention sequence parallelism)."""
    data_axes = tuple(a for a in DATA_AXES if a in mesh.shape)
    seq_axis = "sp" if (with_sp and "sp" in mesh.shape) else None
    return NamedSharding(mesh, P(data_axes if data_axes else None, seq_axis))


def constrain(x, *axes):
    """`with_sharding_constraint` against the current mesh; a no-op when no
    mesh is scoped (unsharded single-chip runs) or when every named axis is
    absent from it. Axes may be axis names, tuples of names, or None.

    Inside a shard_map region (e.g. the pp pipeline) the trace's abstract
    mesh marks the mapped axes Manual; the constraint must be built on THAT
    mesh — a NamedSharding on the concrete all-Auto mesh is rejected for
    arrays varying over a manual axis."""
    from .mesh import current_mesh

    mesh = current_mesh()
    if mesh is None:
        return x
    # jax 0.4.x compat: get_abstract_mesh (and AxisType) first appeared in
    # 0.5 — on older jax there is no manual-axis trace state to consult,
    # so the constraint applies unconditionally (shard_map regions there
    # use the explicit in-spec plumbing instead).
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    abstract = get_abstract() if get_abstract is not None else None
    if abstract is not None and abstract.shape:
        manual = {
            name
            for name, kind in zip(abstract.axis_names, abstract.axis_types)
            if kind == jax.sharding.AxisType.Manual
        }
        if manual:
            # Inside the region the mapped axes are per-shard and the rest
            # is still auto-partitioned; the boundary constraint is only a
            # layout hint, so skip it rather than fight the manual trace
            # (constraining on the abstract mesh here trips an XLA
            # invalid-opcode CHECK as of jax 0.9 / this libtpu).
            return x
    spec = P(*_present(mesh, *axes))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def logical_axis_rules(mesh: Mesh):
    """flax linen logical-axis rules equivalent for the conventions above
    (for models that use nn.with_logical_partitioning)."""
    return [
        ("batch", tuple(a for a in DATA_AXES if a in mesh.shape) or None),
        ("expert", "ep" if "ep" in mesh.shape else None),
        ("stage", "pp" if "pp" in mesh.shape else None),
        ("seq", "sp" if "sp" in mesh.shape else None),
        ("vocab", "tp" if "tp" in mesh.shape else None),
        ("embed", "fsdp" if "fsdp" in mesh.shape else None),
        ("heads", "tp" if "tp" in mesh.shape else None),
        ("kv", None),
        ("ffn", "tp" if "tp" in mesh.shape else None),
    ]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
