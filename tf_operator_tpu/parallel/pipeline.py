"""Pipeline parallelism (pp mesh axis): GPipe schedule over an ICI chain.

No reference counterpart (SURVEY.md §2.5: the reference predates model
parallelism entirely); this is TPU-native scheduling. The layer stack is
split into `pp` contiguous stages; a batch is split into M microbatches
that flow stage -> stage over `lax.ppermute` (neighbor hops ride ICI).
With T = M + pp - 1 ticks, each stage computes every tick (the classic
GPipe bubble of (pp-1)/T idle work); activations for at most one
microbatch per stage are live at a time.

Implementation notes, all load-bearing:

- `shard_map(..., axis_names={axis_name})` maps ONLY the pp axis; every
  other mesh axis (fsdp/tp/dp) stays automatic, so the stage function's
  internal sharding constraints keep working and the partitioner still
  shards the per-stage compute.
- Stage params enter with the stage axis as leading dim, in_spec
  P("pp") — each stage holds only its own layers (true model-memory
  scaling, not replication).
- The tick loop is a `lax.fori_loop` with `dynamic_slice` /
  `dynamic_update_slice` and `where`-masked injection — no Python-level
  data-dependent control flow, one compiled tick body regardless of M.
- Differentiable end-to-end: ppermute's transpose is the reverse
  permute, so jax.grad produces the 1F1B-equivalent backward schedule
  automatically.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .compat import shard_map, supports_partial_manual


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x,
    *consts,
    num_microbatches: int,
    axis_name: str = "pp",
    mesh=None,
):
    """Run `stage_fn` as a `pp`-stage pipeline over microbatches of `x`.

    stage_fn(params_one_stage, x_mb, *consts) -> y_mb — applies ONE stage's
    layers to one microbatch (same activation shape in and out).
    stage_params: pytree whose leaves have a leading [pp] stage axis.
    x: [batch, ...] activations; batch % num_microbatches == 0.
    consts: extra broadcast inputs (e.g. rope tables) — passed through the
    shard_map explicitly (closure-capturing traced values across the
    manual region is asking for trouble).
    Returns [batch, ...] outputs (the last stage's results).
    """
    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    if mesh is None or axis_name not in mesh.shape:
        # Unsharded fallback: sequential stages (same math, no pipeline).
        n_stages = jax.tree.leaves(stage_params)[0].shape[0]
        for s in range(n_stages):
            x = stage_fn(jax.tree.map(lambda p: p[s], stage_params), x, *consts)
        return x

    pp = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by {num_microbatches} microbatches")
    mb = batch // num_microbatches
    x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])

    # bf16 workaround: XLA (this jax/libtpu vintage) CHECK-fails
    # ("Invalid binary instruction opcode copy") when partitioning the
    # backward of the pipeline loop with bf16 activations flowing through
    # ppermute/where/dynamic-update inside the manual region — empirically,
    # params and boundary dtypes are fine, in-region bf16 activations are
    # not. So the LOOP-level tensors (injected microbatches, ring carry,
    # output buffer) run in f32, and the stage computation casts to the
    # model dtype internally. Cost: 2x ppermute payload; the per-stage
    # matmuls still run in bf16.
    compute_dtype = x_mb.dtype
    if compute_dtype == jnp.bfloat16:
        x_mb = x_mb.astype(jnp.float32)

    def pipelined(params_local, x_all, *consts):
        # params_local: [1, per_stage, ...] (pp-mapped); squeeze the stage dim.
        params_local = jax.tree.map(lambda p: p[0], params_local)
        stage = jax.lax.axis_index(axis_name)
        ticks = num_microbatches + pp - 1
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        from .mesh import mark_varying

        zero = jnp.zeros_like(x_all[0])
        outputs0 = mark_varying(jnp.zeros_like(x_all), (axis_name,))
        recv0 = mark_varying(zero, (axis_name,))

        def tick(t, carry):
            recv, outputs = carry
            # Stage 0 injects microbatch t (clamped; masked out past M).
            mb_idx = jnp.clip(t, 0, num_microbatches - 1)
            injected = jax.lax.dynamic_index_in_dim(x_all, mb_idx, keepdims=False)
            x_in = jnp.where(stage == 0, injected, recv)
            y = stage_fn(params_local, x_in.astype(compute_dtype), *consts)
            y = y.astype(x_all.dtype)
            # The last stage finished microbatch (t - pp + 1) this tick.
            out_idx = jnp.clip(t - pp + 1, 0, num_microbatches - 1)
            take = jnp.logical_and(stage == pp - 1, t >= pp - 1)
            current = jax.lax.dynamic_index_in_dim(outputs, out_idx, keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(take, y, current), out_idx, 0
            )
            recv = jax.lax.ppermute(y, axis_name, perm)
            return recv, outputs

        _, outputs = jax.lax.fori_loop(0, ticks, tick, (recv0, outputs0))
        return outputs

    params_spec = jax.tree.map(lambda _: P(axis_name), stage_params)
    if supports_partial_manual():
        wrapped = shard_map(
            pipelined,
            mesh=mesh,
            axis_names={axis_name},
            in_specs=(params_spec, P(), *(P() for _ in consts)),
            out_specs=P(axis_name),  # stacked per-stage: [pp, M, mb, ...]
        )
    else:
        # jax 0.4.x: partially-manual shard_map is declared inversely —
        # `auto` lists the axes that STAY auto-partitioned (and rep
        # checking doesn't support the mixed mode). Best-effort: that
        # jaxlib typically cannot lower the result (PartitionId under
        # partial SPMD) — callers gate on supports_partial_manual().
        wrapped = shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(params_spec, P(), *(P() for _ in consts)),
            out_specs=P(axis_name),
            auto=frozenset(mesh.axis_names) - {axis_name},
            check_rep=False,
        )
    out = wrapped(stage_params, x_mb, *consts)
    # Only the last stage's slot holds real outputs.
    out = out.reshape(pp, num_microbatches, mb, *x.shape[1:])[-1]
    return out.reshape(batch, *x.shape[1:]).astype(compute_dtype)


def split_stages(stacked_params, pp: int):
    """[n_layers, ...] leaves -> [pp, n_layers/pp, ...] (contiguous stages)."""

    def reshape(p):
        n = p.shape[0]
        if n % pp:
            raise ValueError(f"{n} layers not divisible by {pp} pipeline stages")
        return p.reshape(pp, n // pp, *p.shape[1:])

    return jax.tree.map(reshape, stacked_params)
