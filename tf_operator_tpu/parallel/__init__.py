"""Parallelism: device meshes, sharding rules, collectives.

The reference operator has no in-model parallelism (SURVEY.md §2.5: TP/PP/
SP/EP are absent — it scales replica count only). In the TPU-native design
the operator publishes topology (JAXJob `mesh`), and this package turns it
into `jax.sharding.Mesh` + PartitionSpecs so XLA inserts the collectives:
DP/FSDP over the data axes, TP over heads/ffn, SP over sequence, and a
leading DCN axis for multislice.
"""

from .mesh import MeshSpec, make_mesh, standard_mesh
from .sharding import batch_sharding, logical_axis_rules, shard_params_spec

__all__ = [
    "MeshSpec",
    "make_mesh",
    "standard_mesh",
    "batch_sharding",
    "logical_axis_rules",
    "shard_params_spec",
]
