"""Operator process: flags, controller manager, health/metrics endpoints,
leader election.

The L4 tier (SURVEY.md §2.1): the analog of cmd/training-operator.v1/main.go
(scheme registration, --enable-scheme, metrics/health binds, leader elect,
manager start) merged with the legacy server's namespace scoping, resync
period, threadiness and gang flags (cmd/tf-operator.v1/app/options/
options.go:27-83) — one binary, not the reference's dual stack (SURVEY.md §7
anti-goals).

Run: ``python -m tf_operator_tpu --enable-scheme JAXJob --namespace train``.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import logging
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .cluster.base import Cluster
from .controllers import SUPPORTED_CONTROLLERS, enabled_kinds
from .core.job_controller import EngineOptions
from .metrics import METRICS, Metrics

log = logging.getLogger("tf_operator_tpu.operator")

# Periodic resync jitter window is half the resync period, capped: with a
# multi-hour production resync the herd is already rare, and a >10s spread
# would visibly delay the dropped-watch-event safety net.
RESYNC_JITTER_CAP = 10.0


def resync_jitter_seconds(item: str, window: float) -> float:
    """Deterministic per-key delay in [0, window) for periodic resync
    enqueues: a hash of the queue item, not `random`, so two runs (and a
    seeded replay harness) spread the same jobs identically. Keys are
    stable across rounds, which is what matters — the herd is the
    same-instant alignment WITHIN a round, not correlation across rounds."""
    if window <= 0:
        return 0.0
    digest = hashlib.sha256(item.encode()).digest()
    return window * (int.from_bytes(digest[:8], "big") / 2**64)


# ------------------------------------------------------------------ options


@dataclass
class OperatorOptions:
    """Reference ServerOption (options.go:27-43) + new-binary flags
    (main.go:62-75)."""

    enabled_schemes: List[str] = field(default_factory=list)  # empty = all
    namespace: str = ""  # empty = all namespaces
    # Sync workers per controller (--workers; client-go
    # MaxConcurrentReconciles, the legacy server's --threadiness). The
    # default is concurrent: one worker per kind serialized every job in
    # the namespace behind one reconcile at a time, and the scale
    # benchmark showed queue wait — not write latency — dominating at 100
    # jobs. Fault-injection seams (chaos/process) force 1 regardless via
    # supports_concurrent_syncs, so determinism tiers never see a pool.
    threadiness: int = 4
    resync_period: float = 30.0
    bind_address: str = "0.0.0.0"  # kubelet probes reach the pod IP, not loopback
    metrics_port: int = 8443
    health_port: int = 8081
    leader_elect: bool = False
    lease_duration: float = 15.0
    lease_name: str = "tf-operator-tpu-lock"
    # Sharded active-active control plane (core/sharding.py): the job key
    # space is hash-split into this many shards, each guarded by its own
    # Lease; N replicas each claim their membership-ranked subset and
    # reconcile ONLY their shards' jobs. 1 (the default) builds none of
    # it — the global is_leader gate and zero extra lease traffic, so
    # every seeded chaos/crash tier replays byte-identically. >1
    # supersedes --leader-elect (the shard claims ARE the election).
    shards: int = 1
    # Stable replica identity for membership ranking + lease holdership
    # (recommended: the StatefulSet pod name). Empty = hostname + a uuid
    # suffix, which still works but reshuffles shard targets on restart.
    replica_id: str = ""
    # Shard placement mode (core/sharding.py shard_for_key). "uniform"
    # (default): sha256(ns/name) — the PR 8 behavior, byte-identical.
    # "namespace": rendezvous-hash the NAMESPACE first so one tenant's
    # jobs co-locate on one replica's warm watch caches; the spread knob
    # below widens a tenant over its top-K rendezvous shards when it
    # outgrows one (spread >= shards degrades to the uniform per-key
    # spread). Must be configured identically on every replica, like
    # --shards itself.
    shard_affinity: str = "uniform"
    shard_affinity_spread: int = 1
    # Optional path whose integer content is the DESIRED shard count:
    # SIGHUP re-reads it and publishes a live resize (the config-lease
    # protocol every replica migrates through). The /debugz resize verb
    # does the same without a file.
    shards_file: str = ""
    enable_debugz: bool = False  # /debugz exposes thread stacks: opt-in only
    # /tracez exposes per-job timelines (pod names, restart causes, the
    # full apiserver call sequence) on the 0.0.0.0 metrics port — same
    # exposure class as /debugz, same opt-in rule.
    enable_tracez: bool = False
    enable_gang_scheduling: bool = False
    gang_scheduler_name: str = "volcano"
    json_log_format: bool = False
    # Client write throttling (reference QPS 5 / burst 10 defaults are for
    # a remote apiserver; in-process default is unlimited).
    qps: float = 0.0
    burst: int = 0
    # Slow-start parallel replica fan-out (upstream slowStartBatch). On by
    # default; chaos/process cluster seams serialize themselves via the
    # supports_concurrent_writes capability regardless. Disabling is the
    # serial-baseline lever for the scale benchmark.
    parallel_fanout: bool = True
    fanout_max_parallelism: int = 16
    # Apiserver write-pressure collapse (status-write coalescing +
    # batched create/delete events + the patch_job_status verb). On by
    # default; chaos/process seams pin it off via the
    # supports_write_coalescing capability regardless. Disabling is the
    # legacy-write-path lever for the scale benchmark.
    write_coalescing: bool = True
    # Per-job floor between coalesced status flushes: churn inside the
    # window is buffered and carried by a scheduled flush.
    status_flush_interval: float = 1.0
    # Fast-recovery peer restore (docs/design/checkpoint_recovery.md):
    # heartbeat-enabled replicas run a snapshot shard server and recreated
    # pods get survivor addresses for the restore ladder's peer leg. Off
    # (the default) = no pod env changes and no new annotations consumed,
    # so every PR 1-15 seeded tier replays byte-identically.
    enable_peer_restore: bool = False
    # Scatter-gather restore: pods additionally advertise strided shard
    # ownership (/v1/manifest) and restorers pull shards from EVERY
    # survivor in parallel instead of one peer's bundle. Requires
    # --enable-peer-restore; off by default for seeded-replay parity.
    enable_sharded_restore: bool = False
    # Checkpoint-free warm start: pods created by an elastic grow get
    # TPU_WARM_START=1 so their restore pulls live peer snapshots with
    # zero storage reads. Requires --enable-peer-restore.
    enable_warm_start: bool = False
    # Delta checkpoint persists: heartbeat-enabled replicas get
    # TPU_DELTA_PERSIST=1 so their CheckpointManager writes only changed
    # shards + a step manifest, and peer restores advertise a have-list —
    # persist and recovery bytes O(changed shards). Off by default for
    # seeded-replay parity (no delta/ layout is ever written).
    enable_delta_persist: bool = False
    # Capacity-aware gang admission (core/admission.py,
    # docs/design/gang_admission.md). Off (the default) = first-come,
    # capacity-blind admission exactly as before — every PR 1-8 seeded
    # tier replays byte-identically because the arbiter is never built.
    # On: jobs queue against the declared --capacity pool with per-tenant
    # quotas, priority bands, preempt-lowest-band on contention, and
    # bounded backfill with an aging starvation bound.
    enable_gang_admission: bool = False
    # The declared capacity pool: "res=qty[,res=qty...]", e.g.
    # "google.com/tpu=128,pods=32". The synthetic `pods` resource counts
    # gang members (summed minMember), so pools can be declared in plain
    # pod slots when templates carry no resource requests. Backends with
    # a schedulable-capacity model (the in-memory simulator) also bound
    # the pool live — a seeded capacity revocation shrinks it mid-run.
    # "res@generation=qty" entries declare device-GENERATION sub-pools
    # (e.g. "pods@v5lite=8,pods@v6=8"): the flat pool is their sum, and
    # --admission-policy gavel places gangs per generation to maximize
    # effective fleet throughput (schedulingPolicy.throughputRatios).
    capacity: str = ""
    # The admission decision procedure (core/policies.py):
    # priority (default — the PR 9 bands+quotas+backfill arbiter,
    # byte-identical), gavel (heterogeneity-aware effective-throughput
    # placement), or drf (weighted dominant-resource fairness).
    admission_policy: str = "priority"
    # Weighted-DRF tenant weights, each entry "ns=w" (positive float);
    # tenants absent ride weight 1.0. Only --admission-policy drf reads
    # them.
    tenant_weights: List[str] = field(default_factory=list)
    # Explicit decision seed threaded into every policy call: classical
    # policies ignore it, a learned/randomized policy draws its entropy
    # ONLY from it — decisions stay a pure function of
    # (queue, pool, usage, seed).
    admission_seed: int = 0
    # Per-tenant quotas: each entry "ns:res=qty[,res=qty...]".
    namespace_quotas: List[str] = field(default_factory=list)
    # Backfill bound: a waiting gang with at most this many members may
    # jump the queue into a capacity gap; 0 disables backfill.
    backfill_max_members: int = 8
    # Aging bound: once the head-of-line gang has waited this long, no
    # backfill admits until it does (starvation-freedom).
    admission_aging_seconds: float = 300.0
    # Per-SLICE admission granularity (flagged headroom): a multislice
    # job's slices register as individually admittable/preemptable/
    # backfillable demands, so a capacity revocation preempts ONE slice
    # (slice-local counted teardown + slice-local re-queue) instead of
    # evicting the whole job. Off (default) keeps the PR 9 job-granular
    # arbiter byte-identical.
    admission_slice_granularity: bool = False
    # Incremental admissibility index (EngineOptions.admission_index):
    # the arbiter maintains per-band min-demand watermarks, a capacity
    # epoch / dirty bit, and incremental PolicyState structures so a
    # pump is O(newly-fittable) instead of O(waiting set). Schedule-
    # equivalent by contract (byte-equal decision logs); off (default)
    # keeps the historical full-scan pump byte-identical.
    enable_admission_index: bool = False
    # Signal-driven gang autoscaler (core/autoscaler.py, one per operator
    # like the AdmissionController): automatically resizes elastic
    # JAXJob gangs through the existing spec-resize path from the
    # admission pool's free-capacity watermark, queue pressure, and the
    # heartbeat tokens_per_sec/checkpoint lease stream. Off (default) =
    # the controller is never built and no loop thread exists, so every
    # seeded PR 1-14 tier replays byte-identically. Requires
    # --enable-gang-admission (the pool IS the watermark signal).
    enable_autoscaler: bool = False
    autoscaler_interval: float = 5.0
    autoscaler_watermark_pods: float = 2.0
    autoscaler_hold_seconds: float = 15.0
    autoscaler_dwell_seconds: float = 30.0
    autoscaler_cooldown_seconds: float = 60.0
    autoscaler_efficiency_floor: float = 0.7
    autoscaler_seed: int = 0


def build_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tf-operator-tpu",
        description="TPU-native training operator (control plane for "
        "TFJob/PyTorchJob/MXJob/XGBoostJob/JAXJob).",
    )
    parser.add_argument(
        "--enable-scheme",
        action="append",
        default=[],
        metavar="KIND",
        help="Job kind to enable (repeatable); default: all supported kinds.",
    )
    parser.add_argument(
        "--namespace",
        default=os.environ.get("KUBEFLOW_NAMESPACE", ""),
        help="Restrict to one namespace (default: $KUBEFLOW_NAMESPACE, else all).",
    )
    parser.add_argument(
        "--workers", "--threadiness", dest="threadiness", type=int, default=4,
        help="Sync workers per controller (MaxConcurrentReconciles): N "
        "threads pull from the controller's workqueue, reconciling "
        "different jobs concurrently while the queue's dirty/processing "
        "sets keep each job serialized. Backends that cannot tolerate "
        "concurrent syncs (chaos/process test seams) force 1. "
        "--threadiness is the deprecated alias.",
    )
    parser.add_argument("--resync-period", type=float, default=30.0, help="Full relist/resync seconds.")
    parser.add_argument("--bind-address", default="0.0.0.0", help="Address metrics/health servers bind.")
    parser.add_argument("--metrics-port", type=int, default=8443, help="Prometheus /metrics port (0 = off).")
    parser.add_argument("--health-port", type=int, default=8081, help="/healthz,/readyz port (0 = off).")
    parser.add_argument("--leader-elect", action="store_true", help="Require leadership before reconciling.")
    parser.add_argument("--lease-duration", type=float, default=15.0, help="Leader lease seconds.")
    parser.add_argument("--lease-name", default="tf-operator-tpu-lock",
                        help="Name of the coordination.k8s.io Lease used for election.")
    parser.add_argument("--shards", type=int, default=1,
                        help="Shard the job key space across this many "
                        "lease-claimed shards (consistent namespace/name "
                        "hash); run N replicas with the same --shards and "
                        "each claims its membership-ranked subset. 1 "
                        "(default) = the single-leader behavior; >1 "
                        "supersedes --leader-elect.")
    parser.add_argument("--replica-id", default="",
                        help="Stable identity for shard membership ranking "
                        "(recommended: the StatefulSet pod name). Default: "
                        "hostname plus a random suffix.")
    parser.add_argument("--shard-affinity", choices=("uniform", "namespace"),
                        default="uniform",
                        help="Shard placement: 'uniform' hashes ns/name "
                        "(the default); 'namespace' rendezvous-hashes the "
                        "namespace first so one tenant's jobs co-locate on "
                        "one replica's warm watch caches. Set identically "
                        "on every replica.")
    parser.add_argument("--shard-affinity-spread", type=int, default=1,
                        help="With --shard-affinity namespace: spread each "
                        "tenant over its top-K rendezvous shards (1 = whole "
                        "tenant on one shard; >= --shards = the uniform "
                        "per-key spread — the fallback for a tenant that "
                        "outgrows a shard).")
    parser.add_argument("--shards-file", default="",
                        help="Path holding the desired shard count; SIGHUP "
                        "re-reads it and publishes a LIVE resize (drain-"
                        "based migration, no redeploy). /debugz/resize is "
                        "the HTTP equivalent.")
    parser.add_argument("--enable-debugz", action="store_true",
                        help="Expose /debugz (thread stacks, queue depths) on the metrics port.")
    parser.add_argument("--enable-tracez", action="store_true",
                        help="Expose /tracez (per-job lifecycle span timelines, "
                        "core/tracing.py) on the metrics port; pretty-print "
                        "with scripts/trace_dump.py.")
    parser.add_argument("--enable-gang-scheduling", action="store_true")
    parser.add_argument("--gang-scheduler-name", default="volcano")
    parser.add_argument("--enable-gang-admission", action="store_true",
                        help="Capacity-aware gang admission "
                        "(core/admission.py): jobs queue against the "
                        "--capacity pool with per-tenant quotas, priority "
                        "bands (schedulingPolicy.priorityClass), "
                        "preempt-lowest-band on contention, and bounded "
                        "backfill. Default off = first-come admission "
                        "exactly as before.")
    parser.add_argument("--enable-admission-index", action="store_true",
                        help="Incremental admissibility index for the "
                        "gang-admission arbiter: per-band min-demand "
                        "watermarks, a capacity epoch/dirty bit, and "
                        "incrementally-maintained policy state make a "
                        "pump O(newly-fittable) instead of O(waiting "
                        "set). Schedule-equivalent to the full scan "
                        "(byte-equal decision logs). Default off.")
    parser.add_argument("--capacity", default="",
                        help="Declared admission pool, 'res=qty[,res=qty]' "
                        "(e.g. 'google.com/tpu=128,pods=32'); 'pods' "
                        "counts gang members. Empty = unbounded (quota/"
                        "priority arbitration only). 'res@generation=qty' "
                        "entries declare device-generation sub-pools "
                        "(e.g. 'pods@v5lite=8,pods@v6=8') for "
                        "--admission-policy gavel; the flat pool is "
                        "their sum.")
    from .core.policies import POLICIES

    parser.add_argument("--admission-policy",
                        choices=sorted(POLICIES),
                        default="priority",
                        help="Admission decision procedure "
                        "(core/policies.py): 'priority' (default) = the "
                        "bands+quotas+backfill arbiter, byte-identical "
                        "to before the policy seam; 'gavel' = "
                        "heterogeneity-aware placement maximizing "
                        "effective fleet throughput across device "
                        "generations (schedulingPolicy.throughputRatios)"
                        "; 'drf' = weighted dominant-resource fairness "
                        "across tenants (--tenant-weight), replacing "
                        "hard quota ceilings with a work-conserving "
                        "share bound.")
    parser.add_argument("--tenant-weight", action="append", default=[],
                        metavar="NS=WEIGHT",
                        help="Weighted-DRF tenant weight (repeatable; "
                        "positive number, default 1.0 per tenant). Read "
                        "by --admission-policy drf.")
    parser.add_argument("--admission-seed", type=int, default=0,
                        help="Decision seed threaded into the admission "
                        "policy (decisions are a pure function of "
                        "queue/pool/usage/seed; classical policies "
                        "ignore it).")
    parser.add_argument("--namespace-quota", action="append", default=[],
                        metavar="NS:RES=QTY[,RES=QTY]",
                        help="Per-tenant admission quota (repeatable).")
    parser.add_argument("--backfill-max-members", type=int, default=8,
                        help="Largest gang (by member count) eligible to "
                        "backfill into a capacity gap ahead of the "
                        "head-of-line; 0 disables backfill.")
    parser.add_argument("--admission-aging-seconds", type=float, default=300.0,
                        help="Once the head-of-line gang has waited this "
                        "long, backfill stops until it admits "
                        "(starvation bound).")
    parser.add_argument("--enable-autoscaler", action="store_true",
                        help="Signal-driven gang autoscaler "
                        "(core/autoscaler.py): automatically resizes "
                        "elastic JAXJobs (spec.elastic bounds) through "
                        "the validated spec-resize path — grows into "
                        "held free-capacity surplus (scale-efficiency "
                        "guarded), shrinks under admission queue "
                        "pressure only after a fresh checkpoint lands "
                        "(record_checkpoint lease rider), with dwell + "
                        "post-disruption cooldown hysteresis. Requires "
                        "--enable-gang-admission. Default off.")
    parser.add_argument("--autoscaler-interval", type=float, default=5.0,
                        help="Seconds between autoscaler control-loop "
                        "ticks.")
    parser.add_argument("--autoscaler-watermark-pods", type=float,
                        default=2.0,
                        help="Free pod slots above this are growable "
                        "surplus.")
    parser.add_argument("--autoscaler-hold-seconds", type=float,
                        default=15.0,
                        help="Surplus must persist this long (queue "
                        "empty throughout) before a grow fires.")
    parser.add_argument("--autoscaler-dwell-seconds", type=float,
                        default=30.0,
                        help="Minimum time between two resizes of one "
                        "job.")
    parser.add_argument("--autoscaler-cooldown-seconds", type=float,
                        default=60.0,
                        help="No resizes of a job inside this window "
                        "after an observed disruption/restart (the "
                        "capacity-revocation anti-flap).")
    parser.add_argument("--autoscaler-efficiency-floor", type=float,
                        default=0.7,
                        help="After a grow, tokens/sec-per-worker must "
                        "stay >= this fraction of the pre-grow baseline "
                        "for further grows.")
    parser.add_argument("--autoscaler-seed", type=int, default=0,
                        help="Decision seed threaded into the autoscaler "
                        "state (same purity contract as "
                        "--admission-seed).")
    parser.add_argument("--admission-slice-granularity", action="store_true",
                        help="Admit multislice jobs one SLICE at a time: "
                        "each slice is its own admission demand — "
                        "individually admittable, preemptable (slice-"
                        "local counted teardown; surviving slices keep "
                        "running) and backfillable. Default off = the "
                        "job-granular arbiter.")
    parser.add_argument("--json-log-format", action="store_true",
                        help="Deprecated alias for --log-format json.")
    parser.add_argument("--log-format", choices=("text", "json"), default="text",
                        help="json: one JSON object per log record, stamped "
                        "with the active job key and trace/span ids "
                        "(core/tracing.py) when the record is emitted "
                        "inside a reconcile.")
    parser.add_argument("--qps", type=float, default=0.0,
                        help="Client write QPS limit (0 = unlimited; reference default 5).")
    parser.add_argument("--burst", type=int, default=0,
                        help="Client write burst (reference default 10).")
    parser.add_argument("--disable-parallel-fanout", action="store_true",
                        help="Serialize replica create/delete fan-out (the "
                        "serial baseline; default is slow-start parallel batches).")
    parser.add_argument("--fanout-max-parallelism", type=int, default=16,
                        help="Max in-flight writes of one slow-start fan-out batch.")
    parser.add_argument("--disable-write-coalescing", action="store_true",
                        help="Disable status-write coalescing and batched "
                        "create/delete events (the legacy one-update-per-"
                        "sync write path; default is coalesced single-"
                        "request status patches on capable backends).")
    parser.add_argument("--enable-peer-restore", action="store_true",
                        help="Fast-recovery peer restore: heartbeat-enabled "
                             "replicas serve host-snapshot shards and "
                             "recreated pods receive survivor addresses "
                             "(TPU_PEER_RESTORE_ADDRS) so their restore "
                             "ladder can skip the storage round-trip.")
    parser.add_argument("--enable-sharded-restore", action="store_true",
                        help="Scatter-gather restore on top of "
                             "--enable-peer-restore: shard servers "
                             "advertise strided ownership (/v1/manifest) "
                             "and restorers pull shards from every "
                             "survivor in parallel, so recovery no longer "
                             "rides a single peer's bundle.")
    parser.add_argument("--enable-warm-start", action="store_true",
                        help="Checkpoint-free elastic warm start on top of "
                             "--enable-peer-restore: pods created by a "
                             "grow get TPU_WARM_START=1 and restore from "
                             "live peer snapshots with zero storage "
                             "reads.")
    parser.add_argument("--enable-delta-persist", action="store_true",
                        help="Delta checkpoint persists: workloads get "
                             "TPU_DELTA_PERSIST=1 so persists write only "
                             "changed shards + a step manifest, and peer "
                             "restores advertise a have-list — recovery "
                             "bytes proportional to change.")
    parser.add_argument("--status-flush-interval", type=float, default=1.0,
                        help="Per-job floor (seconds) between coalesced "
                        "status flushes; replica-count churn inside the "
                        "window is buffered and flushed on its close.")
    parser.add_argument("--kube", action="store_true",
                        help="Reconcile a real cluster via the kube-apiserver "
                        "(in-cluster service-account auth, or --kube-url/--kube-token).")
    parser.add_argument("--kube-url", default="", help="Apiserver base URL (default: in-cluster).")
    parser.add_argument("--kube-token", default="", help="Bearer token (default: service-account file).")
    parser.add_argument("--kube-insecure", action="store_true", help="Skip TLS verification.")
    parser.add_argument("--kubeconfig", default="",
                        help="Path to a kubeconfig file (default: $KUBECONFIG, "
                        "then ~/.kube/config; the reference's clientcmd "
                        "resolution, server.go:97-107). Implies --kube.")
    parser.add_argument("--kube-context", default="",
                        help="Kubeconfig context to use (default: current-context).")
    return parser


def options_from_args(args: argparse.Namespace) -> OperatorOptions:
    return OperatorOptions(
        enabled_schemes=list(args.enable_scheme),
        namespace=args.namespace,
        threadiness=args.threadiness,
        resync_period=args.resync_period,
        bind_address=args.bind_address,
        metrics_port=args.metrics_port,
        health_port=args.health_port,
        leader_elect=args.leader_elect,
        lease_duration=args.lease_duration,
        lease_name=args.lease_name,
        shards=args.shards,
        replica_id=args.replica_id,
        shard_affinity=args.shard_affinity,
        shard_affinity_spread=args.shard_affinity_spread,
        shards_file=args.shards_file,
        enable_debugz=args.enable_debugz,
        enable_tracez=args.enable_tracez,
        enable_gang_scheduling=args.enable_gang_scheduling,
        gang_scheduler_name=args.gang_scheduler_name,
        json_log_format=args.json_log_format or args.log_format == "json",
        qps=args.qps,
        burst=args.burst,
        parallel_fanout=not args.disable_parallel_fanout,
        fanout_max_parallelism=args.fanout_max_parallelism,
        write_coalescing=not args.disable_write_coalescing,
        status_flush_interval=args.status_flush_interval,
        enable_peer_restore=args.enable_peer_restore,
        enable_sharded_restore=args.enable_sharded_restore,
        enable_warm_start=args.enable_warm_start,
        enable_delta_persist=args.enable_delta_persist,
        enable_gang_admission=args.enable_gang_admission,
        capacity=args.capacity,
        namespace_quotas=list(args.namespace_quota),
        backfill_max_members=args.backfill_max_members,
        admission_aging_seconds=args.admission_aging_seconds,
        admission_slice_granularity=args.admission_slice_granularity,
        enable_admission_index=args.enable_admission_index,
        admission_policy=args.admission_policy,
        tenant_weights=list(args.tenant_weight),
        admission_seed=args.admission_seed,
        enable_autoscaler=args.enable_autoscaler,
        autoscaler_interval=args.autoscaler_interval,
        autoscaler_watermark_pods=args.autoscaler_watermark_pods,
        autoscaler_hold_seconds=args.autoscaler_hold_seconds,
        autoscaler_dwell_seconds=args.autoscaler_dwell_seconds,
        autoscaler_cooldown_seconds=args.autoscaler_cooldown_seconds,
        autoscaler_efficiency_floor=args.autoscaler_efficiency_floor,
        autoscaler_seed=args.autoscaler_seed,
    )


# ----------------------------------------------------------- leader election


class LeaseLock:
    """In-process lock for tests that want a controllable election without a
    cluster. Production replicas use ClusterLeaseLock (core/leaderelection.py)
    — an apiserver-backed coordination.k8s.io/v1 Lease with optimistic-
    concurrency acquire/renew/steal, the analog of the reference's
    EndpointsLock election (server.go:168-196). OperatorManager defaults to
    the cluster-backed lock; pass this one explicitly to simulate."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._holder: Optional[str] = None
        self._expires: float = 0.0

    def try_acquire(self, identity: str, duration: float) -> bool:
        with self._lock:
            now = self._clock()
            if self._holder in (None, identity) or now >= self._expires:
                self._holder = identity
                self._expires = now + duration
                return True
            return False

    def release(self, identity: str) -> None:
        with self._lock:
            if self._holder == identity:
                self._holder = None
                self._expires = 0.0

    @property
    def holder(self) -> Optional[str]:
        with self._lock:
            if self._clock() >= self._expires:
                return None
            return self._holder


# ------------------------------------------------------------ health server


class _BaseHandler(BaseHTTPRequestHandler):
    manager: "OperatorManager"

    def _respond(self, code: int, body: str, content_type: str = "text/plain") -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        log.debug("http: " + fmt, *args)


class _HealthHandler(_BaseHandler):
    """/healthz + /readyz on --health-port (reference main.go:110-117)."""

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.path.startswith("/healthz"):
            self._respond(200, "ok")
        elif self.path.startswith("/readyz"):
            ready = self.manager.ready
            self._respond(200 if ready else 503, "ok" if ready else "not ready")
        else:
            self._respond(404, "not found")


class _MetricsHandler(_BaseHandler):
    """Prometheus /metrics + /debugz + /tracez on --metrics-port. /debugz
    is the analog of the reference's pprof-on-monitoring-port (blank
    import in cmd/tf-operator.v1/main.go:21): live thread stacks and
    per-controller workqueue depths for diagnosing a stuck operator.
    /tracez (opt-in, --enable-tracez — same exposure rule as /debugz)
    serves the recent job-lifecycle traces (core/tracing.py) as JSON —
    ?namespace= and ?job= filter, ?limit=N keeps the newest N;
    pretty-print with scripts/trace_dump.py."""

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.path.startswith("/metrics"):
            self._respond(200, self.manager.metrics.render(), "text/plain; version=0.0.4")
        elif self.path.startswith("/tracez"):
            # Same exposure class as /debugz (the port binds 0.0.0.0 for
            # Prometheus): per-job timelines carry pod names, restart
            # causes, and the apiserver call sequence — opt-in only.
            if not self.manager.options.enable_tracez:
                self._respond(404, "tracez disabled (--enable-tracez)")
                return
            from urllib.parse import parse_qs, urlparse

            query = parse_qs(urlparse(self.path).query)

            def first(name):
                values = query.get(name)
                return values[0] if values else None

            try:
                limit = int(first("limit")) if first("limit") else None
            except ValueError:
                limit = -1
            if limit is not None and limit < 0:
                self._respond(400, "limit must be a non-negative integer")
                return
            self._respond(
                200,
                self.manager.tracer.export_json(
                    namespace=first("namespace") or None,
                    job=first("job") or None,
                    limit=limit,
                ),
                "application/json",
            )
        elif self.path.startswith("/debugz"):
            # Thread stacks leak file paths and internal state; the port
            # binds 0.0.0.0 for Prometheus, so diagnostics are opt-in
            # (--enable-debugz), mirroring how pprof exposure is gated.
            if not self.manager.options.enable_debugz:
                self._respond(404, "debugz disabled (--enable-debugz)")
                return
            self._respond(
                200,
                json.dumps(self.manager.debug_snapshot(), indent=2),
                "application/json",
            )
        else:
            self._respond(404, "not found")

    def do_POST(self):  # noqa: N802 (stdlib API)
        # /debugz/resize?shards=N — the live shard-count admin verb
        # (SIGHUP + --shards-file is the file-driven equivalent). Same
        # exposure gate as the rest of /debugz: a mutation verb on the
        # 0.0.0.0 metrics port is strictly opt-in.
        if not self.path.startswith("/debugz/resize"):
            self._respond(404, "not found")
            return
        if not self.manager.options.enable_debugz:
            self._respond(404, "debugz disabled (--enable-debugz)")
            return
        from urllib.parse import parse_qs, urlparse

        query = parse_qs(urlparse(self.path).query)
        raw = (query.get("shards") or [""])[0]
        try:
            shards = int(raw)
            if shards < 1:
                raise ValueError
        except ValueError:
            self._respond(400, "shards must be a positive integer")
            return
        try:
            epoch = self.manager.request_resize(shards)
        except RuntimeError as err:
            self._respond(409, str(err))
            return
        except Exception as err:  # noqa: BLE001 — apiserver write failed
            self._respond(502, f"resize publish failed: {err}")
            return
        self._respond(
            200, json.dumps({"shards": shards, "ring_epoch": epoch}),
            "application/json",
        )


# ----------------------------------------------------------------- manager


class OperatorManager:
    """Hosts one controller per enabled kind and drains their workqueues —
    the controller-runtime Manager analog (main.go:78-120)."""

    def __init__(
        self,
        cluster: Cluster,
        options: Optional[OperatorOptions] = None,
        metrics: Optional[Metrics] = None,
        lease: Optional[LeaseLock] = None,
        identity: Optional[str] = None,
        tracer=None,
    ):
        self.cluster = cluster
        self.options = options or OperatorOptions()
        self.metrics = metrics if metrics is not None else METRICS
        if tracer is None:
            # Process-wide default like METRICS; benches/tests that need
            # isolation inject their own Tracer.
            from .core.tracing import TRACER

            tracer = TRACER
        self.tracer = tracer
        if lease is None:
            # Production default: the election is arbitrated by the cluster
            # (coordination.k8s.io Lease), so two operator PROCESSES cannot
            # both lead — the in-process LeaseLock is only for tests that
            # inject it.
            from .core.leaderelection import ClusterLeaseLock

            # Lease lives in the scoped namespace, else the operator pod's
            # own namespace (where election RBAC is granted in-cluster).
            lease = ClusterLeaseLock(
                cluster,
                namespace=self.options.namespace or None,
                name=self.options.lease_name,
            )
        self.lease = lease
        # Identity = --replica-id (stable pod name, the recommended form
        # for shard membership ranking), else pod name in-cluster
        # (reference uses hostname) plus a uuid suffix so colliding local
        # runs stay distinct.
        self.identity = identity or self.options.replica_id or (
            f"{os.environ.get('HOSTNAME', 'operator')}-{uuid.uuid4().hex[:8]}"
        )
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._server: Optional[ThreadingHTTPServer] = None
        self._metrics_server: Optional[ThreadingHTTPServer] = None
        self._started = False
        # Sharded mode replaces the all-or-nothing leader flag with
        # per-shard ownership: _is_leader then means "owns at least one
        # shard" (the worker parking condition + the is_leader gauge),
        # while the per-ITEM gate consults the coordinator. Built BEFORE
        # the controllers so their enqueue scope filter can reference it.
        self.coordinator = None
        owns = None
        if self.options.shards > 1:
            from .core.sharding import ShardCoordinator

            self.coordinator = ShardCoordinator(
                cluster,
                shards=self.options.shards,
                identity=self.identity,
                namespace=self.options.namespace or None,
                lease_name=self.options.lease_name,
                duration=self.options.lease_duration,
                on_claim=self._on_shard_claimed,
                on_release=self._on_shard_released,
                drain_check=self._shard_drained,
                drain_timeout=5.0,
                affinity=self.options.shard_affinity,
                affinity_spread=self.options.shard_affinity_spread,
            )
            # Enqueue filter = admits (warming shards included, so the
            # claim resync's enqueues land); the post-pop SYNC gate
            # (_sync_gate -> allows) additionally excludes warming.
            owns = self.coordinator.admits
        self._is_leader = (
            not self.options.leader_elect and self.coordinator is None
        )

        engine_options = EngineOptions(
            enable_gang_scheduling=self.options.enable_gang_scheduling,
            gang_scheduler_name=self.options.gang_scheduler_name,
            qps=self.options.qps,
            burst=self.options.burst,
            parallel_fanout=self.options.parallel_fanout,
            fanout_max_parallelism=self.options.fanout_max_parallelism,
            sync_workers=self.options.threadiness,
            write_coalescing=self.options.write_coalescing,
            status_flush_interval=self.options.status_flush_interval,
            peer_restore=self.options.enable_peer_restore,
            sharded_restore=self.options.enable_sharded_restore,
            warm_start=self.options.enable_warm_start,
            delta_persist=self.options.enable_delta_persist,
            admission_index=self.options.enable_admission_index,
        )
        # ONE gang-admission arbiter shared by every framework controller
        # (core/admission.py): capacity and quota are operator-wide, so a
        # per-kind arbiter would double-count a mixed fleet. Built only
        # when opted in — the None default keeps every seeded tier's
        # engine byte-identical. Backends with a schedulable-capacity
        # model (the in-memory simulator; the chaos proxy passes it
        # through) also bound the pool live, which is how the seeded
        # capacity-revocation fault reaches admission.
        self.admission = None
        if self.options.enable_gang_admission:
            from .core.admission import (
                AdmissionController,
                parse_capacity_flag,
                parse_quota_flag,
                parse_tenant_weight,
            )

            quotas: Dict[str, Dict[str, str]] = {}
            for entry in self.options.namespace_quotas:
                # Merge per-namespace: two --namespace-quota entries for
                # one tenant compose their resource bounds (a wholesale
                # dict replace would silently drop the first entry's).
                for ns, resources in parse_quota_flag(entry).items():
                    quotas.setdefault(ns, {}).update(resources)
            weights: Dict[str, float] = {}
            for entry in self.options.tenant_weights:
                weights.update(parse_tenant_weight(entry))
            # Extended --capacity syntax: plain entries declare the flat
            # pool; res@generation entries declare device-generation
            # sub-pools (the gavel placement unit).
            flat_capacity, generations = parse_capacity_flag(
                self.options.capacity)
            self.admission = AdmissionController(
                capacity=flat_capacity or None,
                generations=generations or None,
                quotas=quotas,
                backfill_max_members=self.options.backfill_max_members,
                aging_seconds=self.options.admission_aging_seconds,
                metrics=self.metrics,
                capacity_fn=getattr(cluster, "schedulable_capacity", None),
                generations_fn=getattr(
                    cluster, "schedulable_generations", None),
                slice_granular=self.options.admission_slice_granularity,
                policy=self.options.admission_policy,
                tenant_weights=weights,
                seed=self.options.admission_seed,
                admission_index=self.options.enable_admission_index,
                capacity_version_fn=getattr(
                    cluster, "schedulable_capacity_version", None),
            )
        # Signal-driven gang autoscaler (core/autoscaler.py): one per
        # operator, built only when opted in — the None default keeps
        # every seeded tier byte-identical (no object, no loop thread).
        # It reads the admission pool's watermarks, so the admission
        # arbiter is a hard prerequisite: without a pool there is no
        # free-capacity signal to close the loop on.
        self.autoscaler = None
        if self.options.enable_autoscaler:
            if self.admission is None:
                raise ValueError(
                    "--enable-autoscaler requires --enable-gang-admission: "
                    "the admission pool's free-capacity watermark is the "
                    "autoscaler's grow signal"
                )
            from .core.autoscaler import AutoscalerConfig, GangAutoscaler

            self.autoscaler = GangAutoscaler(
                cluster,
                self.admission,
                AutoscalerConfig(
                    watermark_pods=self.options.autoscaler_watermark_pods,
                    hold_seconds=self.options.autoscaler_hold_seconds,
                    dwell_seconds=self.options.autoscaler_dwell_seconds,
                    cooldown_seconds=(
                        self.options.autoscaler_cooldown_seconds
                    ),
                    efficiency_floor=(
                        self.options.autoscaler_efficiency_floor
                    ),
                    seed=self.options.autoscaler_seed,
                    # Warm-start grows cost a peer delta-fill, not a
                    # storage restore: attribute them in the ledger and
                    # pace grow-side hysteresis faster (warm_grow_pacing).
                    warm_start=self.options.enable_warm_start,
                ),
                metrics=self.metrics,
            )
        from .core.control import TokenBucket

        shared_limiter = TokenBucket(self.options.qps, self.options.burst)
        # ONE shared watch cache for every framework controller when the
        # backend's delivery contract allows it (cluster/watchcache.py):
        # constructed BEFORE any controller so its handlers run first in
        # each kind's dispatch order — the store must reflect an event by
        # the time a controller's expectation observes it. KubeCluster
        # declines (its reflector already is the cache); chaos/process
        # decline for determinism.
        self.watch_cache = None
        if getattr(cluster, "supports_watch_cache", False):
            from .cluster.watchcache import SharedWatchCache

            # Shard-scoped when sharded: the coordinator is the scope —
            # the cache keeps (and serves) only owned shards' objects, so
            # per-replica watch/list maintenance falls ~1/N instead of
            # staying fleet-wide. scope=None (single replica) is the
            # PR 7 fleet-wide cache, byte-identical.
            self.watch_cache = SharedWatchCache(
                cluster, namespace=self.options.namespace or None,
                metrics=self.metrics, scope=self.coordinator,
            )
        self.controllers: Dict[str, object] = {}
        for kind in enabled_kinds(self.options.enabled_schemes):
            self.controllers[kind] = SUPPORTED_CONTROLLERS[kind](
                cluster,
                options=engine_options,
                metrics=self.metrics,
                namespace=self.options.namespace,
                limiter=shared_limiter,
                tracer=self.tracer,
                watch_cache=self.watch_cache,
                owns=owns,
                admission=self.admission,
            )
        # Effective pool size per kind: the requested --workers ANDed with
        # the cluster seam's supports_concurrent_syncs capability
        # (resolve_sync_workers) — the chaos/crash/process determinism
        # tiers run with the pool "enabled" but forced serial, exactly
        # like parallel_fanout vs supports_concurrent_writes. Resolved
        # against each controller's own (possibly throttle-wrapped)
        # cluster so proxy seams inherit the inner verdict.
        from .core.job_controller import resolve_sync_workers

        self.sync_workers: Dict[str, int] = {
            kind: resolve_sync_workers(c.engine.options, c.cluster)
            for kind, c in self.controllers.items()
        }
        self._set_leader_gauge()

    # ------------------------------------------------------------- status
    @property
    def ready(self) -> bool:
        return self._started and not self._stop.is_set()

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    def _set_leader_gauge(self) -> None:
        self.metrics.set_gauge("training_operator_is_leader", 1.0 if self._is_leader else 0.0)

    def debug_snapshot(self) -> dict:
        """Live diagnostics for /debugz: thread stacks (what pprof's
        goroutine profile gives the reference) + workqueue depths."""
        import sys
        import traceback

        frames = sys._current_frames()
        threads = {}
        for thread in threading.enumerate():
            frame = frames.get(thread.ident)
            threads[thread.name] = (
                traceback.format_stack(frame) if frame is not None else []
            )
        return {
            "leader": self._is_leader,
            "ready": self.ready,
            "queues": {
                kind: c.queue.depth() for kind, c in self.controllers.items()
            },
            "sync_workers": dict(self.sync_workers),
            # Shard map (core/sharding.py snapshot): per-shard last
            # observed holder, the membership-derived target owner, and
            # this replica's owned/draining sets — the first thing to
            # read when a job "nobody reconciles" is suspected (its
            # shard's holder row answers who should).
            "shards": (
                self.coordinator.snapshot()
                if self.coordinator is not None else None
            ),
            # Admission queue dump (core/admission.py snapshot): bands,
            # queue positions, aging clocks, usage vs capacity/quotas,
            # pending preemptions — the first read when a job sits
            # Queued "for no reason".
            "admission": (
                self.admission.snapshot()
                if self.admission is not None else None
            ),
            # Autoscaler state (core/autoscaler.py snapshot): hysteresis
            # clocks, pending checkpoint-gated shrinks, the resize
            # ledger — the first read when a fleet "resized itself" and
            # someone wants to know which signal drove it.
            "autoscaler": (
                self.autoscaler.snapshot()
                if self.autoscaler is not None else None
            ),
            "threads": threads,
        }

    # ---------------------------------------------------------- run loops
    def _elect_loop(self) -> None:
        duration = self.options.lease_duration
        while not self._stop.is_set():
            # An exception escaping an election round must not kill this
            # thread: _is_leader would stay latched at its last value and a
            # latched-True leader keeps reconciling without renewing while a
            # standby steals the expired lease — dual leaders. Abdicating is
            # the safe direction (an extra standby tick beats split-brain).
            try:
                acquired = self.lease.try_acquire(self.identity, duration)
            except Exception:  # noqa: BLE001
                log.warning("election round raised; abdicating", exc_info=True)
                acquired = False
            if acquired != self._is_leader:
                self._is_leader = acquired
                self._set_leader_gauge()
                log.info(
                    "leadership %s (%s)",
                    "acquired" if acquired else "lost",
                    self.identity,
                )
            self._stop.wait(duration / 3.0)
        self.lease.release(self.identity)

    # -------------------------------------------------------- shard claims
    def _shard_loop(self) -> None:
        """The sharded replacement for _elect_loop: one coordinator tick
        per election period. Leadership becomes per-shard; the manager-
        level flag (gauge + worker parking) means "owns >= 1 shard"."""
        duration = self.options.lease_duration
        while not self._stop.is_set():
            try:
                self.coordinator.tick()
            except Exception:  # noqa: BLE001 — a tick must never kill the loop
                log.warning("shard tick raised", exc_info=True)
            owns_any = self.coordinator.owns_any()
            if owns_any != self._is_leader:
                self._is_leader = owns_any
                self._set_leader_gauge()
                log.info(
                    "shard ownership %s (%s: shards %s)",
                    "active" if owns_any else "idle",
                    self.identity, self.coordinator.owned_shards(),
                )
            # Serving shards (draining excluded): a replica mid-rebalance
            # still holds the draining lease but admits no work for it.
            self.metrics.set_gauge(
                "training_operator_owned_shards",
                float(len(self.coordinator.serving_shards())),
            )
            self._stop.wait(duration / 3.0)
        # Clean exit: drain + release every shard (standbys win the next
        # tick) and retire the member lease. All failure-tolerant — a
        # crashing replica must not wedge its own shutdown.
        self.coordinator.shutdown()
        self.metrics.set_gauge("training_operator_owned_shards", 0.0)

    def _on_shard_claimed(self, shard: int, cause: str) -> None:
        """The claim half of the handoff protocol: a shard just became
        ours (fresh claim, expiry-steal, or a cancelled drain reclaiming
        the keys its window dropped). ORDER MATTERS: the scoped watch
        cache primes FIRST, so by the time the resync below enqueues the
        shard's keys, their first syncs read entirely from the warm
        store — zero accounted LIST/GETs even on the sync right after a
        steal (the cold-cache handoff gap). Cost note: one list per
        resource per claimed shard — claims are rare control-plane
        events (boot, failover, rebalance, resize), so the read
        amplification of a multi-shard claim tick is accepted; if
        --shards grows large enough to matter, batch the tick's claims
        into one list."""
        self.metrics.shard_handoff_inc(cause)
        if self.watch_cache is not None:
            self.watch_cache.prime_shard(shard)
        from .core.sharding import resync_shard_jobs

        namespace = self.options.namespace or None
        count = 0
        for kind, controller in self.controllers.items():
            count += resync_shard_jobs(
                controller, self.cluster, kind, namespace, shard,
                self.coordinator.shards,
                shard_of=self.coordinator.shard_of,
            )
        self.metrics.set_owned_jobs(str(shard), count)

    def _on_shard_released(self, shard: int, cause: str) -> None:
        self.metrics.shard_handoff_inc(cause)
        # Tear down the released shard's slice of the scoped watch cache
        # and every controller's per-key in-memory state: a 10k-job
        # fleet under rebalance churn must not leave each replica
        # holding the union of everything it EVER owned.
        if self.watch_cache is not None:
            self.watch_cache.drop_shard(shard)
        for controller in self.controllers.values():
            forget = getattr(controller, "forget_shard", None)
            if forget is not None:
                forget(shard, self.coordinator.shard_of)
        # Drop the released shard's job-count series: a stale gauge here
        # would read as a double owner beside the new holder's.
        self.metrics.clear_owned_jobs(str(shard))

    def _shard_drained(self, shard: int) -> bool:
        """True when no worker is inside a sync of the shard's jobs —
        the release precondition of a graceful handoff (releasing
        mid-sync would let the next owner reconcile beside us)."""
        shard_of = self.coordinator.shard_of
        for controller in self.controllers.values():
            for item in controller.queue.processing_items():
                ns, _, name = item.partition(":")[2].partition("/")
                if shard_of(ns, name) == shard:
                    return False
        return True

    # ------------------------------------------------------- live resize
    def request_resize(self, shards: int) -> int:
        """Publish a live shard-count change (the config-lease protocol,
        core/sharding.py): every replica drains and releases its old-ring
        shards (in-flight syncs finish first — the PR 8 drain-before-
        release protocol), adopts the new ring, waits for every live
        member to adopt, then claims its new targets. No redeploy, no
        cold start beyond the per-shard claim resync. Returns the
        published ring epoch."""
        if self.coordinator is None:
            raise RuntimeError(
                "live resize requires a sharded control plane "
                "(--shards > 1); a single-replica operator has no ring "
                "to migrate"
            )
        shards = int(shards)
        from .core.sharding import read_ring_config

        # Idempotence also for the never-resized fleet: publishing the
        # boot ring size as "epoch 1" would drain-and-reclaim every
        # shard for zero change (publish_ring_resize can only dedupe
        # against an EXISTING config lease).
        if (read_ring_config(self.cluster, self.coordinator.namespace,
                             self.options.lease_name) is None
                and shards == self.coordinator.shards):
            log.info("resize to %d is the current ring; nothing published",
                     shards)
            return 0
        epoch = self.coordinator.request_resize(shards)
        log.info("published ring resize: shards=%d epoch=%d", shards, epoch)
        return epoch

    def _handle_sighup(self, signum=None, frame=None) -> None:
        """SIGHUP = re-read --shards-file and publish the resize. Runs
        the read + publish on a one-shot thread: a signal handler must
        not issue blocking apiserver writes on the main thread."""
        path = self.options.shards_file
        if not path:
            log.warning(
                "SIGHUP received but no --shards-file configured; "
                "use /debugz/resize?shards=N instead")
            return

        def reload_and_publish():
            try:
                with open(path) as f:
                    shards = int(f.read().strip())
                self.request_resize(shards)
            except Exception:  # noqa: BLE001 — a bad file must not kill us
                log.warning("SIGHUP resize reload failed", exc_info=True)

        threading.Thread(target=reload_and_publish, daemon=True).start()

    def _sync_gate(self, item: str) -> bool:
        """The post-pop sync gate, per item: global leadership when
        unsharded; the item's SHARD ownership (owned and not draining)
        when sharded — the PR 5 checked-then-blocked rule generalized
        from one flag to one flag per key."""
        if self.coordinator is None:
            return self._is_leader
        ns, _, name = item.partition(":")[2].partition("/")
        return self.coordinator.allows(ns, name)

    def _autoscaler_loop(self) -> None:
        """The autoscaler's control loop: one tick per interval, gated on
        leadership exactly like the sync workers (a standby replica's
        autoscaler observing a fleet it doesn't reconcile must not
        resize it). A tick that raises is logged and the loop survives —
        the next tick re-observes from scratch; the decision function's
        idempotence (a function of the CURRENT spec) makes the retry
        safe."""
        while not self._stop.is_set():
            if self._is_leader:
                try:
                    self.autoscaler.tick()
                except Exception:  # noqa: BLE001 — the loop must survive
                    log.warning("autoscaler tick raised", exc_info=True)
            self._stop.wait(self.options.autoscaler_interval)

    def _worker_loop(self, kind: str) -> None:
        controller = self.controllers[kind]
        # The gate re-checks authority AFTER the blocking queue pop: a
        # worker parked in get() across a leadership flip (or a shard
        # handoff) must hand its item back, not sync it (see
        # process_next). Each of the N pool workers carries the same gate
        # — quiescing is per-worker, not per-pool.
        while not self._stop.is_set():
            if not self._is_leader:
                self._stop.wait(0.05)
                continue
            controller.process_next(timeout=0.1, gate=self._sync_gate)

    def _resync_loop(self) -> None:
        """Periodic full relist: re-enqueue every job of every enabled kind
        (reference resync period, options.go:24). Also the safety net for
        dropped watch events. Periodic rounds spread their enqueues with
        deterministic per-key jitter: every live job landing in the queue
        at the same instant each period created a queue-depth/token-bucket
        spike exactly `resync_period` apart — a herd the worker pool then
        burned down in a burst instead of a steady trickle."""
        window = min(self.options.resync_period * 0.5, RESYNC_JITTER_CAP)
        while not self._stop.is_set():
            self._stop.wait(self.options.resync_period)
            if self._stop.is_set():
                return
            self.resync_once(jitter_window=window)

    def resync_once(self, jitter_window: float = 0.0) -> None:
        """Relist-and-enqueue every job. jitter_window=0 (the default, and
        the cold-start call in start()) enqueues immediately; periodic
        rounds pass a window and each key is delayed by its deterministic
        hash fraction of it (clock-injected through the WorkQueue — no
        `random`, so a seeded harness replays the identical schedule)."""
        namespace = self.options.namespace or None
        owned_counts: Dict[int, int] = {}
        for kind, controller in self.controllers.items():
            for job in self.cluster.list_jobs(kind, namespace):
                meta = job.get("metadata", {})
                ns = meta.get("namespace", "default")
                name = meta.get("name", "")
                if self.coordinator is not None:
                    shard = self.coordinator.shard_of(ns, name)
                    if self.coordinator.owns(shard):
                        owned_counts[shard] = owned_counts.get(shard, 0) + 1
                controller._enqueue_after(
                    ns, name,
                    resync_jitter_seconds(f"{kind}:{ns}/{name}", jitter_window),
                )
        if self.coordinator is not None:
            # Refresh the per-shard job-count gauges off the relist we
            # just paid for (claims set them too; churn between resyncs
            # is tolerated staleness).
            for shard in self.coordinator.owned_shards():
                self.metrics.set_owned_jobs(
                    str(shard), owned_counts.get(shard, 0)
                )

    # --------------------------------------------------------- http server
    def _serve(self, handler_cls, port: int) -> Optional[ThreadingHTTPServer]:
        if port < 0:
            return None
        handler = type("Handler", (handler_cls,), {"manager": self})
        server = ThreadingHTTPServer((self.options.bind_address, port), handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        self._threads.append(thread)
        return server

    def _start_http_servers(self) -> None:
        # 0 disables a server; port 0 is "disabled" rather than "ephemeral"
        # to match the reference's bind-address flags.
        if self.options.health_port > 0:
            self._server = self._serve(_HealthHandler, self.options.health_port)
        if self.options.metrics_port > 0:
            self._metrics_server = self._serve(_MetricsHandler, self.options.metrics_port)

    @property
    def health_address(self) -> Optional[str]:
        if self._server is None:
            return None
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def metrics_address(self) -> Optional[str]:
        if self._metrics_server is None:
            return None
        host, port = self._metrics_server.server_address[:2]
        return f"http://{host}:{port}"

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._started:
            return
        # Support stop() -> start() cycles: a set _stop Event would make
        # every new loop thread exit on its first check.
        self._stop.clear()
        self._threads = []
        if self.coordinator is not None:
            # Sharded mode: the shard claim loop IS the election —
            # running the global elect loop beside it would gate workers
            # on a lock no peer contends per-shard.
            thread = threading.Thread(target=self._shard_loop, daemon=True)
            thread.start()
            self._threads.append(thread)
        elif self.options.leader_elect:
            thread = threading.Thread(target=self._elect_loop, daemon=True)
            thread.start()
            self._threads.append(thread)
        for kind in self.controllers:
            for i in range(self.sync_workers[kind]):
                thread = threading.Thread(
                    target=self._worker_loop, args=(kind,), daemon=True,
                    name=f"sync-{kind}-{i}",
                )
                thread.start()
                self._threads.append(thread)
        thread = threading.Thread(target=self._resync_loop, daemon=True)
        thread.start()
        self._threads.append(thread)
        if self.autoscaler is not None:
            thread = threading.Thread(
                target=self._autoscaler_loop, name="gang-autoscaler",
                daemon=True,
            )
            thread.start()
            self._threads.append(thread)
        self._start_http_servers()
        self.resync_once()
        self._started = True

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        for server in (self._server, self._metrics_server):
            if server is not None:
                server.shutdown()
                server.server_close()
        for thread in self._threads:
            thread.join(timeout=timeout)
        # After the workers have quiesced: release each controller's
        # fan-out pool (lazily rebuilt on a start() cycle) so repeated
        # manager lifecycles — the scale benchmark builds one per
        # measurement — don't accumulate idle thread pools.
        for controller in self.controllers.values():
            close = getattr(controller, "close", None)
            if close is not None:
                close()
        self._started = False

    def run_forever(self) -> None:
        self.start()
        try:
            import signal

            # Config-reload signal (resize via --shards-file). Only
            # installable from the main thread; embedded managers (tests,
            # benches) simply don't get the signal surface.
            signal.signal(signal.SIGHUP, self._handle_sighup)
        except (ValueError, AttributeError, OSError):
            pass
        try:
            while not self._stop.is_set():
                time.sleep(0.5)
        except KeyboardInterrupt:
            log.info("shutting down")
        finally:
            self.stop()


# -------------------------------------------------------------------- main


def json_log_formatter(tracer=None) -> logging.Formatter:
    """The --log-format json formatter: one JSON object per record,
    stamped with {job, trace_id, span_id} when the EMITTING thread is
    inside a traced reconcile (core/tracing.py current_log_context) —
    `grep trace-000042` then reconstructs one job's interleaved log
    lines from an N-worker pool."""
    if tracer is None:
        from .core.tracing import TRACER as tracer  # noqa: N811

    class JsonFormatter(logging.Formatter):
        def format(self, record):
            entry = {
                "level": record.levelname.lower(),
                "time": self.formatTime(record),
                "logger": record.name,
                "msg": record.getMessage(),
            }
            entry.update(tracer.current_log_context())
            if record.exc_info and record.exc_info[0] is not None:
                entry["exception"] = record.exc_info[0].__name__
            return json.dumps(entry)

    return JsonFormatter()


def _setup_logging(json_format: bool) -> None:
    if json_format:
        handler = logging.StreamHandler()
        handler.setFormatter(json_log_formatter())
        logging.basicConfig(level=logging.INFO, handlers=[handler], force=True)
    else:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(levelname)s %(name)s %(filename)s:%(lineno)d %(message)s",
            force=True,
        )


def main(argv: Optional[List[str]] = None, cluster: Optional[Cluster] = None) -> int:
    args = build_arg_parser().parse_args(argv)
    options = options_from_args(args)
    _setup_logging(options.json_log_format)
    if cluster is None:
        kubeconfig = getattr(args, "kubeconfig", "")
        if (
            not kubeconfig
            and getattr(args, "kube", False)
            and not args.kube_url
            and not args.kube_token
            and not args.kube_insecure
            and "KUBERNETES_SERVICE_HOST" not in os.environ
        ):
            # Out-of-cluster --kube with no explicit URL AND no explicit
            # credential flags: fall back to the ambient kubeconfig before
            # failing, like the reference's clientcmd. Explicit flags mean
            # the user is describing a connection directly — honoring an
            # ambient kubeconfig instead would silently connect somewhere
            # else with other credentials.
            from .cluster.kubeconfig import resolve_kubeconfig_path

            kubeconfig = resolve_kubeconfig_path(None) or ""
        if kubeconfig:
            from .cluster.kube import KubeCluster

            cluster = KubeCluster.from_kubeconfig(
                kubeconfig,
                context=getattr(args, "kube_context", "") or None,
                **({"namespace": options.namespace} if options.namespace else {}),
            )
        elif getattr(args, "kube", False) or args.kube_url:
            from .cluster.kube import KubeCluster

            cluster = KubeCluster(
                base_url=args.kube_url or None,
                token=args.kube_token or None,
                insecure=args.kube_insecure,
                namespace=options.namespace,
            )
        else:
            # Dev default: the in-repo cluster runtime; the real apiserver
            # backend plugs in through the same Cluster interface.
            from .cluster.memory import InMemoryCluster

            cluster = InMemoryCluster()
    manager = OperatorManager(cluster, options)
    log.info(
        "starting operator: kinds=%s namespace=%s gang=%s",
        list(manager.controllers),
        options.namespace or "<all>",
        options.enable_gang_scheduling,
    )
    manager.run_forever()
    return 0
