"""Shared rendezvous-port lookup.

Every framework contract finds its port the same way the reference does
(e.g. getPortFromPyTorchJob pytorch.go:97-110): scan the replica type's
canonical container for the canonically-named port, fall back to the
framework default.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api.common import ReplicaSpec


def get_container_port(
    replica_specs: Dict[str, ReplicaSpec],
    rtype: Optional[str],
    container_name: str,
    port_name: str,
    default: int,
) -> int:
    spec = replica_specs.get(rtype) if rtype is not None else None
    if spec is not None:
        for container in spec.template.spec.containers:
            if container.name == container_name:
                for port in container.ports:
                    if port.name == port_name:
                        return port.container_port
    return default
