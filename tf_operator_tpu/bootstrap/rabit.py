"""XGBoost Rabit / LightGBM env.

Reference parity: pkg/controller.v1/xgboost/xgboost.go (SetPodEnv) — master
rendezvous env on every pod, worker ranks offset by the master count, and
the LightGBM extras (WORKER_PORT/WORKER_ADDRS) for multi-replica jobs.
"""

from __future__ import annotations

from typing import Dict

from ..api import xgboostjob as xgbapi
from ..api.xgboostjob import XGBoostJob
from ..core.job_controller import gen_general_name
from .ports import get_container_port


def get_port(job: XGBoostJob, rtype: str) -> int:
    return get_container_port(
        job.spec.xgb_replica_specs,
        rtype,
        xgbapi.DEFAULT_CONTAINER_NAME,
        xgbapi.DEFAULT_PORT_NAME,
        xgbapi.DEFAULT_PORT,
    )


def total_replicas(job: XGBoostJob) -> int:
    return sum(spec.replicas or 0 for spec in job.spec.xgb_replica_specs.values())


def gen_env(job: XGBoostJob, rtype: str, index: int) -> Dict[str, str]:
    rank = index
    master_spec = job.spec.xgb_replica_specs.get(xgbapi.REPLICA_TYPE_MASTER)
    if rtype.lower() == xgbapi.REPLICA_TYPE_WORKER.lower() and master_spec is not None:
        rank += master_spec.replicas or 0

    total = total_replicas(job)
    env = {
        "MASTER_PORT": str(get_port(job, xgbapi.REPLICA_TYPE_MASTER)),
        "MASTER_ADDR": gen_general_name(job.name, xgbapi.REPLICA_TYPE_MASTER, 0),
        "WORLD_SIZE": str(total),
        "RANK": str(rank),
        "PYTHONUNBUFFERED": "0",
    }
    if total > 1:
        # LightGBM extras: total-1 worker addresses (reference xgboost.go:95-107;
        # the -1 assumes the single validated master).
        env["WORKER_PORT"] = str(get_port(job, xgbapi.REPLICA_TYPE_WORKER))
        env["WORKER_ADDRS"] = ",".join(
            gen_general_name(job.name, xgbapi.REPLICA_TYPE_WORKER, i)
            for i in range(total - 1)
        )
    return env
