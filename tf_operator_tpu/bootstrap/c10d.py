"""PyTorch c10d rendezvous env (MASTER_ADDR/PORT, WORLD_SIZE, RANK).

Reference parity: pkg/controller.v1/pytorch/pytorch.go:27-82 (SetPodEnv) —
including the master-sees-localhost rule and the +1 rank offset for workers.
"""

from __future__ import annotations

from typing import Dict

from ..api import pytorchjob as ptapi
from ..api.pytorchjob import PyTorchJob
from ..core.job_controller import gen_general_name
from .ports import get_container_port


def get_master_port(job: PyTorchJob) -> int:
    return get_container_port(
        job.spec.pytorch_replica_specs,
        ptapi.REPLICA_TYPE_MASTER,
        ptapi.DEFAULT_CONTAINER_NAME,
        ptapi.DEFAULT_PORT_NAME,
        ptapi.DEFAULT_PORT,
    )


def total_replicas(job: PyTorchJob) -> int:
    return sum(spec.replicas or 0 for spec in job.spec.pytorch_replica_specs.values())


def gen_env(job: PyTorchJob, rtype: str, index: int) -> Dict[str, str]:
    """Env for one replica. Master (always index 0) rendezvous on localhost;
    workers get rank index+1 (reference pytorch.go:46-53)."""
    rank = index
    master_addr = gen_general_name(job.name, ptapi.REPLICA_TYPE_MASTER, 0)
    if rtype.lower() == ptapi.REPLICA_TYPE_MASTER.lower():
        if index != 0:
            raise ValueError("invalid config: There should be only a single master with index=0")
        master_addr = "localhost"
    else:
        rank = index + 1
    return {
        "MASTER_PORT": str(get_master_port(job)),
        "MASTER_ADDR": master_addr,
        "WORLD_SIZE": str(total_replicas(job)),
        "RANK": str(rank),
        "PYTHONUNBUFFERED": "0",
    }
