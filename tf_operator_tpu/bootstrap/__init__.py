"""Per-framework communication-bootstrap contracts.

The operator contains no transport; it is a rendezvous-config injector
(SURVEY.md §5.8). Each module here generates the env one framework's
processes need to find each other: `tf_config` (TF_CONFIG JSON), `c10d`
(MASTER_ADDR/RANK/WORLD_SIZE), `dmlc` (MXNet PS-Lite), `rabit`
(XGBoost/LightGBM), and `jaxdist` (jax.distributed coordinator + TPU slice
topology — the TPU-native contract with no reference counterpart).
"""
