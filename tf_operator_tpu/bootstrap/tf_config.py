"""TF_CONFIG generation.

Reference parity: pkg/controller.v1/tensorflow/tensorflow.go (genTFConfigJSONStr,
genClusterSpec, SparseClusterSpec for dynamic workers).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

from ..api import tfjob as tfapi
from ..api.tfjob import TFJob
from ..core.job_controller import gen_general_name
from .ports import get_container_port

# Custom cluster DNS domain, e.g. "cluster.local" (reference tensorflow.go:30-33).
ENV_CUSTOM_CLUSTER_DOMAIN = "CUSTOM_CLUSTER_DOMAIN"


def replica_service_host(job_name: str, namespace: str, rtype: str, index: int) -> str:
    """Stable DNS name of one replica's headless service:
    "<job>-<type>-<i>.<ns>.svc[.<domain>]" (reference tensorflow.go:153-166).
    Built on gen_general_name so the hostnames always match the services the
    engine actually creates."""
    host = gen_general_name(job_name, rtype, index) + f".{namespace}.svc"
    domain = os.environ.get(ENV_CUSTOM_CLUSTER_DOMAIN, "")
    if domain:
        host += f".{domain}"
    return host


def get_port_from_job(job: TFJob, rtype: str) -> int:
    return get_container_port(
        job.spec.tf_replica_specs,
        rtype,
        tfapi.DEFAULT_CONTAINER_NAME,
        tfapi.DEFAULT_PORT_NAME,
        tfapi.DEFAULT_PORT,
    )


def gen_cluster_spec(job: TFJob) -> Dict[str, List[str]]:
    """{"ps": ["host:2222", ...], "worker": [...]} (reference genClusterSpec)."""
    cluster: Dict[str, List[str]] = {}
    for rtype, spec in job.spec.tf_replica_specs.items():
        rt = rtype.lower()
        port = get_port_from_job(job, rtype)
        cluster[rt] = [
            f"{replica_service_host(job.name, job.namespace, rt, i)}:{port}"
            for i in range(spec.replicas or 0)
        ]
    return cluster


def gen_tf_config(job: TFJob, rtype: str, index: int) -> str:
    """The TF_CONFIG JSON for one replica (reference genTFConfigJSONStr).

    With EnableDynamicWorker, emit the sparse form: each worker sees only
    itself + the PS list, so workers can join/leave without restarting the
    world (reference tensorflow.go:62-83,110-119)."""
    cluster = gen_cluster_spec(job)
    rt = rtype.lower()
    if job.spec.enable_dynamic_worker:
        sparse: Dict[str, object] = {"worker": {}, "ps": []}
        if rt == tfapi.REPLICA_TYPE_PS.lower():
            sparse["ps"] = [cluster[rt][index]]
        elif rt == tfapi.REPLICA_TYPE_WORKER.lower():
            sparse["ps"] = cluster.get(tfapi.REPLICA_TYPE_PS.lower(), [])
            sparse["worker"] = {index: cluster[rt][index]}
        return json.dumps(
            {"sparseCluster": sparse, "task": {"type": rt, "index": index}},
            separators=(",", ":"),
        )
    return json.dumps(
        {
            "cluster": cluster,
            "task": {"type": rt, "index": index},
            # "cloud" keeps legacy tf.contrib.learn from defaulting to local
            # (reference tensorflow.go:127-131).
            "environment": "cloud",
        },
        separators=(",", ":"),
    )


def is_distributed(job: TFJob) -> bool:
    """Single-process jobs get no TF_CONFIG (reference pod.go:296-319)."""
    specs = job.spec.tf_replica_specs
    total = sum(spec.replicas or 0 for spec in specs.values())
    return total > 1
