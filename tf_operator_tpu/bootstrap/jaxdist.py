"""JAX/TPU bootstrap env — the TPU-native rendezvous contract.

No reference counterpart: where TF_CONFIG/c10d env wires GPU-era transports,
this contract wires `jax.distributed` + libtpu:

- JAX_COORDINATOR_ADDRESS  worker-0's headless service (host:port) — the
                           jax.distributed coordinator.
- JAX_NUM_PROCESSES        total worker count (all slices).
- JAX_PROCESS_ID           this worker's global index.
- TPU_WORKER_ID            index within its slice (libtpu host ordinal).
- TPU_WORKER_HOSTNAMES     comma list of this slice's worker DNS names
                           (libtpu uses it to form the ICI mesh).
- TPU_ACCELERATOR_TYPE /   published topology so the workload can build
  TPU_TOPOLOGY               meshes without cloud metadata queries.
- JAX_MESH_SPEC            JSON of the declared logical mesh axes.
- MEGASCALE_*              multislice (DCN) coordination: coordinator =
                           slice-0 worker-0, slice id, slice count.

`tf_operator_tpu.runtime.tpu_init` consumes these inside the container.
"""

from __future__ import annotations

import json
from typing import Dict

from ..api import jaxjob as jaxapi
from ..api.jaxjob import JAXJob
from .ports import get_container_port
from .tf_config import replica_service_host

ENV_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"
ENV_MESH_SPEC = "JAX_MESH_SPEC"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
ENV_NUM_SLICES = "JAX_NUM_SLICES"
ENV_SLICE_INDEX = "JAX_SLICE_INDEX"
ENV_MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"


def world_generation(job: JAXJob) -> str:
    """Stable hash of the SPMD world a pod's env encodes: worker count,
    slice count, coordinator port, and mesh. Stamped as a pod label; a pod
    whose label differs from the live spec was bootstrapped into a stale
    world and must be recreated for the membership change to take effect
    (all processes re-run jax.distributed.initialize — resize is a
    coordinated re-init, not an in-place membership edit)."""
    import hashlib

    worker = job.spec.jax_replica_specs.get(jaxapi.REPLICA_TYPE_WORKER)
    total = (worker.replicas or 1) if worker else 1
    tpu = job.spec.tpu
    payload = json.dumps(
        {
            "workers": total,
            "slices": max(1, job.spec.num_slices),
            "port": get_port(job),
            "mesh": job.spec.mesh,
            # tpu fields feed TPU_ACCELERATOR_TYPE/TPU_TOPOLOGY env: a
            # topology patch must also roll the world, or live pods and
            # later-recreated ones would disagree on the libtpu mesh.
            "tpu": (
                [tpu.accelerator_type, tpu.topology, tpu.chips_per_host]
                if tpu is not None
                else None
            ),
        },
        sort_keys=True,
        separators=(",", ":"),
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:10]


def get_port(job: JAXJob) -> int:
    return get_container_port(
        job.spec.jax_replica_specs,
        jaxapi.REPLICA_TYPE_WORKER,
        jaxapi.DEFAULT_CONTAINER_NAME,
        jaxapi.DEFAULT_PORT_NAME,
        jaxapi.DEFAULT_PORT,
    )


def hosts_per_slice(job: JAXJob) -> int:
    worker = job.spec.jax_replica_specs.get(jaxapi.REPLICA_TYPE_WORKER)
    total = (worker.replicas or 1) if worker else 1
    if job.spec.tpu is not None:
        hosts = jaxapi.hosts_for(job.spec.tpu)
        if hosts:
            return hosts
    return max(1, total // max(1, job.spec.num_slices))


def gen_env(job: JAXJob, rtype: str, index: int) -> Dict[str, str]:
    if rtype != jaxapi.REPLICA_TYPE_WORKER:
        # Out-of-world replicas (Evaluator): deliberately NO world vars —
        # runtime/tpu_init.py keys jax.distributed.initialize on
        # JAX_COORDINATOR_ADDRESS presence, and an evaluator joining the
        # SPMD rendezvous would deadlock the worker gang. It gets the
        # published topology (to size its own eval batch) and a role
        # marker; checkpoint discovery is spec-level (the workload's env/
        # volume), not a bootstrap concern.
        env = {"JAXJOB_ROLE": rtype.lower()}
        if job.spec.tpu is not None:
            if job.spec.tpu.accelerator_type:
                env[ENV_TPU_ACCELERATOR_TYPE] = job.spec.tpu.accelerator_type
            if job.spec.tpu.topology:
                env[ENV_TPU_TOPOLOGY] = job.spec.tpu.topology
        return env
    worker = job.spec.jax_replica_specs.get(jaxapi.REPLICA_TYPE_WORKER)
    total = (worker.replicas or 1) if worker else 1
    port = get_port(job)
    per_slice = hosts_per_slice(job)
    slice_index = index // per_slice
    worker_id = index % per_slice

    rt = jaxapi.REPLICA_TYPE_WORKER.lower()
    coordinator = f"{replica_service_host(job.name, job.namespace, rt, 0)}:{port}"
    slice_hosts = [
        replica_service_host(job.name, job.namespace, rt, slice_index * per_slice + i)
        for i in range(per_slice)
    ]

    env = {
        ENV_COORDINATOR_ADDRESS: coordinator,
        ENV_NUM_PROCESSES: str(total),
        ENV_PROCESS_ID: str(index),
        ENV_TPU_WORKER_ID: str(worker_id),
        ENV_TPU_WORKER_HOSTNAMES: ",".join(slice_hosts),
        ENV_NUM_SLICES: str(max(1, job.spec.num_slices)),
        ENV_SLICE_INDEX: str(slice_index),
    }
    if job.spec.tpu is not None:
        if job.spec.tpu.accelerator_type:
            env[ENV_TPU_ACCELERATOR_TYPE] = job.spec.tpu.accelerator_type
        if job.spec.tpu.topology:
            env[ENV_TPU_TOPOLOGY] = job.spec.tpu.topology
    if job.spec.mesh:
        env[ENV_MESH_SPEC] = json.dumps(job.spec.mesh, separators=(",", ":"))
    if job.spec.num_slices > 1:
        # DCN-coordinated multislice: megascale coordinator lives on
        # slice-0 worker-0.
        env[ENV_MEGASCALE_COORDINATOR] = replica_service_host(
            job.name, job.namespace, rt, 0
        )
        env[ENV_MEGASCALE_NUM_SLICES] = str(job.spec.num_slices)
        env[ENV_MEGASCALE_SLICE_ID] = str(slice_index)
    return env
