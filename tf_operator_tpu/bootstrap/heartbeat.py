"""Gang-liveness heartbeat env — the operator→container contract.

No reference counterpart: the reference operator's only liveness signal is
wall-clock ``activeDeadlineSeconds`` (job.go:174-190), which cannot tell a
slow job from a wedged one. When a job opts in (``runPolicy.
progressDeadlineSeconds``), the engine injects these variables into every
replica pod and ``runtime/heartbeat.py`` consumes them inside the
container:

- TPU_HEARTBEAT_LEASE              name of this pod's heartbeat Lease
                                   ("<pod>-hb") — renewed through the same
                                   coordination.k8s.io seam leader election
                                   uses.
- TPU_HEARTBEAT_NAMESPACE          namespace the Lease lives in (the job's).
- TPU_HEARTBEAT_INTERVAL_SECONDS   renewal cadence (progressDeadline /
                                   HEARTBEAT_INTERVAL_FRACTION, min 1s).
- TPU_HEARTBEAT_FILE               file-bridge override: when set (the
                                   process e2e tier; a kubelet-analog
                                   translates file beats into Lease
                                   renewals), the runtime writes beats to
                                   this path instead of an apiserver.

Absent env means no heartbeat thread at all, so the same training script
runs unmodified on a dev box — the degrade-to-local rule every bootstrap
contract in this package follows.
"""

from __future__ import annotations

from typing import Dict

from ..core.constants import HEARTBEAT_INTERVAL_FRACTION, heartbeat_lease_name

ENV_HEARTBEAT_LEASE = "TPU_HEARTBEAT_LEASE"
ENV_HEARTBEAT_NAMESPACE = "TPU_HEARTBEAT_NAMESPACE"
ENV_HEARTBEAT_INTERVAL = "TPU_HEARTBEAT_INTERVAL_SECONDS"
ENV_HEARTBEAT_FILE = "TPU_HEARTBEAT_FILE"
# Fast-recovery plane (EngineOptions.peer_restore; both absent unless the
# operator enables it — the peer path is capability-gated off by default):
# - TPU_SHARD_SERVER=1           the workload should start a
#                                runtime/shard_server.py over its host
#                                snapshot and advertise the address via
#                                record_peer_address().
# - TPU_PEER_RESTORE_ADDRS       comma-joined "host:port" survivor
#                                addresses (read from live pods' heartbeat
#                                leases at pod build time) the restore
#                                ladder tries before the storage fallback.
ENV_SHARD_SERVER = "TPU_SHARD_SERVER"
ENV_PEER_RESTORE_ADDRS = "TPU_PEER_RESTORE_ADDRS"
# Sharded-restore plane (EngineOptions.sharded_restore / warm_start; both
# absent unless the operator enables them):
# - TPU_SHARDED_RESTORE=1        the restore ladder should plan a
#                                scatter-gather across the advertised
#                                survivors (train/restore.py sharded=True)
#                                instead of the single-survivor pull.
# - TPU_WARM_START=1             elastic-grow contract: this rank was
#                                (re)created by an autoscaler grow while
#                                peers survived — restore from live peer
#                                snapshots without any storage read
#                                (train/restore.py warm_start=True).
#                                Injected only on grow-recreated pods and
#                                only while the grow is settling.
ENV_SHARDED_RESTORE = "TPU_SHARDED_RESTORE"
ENV_WARM_START = "TPU_WARM_START"
# Delta-persist plane (EngineOptions.delta_persist; absent unless the
# operator enables it):
# - TPU_DELTA_PERSIST=1          the workload's CheckpointManager should
#                                run delta persists (changed shards + a
#                                step manifest, train/checkpoint.py) and
#                                advertise its have-list on peer restores
#                                (train/restore.py have=True) so persist
#                                and recovery bytes are O(changed shards).
ENV_DELTA_PERSIST = "TPU_DELTA_PERSIST"


def heartbeat_interval_seconds(progress_deadline_seconds: int) -> float:
    return max(1.0, progress_deadline_seconds / HEARTBEAT_INTERVAL_FRACTION)


def gen_env(pod_name: str, namespace: str,
            progress_deadline_seconds: int) -> Dict[str, str]:
    """The heartbeat env block for one replica pod."""
    return {
        ENV_HEARTBEAT_LEASE: heartbeat_lease_name(pod_name),
        ENV_HEARTBEAT_NAMESPACE: namespace,
        ENV_HEARTBEAT_INTERVAL: str(
            heartbeat_interval_seconds(progress_deadline_seconds)
        ),
    }
