"""MXNet DMLC / PS-Lite env (MX_CONFIG + DMLC_*).

Reference parity: pkg/controller.v1/mxnet/mxnet.go (genMXConfig,
SetPodEnv incl. the BytePS DMLC_WORKER_ID extra).
"""

from __future__ import annotations

import json
from typing import Dict, List

from ..api import mxjob as mxapi
from ..api.mxjob import MXJob
from ..core.job_controller import gen_general_name
from .ports import get_container_port


def get_port(job: MXJob, rtype: str) -> int:
    return get_container_port(
        job.spec.mx_replica_specs,
        rtype,
        mxapi.DEFAULT_CONTAINER_NAME,
        mxapi.DEFAULT_PORT_NAME,
        mxapi.DEFAULT_PORT,
    )


def gen_cluster_spec(job: MXJob) -> Dict[str, List[dict]]:
    """{"scheduler": [{"url": ..., "port": ...}], ...} (reference
    genClusterSpec — URLs are bare pod/service names, no namespace suffix)."""
    cluster: Dict[str, List[dict]] = {}
    for rtype, spec in job.spec.mx_replica_specs.items():
        rt = rtype.lower()
        port = get_port(job, rtype)
        cluster[rt] = [
            {"url": gen_general_name(job.name, rt, i), "port": int(port)}
            for i in range(spec.replicas or 0)
        ]
    return cluster


def gen_labels_spec(job: MXJob) -> Dict[str, str]:
    """Per-type tuner-server-key annotations for TVM auto-tuning topologies
    (reference genLabelsSpec)."""
    return {
        rtype.lower(): spec.template.metadata.annotations.get(mxapi.TUNER_SERVER_KEY, "")
        for rtype, spec in job.spec.mx_replica_specs.items()
    }


def gen_env(job: MXJob, rtype: str, index: int) -> Dict[str, str]:
    cluster = gen_cluster_spec(job)
    rt = rtype.lower()
    mx_config = {
        "cluster": cluster,
        "labels": gen_labels_spec(job),
        "task": {"type": rt, "index": int(index)},
    }
    scheduler = (cluster.get(mxapi.REPLICA_TYPE_SCHEDULER.lower()) or [{"url": "", "port": 0}])[0]
    env = {
        "MX_CONFIG": json.dumps(mx_config, separators=(",", ":")),
        "DMLC_PS_ROOT_PORT": str(scheduler["port"]),
        "DMLC_PS_ROOT_URI": scheduler["url"],
        "DMLC_NUM_SERVER": str(len(cluster.get(mxapi.REPLICA_TYPE_SERVER.lower(), []))),
        "DMLC_NUM_WORKER": str(len(cluster.get(mxapi.REPLICA_TYPE_WORKER.lower(), []))),
        "DMLC_ROLE": rt,
        "DMLC_USE_KUBERNETES": "1",
    }
    # BytePS wants a per-worker id (reference addBytePSEnv).
    if rt == mxapi.REPLICA_TYPE_WORKER.lower():
        env["DMLC_WORKER_ID"] = str(index)
    return env
