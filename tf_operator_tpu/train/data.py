"""Data pipelines for the benchmark/example workloads.

Synthetic token streams (deterministic, seeded) so benchmarks measure the
training path, not disk IO. Batches are produced host-side as numpy and
device_put onto the data sharding — the one host->device transfer per step.
"""

from __future__ import annotations

import numpy as np


class SyntheticTokens:
    """Infinite deterministic stream of token batches [batch, seq+1]
    (train_step splits input/target internally)."""

    def __init__(self, batch: int, seq: int, vocab_size: int, seed: int = 0):
        self.batch = batch
        self.seq = seq
        self.vocab_size = vocab_size
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        return self._rng.integers(
            0, self.vocab_size, size=(self.batch, self.seq + 1), dtype=np.int32
        )


def shard_batch(batch, sharding):
    """Place one host batch onto its data sharding.

    Single process: a plain transfer. Multi-process: `batch` is this
    process's LOCAL shard and JAX assembles the global array — no host ever
    gathers the global batch (the SPMD input path, scaling-book style).
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.make_array_from_process_local_data(sharding, batch)
