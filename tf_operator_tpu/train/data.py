"""Data pipelines for the benchmark/example workloads.

Synthetic token streams (deterministic, seeded) so benchmarks measure the
training path, not disk IO. Batches are produced host-side as numpy and
device_put onto the data sharding — the one host->device transfer per step.
"""

from __future__ import annotations

import numpy as np


class SyntheticTokens:
    """Infinite deterministic stream of token batches [batch, seq+1]
    (train_step splits input/target internally)."""

    def __init__(self, batch: int, seq: int, vocab_size: int, seed: int = 0):
        self.batch = batch
        self.seq = seq
        self.vocab_size = vocab_size
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        return self._rng.integers(
            0, self.vocab_size, size=(self.batch, self.seq + 1), dtype=np.int32
        )
