"""Data pipelines for the benchmark/example workloads.

Synthetic token streams (deterministic, seeded) so benchmarks measure the
training path, not disk IO. Batches are produced host-side as numpy and
device_put onto the data sharding; `DevicePrefetch` issues that transfer a
step AHEAD of the consumer so the one host->device copy per step overlaps
the running device step instead of sitting on the critical path.
"""

from __future__ import annotations

import collections
import ctypes

import numpy as np


class SyntheticTokens:
    """Infinite deterministic stream of token batches [batch, seq+1]
    (train_step splits input/target internally)."""

    def __init__(self, batch: int, seq: int, vocab_size: int, seed: int = 0):
        self.batch = batch
        self.seq = seq
        self.vocab_size = vocab_size
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        return self._rng.integers(
            0, self.vocab_size, size=(self.batch, self.seq + 1), dtype=np.int32
        )


class TokenFileDataset:
    """Batches [batch, seq+1] from a raw token shard file ("tokens v1":
    headerless little-endian int32 or uint16 ids).

    Backed by the native C++ loader (native/dataloader.cc — mmap +
    background prefetch ring, so batch assembly overlaps the device step)
    with a numpy-mmap fallback when the toolchain is unavailable. Both
    paths produce IDENTICAL batches: window w of this process is
    w_global = w * num_processes + process_id, starting at
    (w_global * 1000003) mod (n_tokens - seq - 1).
    """

    _STRIDE = 1000003  # keep in sync with kStride in dataloader.cc

    def __init__(
        self,
        path: str,
        batch: int,
        seq: int,
        dtype="int32",
        process_id: int = 0,
        num_processes: int = 1,
        prefetch_depth: int = 4,
        skip_windows: int = 0,
        force_python: bool = False,
    ):
        self.path = path
        self.batch = batch
        self.seq = seq
        self.dtype = np.dtype(dtype)
        if self.dtype not in (np.dtype("int32"), np.dtype("uint16")):
            raise ValueError(f"token dtype must be uint16 or int32, got {dtype}")
        self.process_id = process_id
        self.num_processes = num_processes
        # Checkpoint resume: windows this process already consumed
        # (steps_done * local_batch) — both backends skip them.
        self._window = skip_windows
        self._handle = None
        self._lib = None
        self._mm = None

        if not force_python:
            from ..native import load_library

            lib = load_library("dataloader")
            if lib is not None:
                lib.tl_open.restype = ctypes.c_void_p
                lib.tl_open.argtypes = [
                    ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                    ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
                    ctypes.c_int64, ctypes.c_int64,
                ]
                lib.tl_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
                lib.tl_next.restype = ctypes.c_int
                lib.tl_token_count.argtypes = [ctypes.c_void_p]
                lib.tl_token_count.restype = ctypes.c_int64
                lib.tl_close.argtypes = [ctypes.c_void_p]
                handle = lib.tl_open(
                    path.encode(), batch, seq, self.dtype.itemsize,
                    process_id, num_processes, prefetch_depth, skip_windows,
                )
                if handle:
                    self._lib, self._handle = lib, handle
        if self._handle is None:
            self._mm = np.memmap(path, dtype=self.dtype, mode="r")
            if len(self._mm) <= seq + 1:
                raise ValueError(
                    f"{path}: {len(self._mm)} tokens < one window ({seq + 1})"
                )

    @property
    def native(self) -> bool:
        return self._handle is not None

    @property
    def n_tokens(self) -> int:
        if self.native:
            return int(self._lib.tl_token_count(self._handle))
        return len(self._mm)

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        out = np.empty((self.batch, self.seq + 1), dtype=np.int32)
        if self.native:
            rc = self._lib.tl_next(
                self._handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            )
            if rc != 0:
                raise StopIteration
            return out
        usable = len(self._mm) - (self.seq + 1)
        if usable % self._STRIDE == 0:
            # Degenerate stride cycle: (w*STRIDE) mod usable would visit
            # only usable/STRIDE offsets. Mirrored in dataloader.cc.
            usable -= 1
        for b in range(self.batch):
            w = self._window * self.num_processes + self.process_id
            self._window += 1
            start = (w * self._STRIDE) % usable
            out[b] = self._mm[start : start + self.seq + 1].astype(np.int32)
        return out

    def close(self) -> None:
        if self._handle is not None:
            self._lib.tl_close(self._handle)
            self._handle = None

    def __del__(self):  # best-effort: stop the producer thread
        try:
            self.close()
        except Exception:
            pass


def write_token_file(path: str, tokens: np.ndarray) -> None:
    """Write a "tokens v1" shard (headerless raw ids, native byte order)."""
    arr = np.asarray(tokens)
    if arr.dtype not in (np.dtype("int32"), np.dtype("uint16")):
        raise ValueError(f"token dtype must be uint16 or int32, got {arr.dtype}")
    arr.tofile(path)


class DevicePrefetch:
    """Device-side double-buffered prefetch: the second stage of the input
    pipeline, after the host-side ring (native dataloader / SyntheticTokens).

    Wraps a host batch iterator and keeps up to ``depth`` batches already
    transferred onto ``sharding``. ``jax.device_put`` (and the multi-process
    ``make_array_from_process_local_data``) only ENQUEUES the copy — so by
    issuing batch k+1's transfer before batch k is consumed by the step, the
    host->device hop (a network round trip on remote-relay PJRT backends)
    runs concurrently with step k's compute. depth=2 is classic double
    buffering: one batch feeding the step, one in flight.

    Consumption accounting (checkpoint/restart contract): one host batch is
    drawn per yielded batch PLUS the ``in_flight`` batches buffered ahead.
    A resume must therefore derive its skip from STEPS TRAINED
    (``skip_windows = start_step * local_batch`` — what llama_train passes
    to TokenFileDataset), never from how many batches the host iterator
    produced: the in-flight batches of a killed process were never trained
    on and are simply re-produced by the resumed one. Double-consumption is
    structurally impossible because the window index is a pure function of
    the step count, not of this buffer.

    Donation safety: every yielded array is a DISTINCT device buffer (one
    transfer per host batch, nothing reused), so a train step donating its
    batch argument (``make_train_step_for(donate_batch=True)``) can never
    alias a batch still in flight; the step consuming batch k donates k's
    buffer while k+1 already owns its own.
    """

    def __init__(self, host_iter, sharding=None, depth: int = 2, place=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if place is None:
            if sharding is None:
                raise ValueError("DevicePrefetch needs a sharding or a place fn")

            def place(batch, _sharding=sharding):
                return shard_batch(batch, _sharding)

        self._place = place
        self._it = iter(host_iter)
        self.depth = depth
        self._buf = collections.deque()
        self._exhausted = False

    @property
    def in_flight(self) -> int:
        """Batches transferred but not yet yielded (resume accounting)."""
        return len(self._buf)

    def __iter__(self):
        return self

    def _fill(self) -> None:
        while not self._exhausted and len(self._buf) < self.depth:
            try:
                batch = next(self._it)
            except StopIteration:
                self._exhausted = True
                return
            self._buf.append(self._place(batch))

    def __next__(self):
        self._fill()
        if not self._buf:
            raise StopIteration
        out = self._buf.popleft()
        # Issue the replacement transfer NOW — before the caller dispatches
        # the step on `out` — so the copy overlaps that step end to end.
        self._fill()
        return out


def shard_batch(batch, sharding):
    """Place one host batch onto its data sharding.

    Single process: a plain transfer. Multi-process: `batch` is this
    process's LOCAL shard and JAX assembles the global array — no host ever
    gathers the global batch (the SPMD input path, scaling-book style).
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.make_array_from_process_local_data(sharding, batch)
