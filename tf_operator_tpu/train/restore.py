"""Restore ladder: peer-to-peer shard fetch with degraded storage fallback.

A recreated slice or a grown gang restores from, in order of preference:

1. **peer** — a survivor rank's host-resident snapshot, fetched over the
   runtime/shard_server.py wire (discovered via the heartbeat-lease
   peer-address rider, injected by the operator as
   ``TPU_PEER_RESTORE_ADDRS``). Skips the storage round-trip entirely.
2. **storage** — the orbax checkpoint directory
   (``CheckpointManager.restore_latest``), whenever the peer path degrades.
3. **none** — fresh state (first boot: no peers AND no checkpoint).

With ``sharded=True`` the peer rung becomes a **scatter-gather**: every
peer's ``/v1/manifest`` is probed (which shard names does THIS survivor
own — the slice-scoped partition the shard server derives), each shard is
planned onto the least-loaded claiming owner (ties to the lowest
discovery index — the plan is a pure function of the manifests in
discovery order plus the sorted shard names, so seeded runs replay
byte-identically), transfers run in parallel across survivors, and a peer
dying mid-transfer re-plans its unfetched shards against the remaining
survivor set. The storage ladder then degrades **per shard**: shards with
no surviving source are filled from storage — but only when storage holds
exactly the plan step (a mixed-step fill would be torn state, the same
silent corruption the shard server's 409-on-rotation refuses). A peer
that predates the manifest endpoint (404) is treated as a full owner and
served over the per-shard wire, so mixed-version fleets converge. The
sharded happy path reports ``path="peer-sharded"``.

With ``warm_start=True`` (the elastic-grow contract, ``TPU_WARM_START``):
the restoring rank is a recreated/new member of a gang whose survivors
hold live host snapshots at least as fresh as anything durable — so the
happy path never touches storage at all (no ``latest_step()`` probe, no
orbax read; the staleness arbitration is skipped). Peers all failing
still degrades to storage with the cause named: warm start is an
optimization contract, never a correctness gate.

With ``have=True`` (the delta-transfer contract): the restoring rank
hashes its CURRENT in-memory tree into a have-list ``{shard: checksum}``
and advertises it — the scatter-gather planner prunes every shard whose
checksum matches the winning peer's meta (those leaves are already
byte-identical locally and are taken from the local tree, attributed to
source ``"local"``), and the single-peer bundle wire passes the list as
``/v1/bundle?have=`` so the server filters frames it would otherwise
ship. Older peers ignore the parameter and serve the full bundle; the
client keeps the frames it needs and discards the rest, so a
mixed-version fleet loses only the byte savings, never correctness.
``RestoreOutcome.bytes_moved`` counts the payload bytes that actually
crossed the wire on the peer path (the
``training_restore_bytes_total{source}`` feed, and the 4th field of the
restore heartbeat rider).

The STORAGE rung understands delta-checkpoint layouts transparently
(train/checkpoint.py delta persists): ``restore_latest`` resolves the
newest manifest, and a broken chain degrades the whole tree to the
newest full step with the named cause — ``delta-chain-broken`` /
``delta-checksum-mismatch`` — surfaced on the outcome here.

Degradations and their recorded causes (metrics label + fault log):

- ``no-peers``           — no addresses advertised (peer path not enabled,
                           or every survivor died with the slice)
- ``peer-unreachable``   — connect refused / per-peer timeout after
                           retry-with-backoff on every peer
- ``partial-snapshot``   — a peer answered but holds no servable snapshot
                           (multi-host sharded state, or pre-first-save)
- ``stale-snapshot``     — the best peer's step is strictly older than
                           storage's newest checkpoint; storage wins
- ``checksum-mismatch``  — a shard failed sha256 verification (truncated
                           or corrupted in flight) and retries didn't heal
- ``storage-shard-fill`` — scatter-gather completed, but some shards lost
                           every surviving owner and were filled from
                           same-step storage (path stays "peer-sharded";
                           the fill is the per-shard degraded rung)
- ``shard-fill-step-mismatch`` — shards needed a storage fill but storage
                           does not hold the plan step; the whole tree
                           degrades to storage (torn-state refusal)

One failure is NOT a degradation: a ``model_meta`` geometry mismatch on
the peer path hard-fails (:class:`GeometryMismatch`). A peer serving a
differently-grouped attention layout is a config error — silently falling
back to storage would mask it and let a mixed-geometry gang train (the
exact hazard the sidecar check guards on the storage path).

Everything network-shaped goes through the ``fetcher`` seam so chaos tests
and the seeded :class:`~tf_operator_tpu.cluster.chaos.RestoreFaultInjector`
can fault the path deterministically without sockets.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)


class GeometryMismatch(ValueError):
    """Peer snapshot was trained under a different model geometry — a
    config error, never recoverable by falling back (see module doc)."""


@dataclass
class RestoreOutcome:
    """What the ladder decided, for metrics + the restore heartbeat rider."""

    state: Any
    step: Optional[int]
    path: str          # "peer" | "peer-sharded" | "storage" | "none"
    cause: str         # "ok" on the happy paths, degradation cause otherwise
    seconds: float
    peer: Optional[str] = None  # winning peer address, peer path only
    # Scatter-gather attribution: shard counts per source ("<host:port>",
    # "storage" for per-shard fills, "local" for have-list matches taken
    # from the restoring rank's own tree). None outside the sharded path.
    sources: Optional[Dict[str, int]] = None
    # Payload bytes that crossed the wire on the peer path (have-list
    # pruning makes this the number worth watching). None when unknown
    # (storage/none paths).
    bytes_moved: Optional[int] = None


def have_list(tree) -> Dict[str, str]:
    """``{shard name: sha256 of its encoded payload}`` of a local tree —
    the have-list a restoring rank advertises. Uses the identical
    encode-then-hash the shard server uses, so a match PROVES the local
    bytes equal the peer's."""
    from ..runtime.shard_server import (
        encode_shard,
        flatten_tree,
        shard_checksum,
    )

    return {
        name: shard_checksum(encode_shard(leaf))
        for name, leaf in flatten_tree(tree).items()
    }


# ---------------------------------------------------------------- transport
def http_fetch(peer: str, path: str, timeout: float) -> Tuple[int, Dict[str, str], bytes]:
    """Default fetcher: one GET against ``http://<peer><path>``. Returns
    (status, headers, body); raises OSError/TimeoutError on transport
    failure — exactly what the retry loop classifies."""
    import urllib.error
    import urllib.request

    url = f"http://{peer}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as err:  # non-2xx still has a status
        return err.code, dict(err.headers or {}), err.read() or b""


def _fetch_with_retry(fetcher, peer: str, peer_index: int, path: str, *,
                      op: str, timeout: float, retries: int, backoff: float,
                      fault_injector=None, sleep=time.sleep):
    """Retry-with-backoff around one logical fetch. Seeded faults are
    consulted per attempt, so an ``at_call``-windowed fault can refuse the
    first attempt and let the retry through (transient-fault shape) or
    out-live the retry budget (hard-fault shape)."""
    last_err: Optional[BaseException] = None
    for attempt in range(retries + 1):
        if fault_injector is not None:
            kind = fault_injector.fault_for(op, peer_index)
            if kind == "refuse":
                last_err = ConnectionRefusedError("injected: connection refused")
                sleep(backoff * (2 ** attempt))
                continue
            if kind == "hang":
                # A hang IS a timeout from the client's point of view: the
                # injector records it and the ladder sees the same
                # TimeoutError a dead-but-accepting peer would produce
                # (no real sleep — tests stay fast and deterministic).
                last_err = TimeoutError("injected: peer hang (timeout)")
                sleep(backoff * (2 ** attempt))
                continue
            if kind == "die-mid-transfer":
                # The peer process died partway through this transfer:
                # the connection drops NOW and every later consult for
                # this peer refuses (the injector remembers the death).
                # No retry loop — retrying a dead peer burns budget the
                # re-planner should spend on survivors.
                raise ConnectionResetError(
                    "injected: peer died mid-transfer")
        try:
            status, headers, body = fetcher(peer, path, timeout)
        except (OSError, TimeoutError) as err:
            last_err = err
            sleep(backoff * (2 ** attempt))
            continue
        if fault_injector is not None and op == "shard":
            kind = fault_injector.fault_for("shard-body", peer_index)
            if kind == "truncate" and body:
                body = body[: max(0, len(body) // 2)]
        return status, headers, body
    raise last_err if last_err is not None else OSError("fetch failed")


# ------------------------------------------------------------------ ladder
def _assemble(abstract, shards: Dict[str, Any]):
    """Reassemble a restored state: every abstract leaf takes its
    same-named fetched array, placed onto the leaf's target sharding."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(abstract)
    leaves = []
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        if name not in shards:
            raise KeyError(name)
        value = shards[name]
        if tuple(value.shape) != tuple(leaf.shape):
            raise GeometryMismatch(
                f"peer shard {name} has shape {tuple(value.shape)} but the "
                f"local state expects {tuple(leaf.shape)} — refusing a "
                "mixed-geometry restore"
            )
        value = value.astype(leaf.dtype)
        sharding = getattr(leaf, "sharding", None)
        leaves.append(
            jax.device_put(value, sharding) if sharding is not None
            else jax.numpy.asarray(value)
        )
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _restore_from_peer(state, ckpt, peer: str, peer_index: int, meta: dict, *,
                       fetcher, timeout: float, retries: int, backoff: float,
                       fault_injector, sleep,
                       have: Optional[Dict[str, str]] = None,
                       ) -> Tuple[Any, int]:
    """Fetch + verify + reassemble one peer's snapshot; returns
    (restored state, payload bytes moved). Raises on any failure; the
    caller owns fallback. ``have`` prunes the transfer: shards whose
    local checksum matches the peer meta are taken from the local tree
    (byte-identical by construction) and never requested."""
    from urllib.parse import quote

    from ..runtime.shard_server import decode_shard, shard_checksum

    step = int(meta["step"])
    bytes_moved = 0

    def fetch_one(name: str):
        nonlocal bytes_moved
        expect = meta["shards"][name]["checksum"]
        status, _, body = _fetch_with_retry(
            fetcher, peer, peer_index,
            f"/v1/shard/{quote(name)}?step={step}",
            op="shard", timeout=timeout, retries=retries, backoff=backoff,
            fault_injector=fault_injector, sleep=sleep,
        )
        if status != 200:
            raise OSError(f"peer {peer} returned {status} for shard {name}")
        if shard_checksum(body) != expect:
            raise ChecksumMismatch(
                f"shard {name} from {peer} failed sha256 verification"
            )
        bytes_moved += len(body)
        return decode_shard(body)

    names = sorted(meta["shards"])
    shards: Dict[str, Any] = {}
    needed = names
    if have:
        import numpy as np

        from ..runtime.shard_server import flatten_tree

        local_flat = flatten_tree(state)
        needed = []
        for name in names:
            if have.get(name) == meta["shards"][name]["checksum"] \
                    and name in local_flat:
                # Byte-identical locally (same encode, same sha256):
                # the warm leaf IS the restored value.
                shards[name] = np.asarray(local_flat[name])
            else:
                needed.append(name)
    if fault_injector is not None:
        # Sorted, sequential, per-shard: the seeded fault injector counts
        # calls, and byte-equal replay needs the same request sequence
        # every run.
        for name in needed:
            shards[name] = fetch_one(name)
        return _assemble(ckpt.abstract_state(state), shards), bytes_moved

    # Production path: one bundle request for the whole tree — per-request
    # overhead is what lets the storage path catch up on small states.
    # Every framed payload is still verified against the meta checksum, so
    # integrity semantics match the per-shard wire exactly.
    from ..runtime.shard_server import parse_bundle

    bundle_path = f"/v1/bundle?step={step}"
    if have and len(needed) < len(names):
        # Advertise what we hold; a server that understands the
        # parameter omits the matching frames, an older one ignores it
        # (we use only the needed frames either way).
        matched = [n for n in names if n not in needed]
        bundle_path += "&have=" + ",".join(
            f"{quote(n, safe='')}:{have[n]}" for n in matched)
    status, _, body = _fetch_with_retry(
        fetcher, peer, peer_index, bundle_path,
        op="bundle", timeout=timeout, retries=retries, backoff=backoff,
        fault_injector=fault_injector, sleep=sleep,
    )
    if status == 404:
        # Older peer without the bundle endpoint: per-shard wire.
        for name in needed:
            shards[name] = fetch_one(name)
        return _assemble(ckpt.abstract_state(state), shards), bytes_moved
    if status != 200:
        raise OSError(f"peer {peer} returned {status} for bundle")
    frames = parse_bundle(body)
    for name in needed:
        payload = frames.get(name)
        if payload is None:
            raise OSError(f"peer {peer} bundle missing shard {name}")
        if shard_checksum(payload) != meta["shards"][name]["checksum"]:
            raise ChecksumMismatch(
                f"shard {name} from {peer} failed sha256 verification"
            )
        bytes_moved += len(payload)
        shards[name] = decode_shard(payload)
    return _assemble(ckpt.abstract_state(state), shards), bytes_moved


class ChecksumMismatch(OSError):
    """A fetched shard's bytes don't hash to the advertised checksum."""


class ShardFillStepMismatch(OSError):
    """Shards lost every surviving peer source and storage does not hold
    the plan step — a per-shard fill from a different step would assemble
    torn state, so the whole tree must degrade to storage instead."""


# ---------------------------------------------------------- scatter-gather
def plan_scatter(shard_names: Sequence[str],
                 owners: Dict[int, set]) -> Dict[str, int]:
    """Assign each shard to a peer: among the peers claiming ownership
    (falling back to ALL live peers for orphaned names — ownership is a
    planning hint, every survivor serves every shard), pick the one with
    the fewest shards assigned so far, ties to the lowest discovery
    index. Pure function of (sorted names, owners map) so a seeded run
    plans — and replays — identically."""
    assignments: Dict[str, int] = {}
    load = {index: 0 for index in owners}
    all_indices = sorted(owners)
    for name in sorted(shard_names):
        claiming = [i for i in all_indices if name in owners[i]]
        candidates = claiming or all_indices
        pick = min(candidates, key=lambda i: (load[i], i))
        assignments[name] = pick
        load[pick] += 1
    return assignments


def _fetch_one_shard(fetcher, peer: str, peer_index: int, name: str,
                     step: int, expect: str, *, timeout, retries, backoff,
                     fault_injector, sleep):
    """One shard off one peer, verified. Raises on any failure; the
    scatter-gather loop owns re-planning."""
    from urllib.parse import quote

    from ..runtime.shard_server import decode_shard, shard_checksum

    status, _, body = _fetch_with_retry(
        fetcher, peer, peer_index, f"/v1/shard/{quote(name)}?step={step}",
        op="shard", timeout=timeout, retries=retries, backoff=backoff,
        fault_injector=fault_injector, sleep=sleep,
    )
    if status != 200:
        raise OSError(f"peer {peer} returned {status} for shard {name}")
    if shard_checksum(body) != expect:
        raise ChecksumMismatch(
            f"shard {name} from {peer} failed sha256 verification"
        )
    return decode_shard(body), len(body)


def _storage_shard_fill(state, ckpt, step: int, names: Sequence[str]):
    """The per-shard degraded rung: read ONLY the named shards' values out
    of storage — legal solely when storage holds exactly the plan step
    (module doc: a mixed-step fill is torn state)."""
    import numpy as np

    from ..runtime.shard_server import flatten_tree

    latest = ckpt.latest_step()
    if latest != step:
        raise ShardFillStepMismatch(
            f"storage holds step {latest} but the scatter-gather plan is "
            f"step {step}; refusing a mixed-step shard fill"
        )
    restored, _ = ckpt.restore_latest(state)
    flat = flatten_tree(restored)
    out = {}
    for name in names:
        if name not in flat:
            raise KeyError(name)
        out[name] = np.asarray(flat[name])
    return out


def _restore_sharded(state, ckpt, candidates, step: int, *, fetcher,
                     timeout: float, retries: int, backoff: float,
                     fault_injector, sleep,
                     have: Optional[Dict[str, str]] = None):
    """Scatter-gather restore against every candidate peer at ``step``.

    ``candidates`` is ``[(peer_index, peer, manifest)]`` in discovery
    order. Loops plan -> fetch -> re-plan: any peer failure marks that
    peer dead for the rest of the restore and its unfetched shards are
    re-planned against the survivors; shards that run out of peers are
    filled per-shard from same-step storage. ``have`` prunes the plan
    BEFORE any fetch: shards whose local checksum matches the winning
    manifest come from the local tree (source "local", zero wire bytes).
    Returns ``(assembled_state, sources, bytes_moved)`` where sources
    counts shards per serving address (plus "storage" for fills and
    "local" for have-list matches)."""
    live = {}
    all_names = None
    reference_manifest = None
    for index, peer, manifest in candidates:
        names = sorted(manifest["shards"])
        if all_names is None:
            all_names = names
            reference_manifest = manifest
        owned = manifest.get("owned")
        live[index] = {
            "peer": peer,
            "manifest": manifest,
            # A manifest-less (bundle-era) peer claims everything.
            "owned": set(owned) if owned is not None else set(names),
        }
    shards: Dict[str, Any] = {}
    sources: Dict[str, int] = {}
    bytes_moved = 0
    remaining = list(all_names or ())
    if have and reference_manifest is not None:
        import numpy as np

        from ..runtime.shard_server import flatten_tree

        local_flat = flatten_tree(state)
        pruned = []
        for name in remaining:
            expect = reference_manifest["shards"][name]["checksum"]
            if have.get(name) == expect and name in local_flat:
                shards[name] = np.asarray(local_flat[name])
                sources["local"] = sources.get("local", 0) + 1
            else:
                pruned.append(name)
        remaining = pruned

    def fetch_group(index: int, names: Sequence[str]):
        """Sequentially pull one peer's assigned shards. Returns
        (fetched, unfetched, group_bytes) — a failure abandons the rest
        of the group (the peer is presumed dead; the re-planner owns its
        shards)."""
        entry = live[index]
        fetched: Dict[str, Any] = {}
        unfetched: List[str] = []
        group_bytes = 0
        for pos, name in enumerate(names):
            try:
                fetched[name], nbytes = _fetch_one_shard(
                    fetcher, entry["peer"], index, name, step,
                    entry["manifest"]["shards"][name]["checksum"],
                    timeout=timeout, retries=retries, backoff=backoff,
                    fault_injector=fault_injector, sleep=sleep,
                )
                group_bytes += nbytes
            except (OSError, TimeoutError, ValueError, KeyError) as err:
                log.warning("peer %s lost mid-scatter (%s); re-planning "
                            "%d shard(s)", entry["peer"], err,
                            len(names) - pos)
                unfetched = list(names[pos:])
                break
        return fetched, unfetched, group_bytes

    while remaining:
        if not live:
            fill = _storage_shard_fill(state, ckpt, step, remaining)
            shards.update(fill)
            sources["storage"] = sources.get("storage", 0) + len(fill)
            break
        plan = plan_scatter(
            remaining, {i: e["owned"] for i, e in live.items()})
        groups: Dict[int, List[str]] = {}
        for name in sorted(plan):
            groups.setdefault(plan[name], []).append(name)
        failed: List[str] = []
        dead: List[int] = []
        if fault_injector is not None or len(groups) <= 1:
            # Deterministic sequential wire: peers in discovery order,
            # each group in sorted shard order — the consult-counter
            # sequence the seeded injector replays byte-identically.
            results = [(i, fetch_group(i, groups[i])) for i in sorted(groups)]
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=len(groups)) as pool:
                futures = [
                    (i, pool.submit(fetch_group, i, groups[i]))
                    for i in sorted(groups)
                ]
                results = [(i, f.result()) for i, f in futures]
        for index, (fetched, unfetched, group_bytes) in results:
            shards.update(fetched)
            bytes_moved += group_bytes
            if fetched:
                peer = live[index]["peer"]
                sources[peer] = sources.get(peer, 0) + len(fetched)
            if unfetched:
                failed.extend(unfetched)
                dead.append(index)
        for index in dead:
            live.pop(index, None)
        remaining = failed
    return _assemble(ckpt.abstract_state(state), shards), sources, bytes_moved


def restore_with_fallback(
    state,
    ckpt,
    peers: Sequence[str] = (),
    *,
    model_meta: Optional[dict] = None,
    timeout: float = 5.0,
    retries: int = 2,
    backoff: float = 0.2,
    fetcher: Callable[[str, str, float], Tuple[int, Dict[str, str], bytes]] = http_fetch,
    fault_injector=None,
    sleep: Callable[[float], None] = time.sleep,
    sharded: bool = False,
    warm_start: bool = False,
    have: bool = False,
) -> RestoreOutcome:
    """Run the restore ladder (module doc) and return the outcome.

    ``peers`` are ``host:port`` strings in discovery order; ``model_meta``
    is the local geometry to validate peer metas against (defaults to the
    checkpoint manager's); ``fetcher``/``fault_injector``/``sleep`` are the
    determinism seams. ``sharded`` turns the peer rung into the
    scatter-gather plan (module doc); ``warm_start`` is the elastic-grow
    contract — skip the storage staleness probe entirely so the happy
    path performs zero storage reads. ``have`` advertises the current
    in-memory tree's per-shard checksums so the peer rung transfers only
    the shards that actually differ (module doc).
    """
    from .checkpoint import geometry_mismatch

    t0 = time.perf_counter()
    if model_meta is None:
        model_meta = getattr(ckpt, "_model_meta", None)
    if fault_injector is not None and \
            hasattr(ckpt, "restore_fault_injector"):
        # Hand the seeded injector to the storage rung too: delta-chain
        # fault kinds (delta-missing-shard / delta-corrupt-shard) fire
        # inside CheckpointManager's manifest resolution.
        ckpt.restore_fault_injector = fault_injector
    have_map: Optional[Dict[str, str]] = have_list(state) if have else None
    # Warm start: don't even ask storage what it has. Survivor snapshots
    # are the freshest state a grown gang can see, and the latest_step()
    # probe is itself a storage read the zero-read contract forbids.
    storage_step = None if warm_start else ckpt.latest_step()

    cause = "no-peers"
    best: Optional[Tuple[int, str, dict]] = None  # (peer_index, peer, meta)
    probed: List[Tuple[int, str, dict]] = []
    import json

    for index, peer in enumerate(peers):
        probe_path = "/v1/manifest" if sharded else "/v1/meta"
        probe_op = "manifest" if sharded else "meta"
        try:
            status, _, body = _fetch_with_retry(
                fetcher, peer, index, probe_path, op=probe_op,
                timeout=timeout, retries=retries, backoff=backoff,
                fault_injector=fault_injector, sleep=sleep,
            )
            if sharded and status == 404:
                # Bundle-era peer that predates /v1/manifest: probe the
                # meta endpoint instead and treat the peer as a full
                # owner (no "owned" key — _restore_sharded's default).
                status, _, body = _fetch_with_retry(
                    fetcher, peer, index, "/v1/meta", op="meta",
                    timeout=timeout, retries=retries, backoff=backoff,
                    fault_injector=fault_injector, sleep=sleep,
                )
        except (OSError, TimeoutError):
            cause = "peer-unreachable"
            log.warning("peer %s unreachable for restore meta", peer)
            continue
        if status == 503:
            cause = "partial-snapshot"
            continue
        if status != 200:
            cause = "peer-unreachable"
            continue
        try:
            meta = json.loads(body)
        except ValueError:
            cause = "peer-unreachable"
            continue
        if fault_injector is not None and sharded:
            kind = fault_injector.fault_for("manifest-body", index)
            if kind == "stale-manifest":
                # The manifest a real straggler would serve: one step
                # behind whatever storage has finalized.
                meta = dict(meta)
                meta["step"] = (storage_step if storage_step is not None
                                else int(meta["step"])) - 1
            elif kind == "partial-owner" and meta.get("owned"):
                # A survivor that lost half its claimed stride (e.g. a
                # mid-resharding manifest): claims only the front half,
                # leaving orphans for the planner's all-peers fallback.
                meta = dict(meta)
                owned = list(meta["owned"])
                meta["owned"] = owned[: (len(owned) + 1) // 2]
        elif fault_injector is not None:
            kind = fault_injector.fault_for("meta-body", index)
            if kind == "stale-meta":
                # The snapshot a real straggler would serve: one step
                # behind whatever storage has finalized.
                meta = dict(meta)
                meta["step"] = (storage_step if storage_step is not None
                                else int(meta["step"])) - 1
        mismatched = geometry_mismatch(meta.get("model_meta"), model_meta)
        if mismatched:
            raise GeometryMismatch(
                "peer checkpoint model geometry mismatch (peer vs local): "
                f"{mismatched} from {peer} — a mixed-geometry gang is a "
                "config error; refusing to fall back silently"
            )
        probed.append((index, peer, meta))
        if best is None or int(meta["step"]) > int(best[2]["step"]):
            best = (index, peer, meta)

    if best is not None and sharded:
        best_step = int(best[2]["step"])
        if storage_step is not None and best_step < storage_step:
            cause = "stale-snapshot"
            log.warning(
                "peer snapshot step %d staler than storage step %d; "
                "falling back to storage", best_step, storage_step,
            )
        else:
            # Every peer serving the winning step joins the scatter plan;
            # stragglers on an older step are excluded (their shards would
            # be a mixed-step reassembly).
            candidates = [
                entry for entry in probed
                if int(entry[2]["step"]) == best_step
            ]
            try:
                restored, sources, moved = _restore_sharded(
                    state, ckpt, candidates, best_step,
                    fetcher=fetcher, timeout=timeout, retries=retries,
                    backoff=backoff, fault_injector=fault_injector,
                    sleep=sleep, have=have_map,
                )
            except GeometryMismatch:
                raise
            except ShardFillStepMismatch as err:
                cause = "shard-fill-step-mismatch"
                log.warning("sharded restore degraded: %s", err)
            except ChecksumMismatch as err:
                cause = "checksum-mismatch"
                log.warning("sharded restore degraded: %s", err)
            except (OSError, TimeoutError, KeyError, ValueError) as err:
                cause = "peer-unreachable"
                log.warning("sharded restore degraded: %s", err)
            else:
                outcome = RestoreOutcome(
                    state=restored, step=best_step, path="peer-sharded",
                    cause=("storage-shard-fill" if "storage" in sources
                           else "ok"),
                    seconds=time.perf_counter() - t0, peer=best[1],
                    sources=sources, bytes_moved=moved,
                )
                _observe(outcome)
                return outcome
    elif best is not None:
        index, peer, meta = best
        peer_step = int(meta["step"])
        if storage_step is not None and peer_step < storage_step:
            cause = "stale-snapshot"
            log.warning(
                "peer snapshot step %d staler than storage step %d; "
                "falling back to storage", peer_step, storage_step,
            )
        else:
            try:
                restored, moved = _restore_from_peer(
                    state, ckpt, peer, index, meta,
                    fetcher=fetcher, timeout=timeout, retries=retries,
                    backoff=backoff, fault_injector=fault_injector,
                    sleep=sleep, have=have_map,
                )
            except GeometryMismatch:
                raise
            except ChecksumMismatch as err:
                cause = "checksum-mismatch"
                log.warning("peer restore degraded: %s", err)
            except (OSError, TimeoutError, KeyError, ValueError) as err:
                cause = "peer-unreachable"
                log.warning("peer restore degraded: %s", err)
            else:
                outcome = RestoreOutcome(
                    state=restored, step=peer_step, path="peer", cause="ok",
                    seconds=time.perf_counter() - t0, peer=peer,
                    bytes_moved=moved,
                )
                _observe(outcome)
                return outcome

    restored, step = ckpt.restore_latest(state)
    # A delta manifest chain that degraded to an older full step names its
    # cause (delta-chain-broken / delta-checksum-mismatch); surface it over
    # the generic peer-rung cause so operators see why storage went stale.
    delta_cause = getattr(ckpt, "last_delta_degradation", None)
    if step is None:
        outcome = RestoreOutcome(
            state=state, step=None, path="none", cause=delta_cause or cause,
            seconds=time.perf_counter() - t0,
        )
    else:
        outcome = RestoreOutcome(
            state=restored, step=step, path="storage",
            cause=delta_cause or (
                "ok" if cause == "no-peers" and not peers else cause),
            seconds=time.perf_counter() - t0,
        )
    _observe(outcome)
    return outcome


def _observe(outcome: RestoreOutcome) -> None:
    try:
        from ..metrics import METRICS

        METRICS.observe_restore(outcome.path, outcome.cause, outcome.seconds)
        if outcome.bytes_moved is not None:
            METRICS.observe_restore_bytes(outcome.path, outcome.bytes_moved)
    except Exception:  # noqa: BLE001 — telemetry never gates a restore
        pass
