"""Sharded training step.

One jitted function containing the whole step — forward, backward, optimizer
— so XLA fuses elementwise work into the matmuls and schedules the FSDP
all-gathers/reduce-scatters (from the sharding annotations) itself. Buffers
are donated: parameters and optimizer state update in place in HBM.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import batch_sharding


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def _masked_nll(logits, targets, ignore_id: int = -1):
    """(negative-log-likelihood sum, valid-token count) in fp32 — the shared
    core of both loss paths; `ignore_id` targets masked out."""
    logits = logits.astype(jnp.float32)
    mask = (targets != ignore_id).astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(log_probs, targets[..., None].clip(0), axis=-1)[..., 0]
    return -(ll * mask).sum(), mask.sum()


def cross_entropy_loss(logits, targets, ignore_id: int = -1):
    """Mean next-token cross entropy in fp32; `ignore_id` targets masked out."""
    nll, count = _masked_nll(logits, targets, ignore_id)
    return nll / jnp.maximum(count, 1.0)


def make_optimizer(
    learning_rate: float = 3e-4,
    warmup_steps: int = 100,
    decay_steps: int = 10_000,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(decay_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def init_train_state(model, rng, optimizer, batch: int = 1, seq: Optional[int] = None) -> TrainState:
    from ..models.llama import init_params

    params = init_params(model, rng, batch=batch, seq=seq)
    opt_state = optimizer.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def init_sharded_train_state(
    model, rng, optimizer, mesh: Mesh, batch: int = 1, seq: Optional[int] = None
):
    """Initialize the TrainState *born sharded*: shapes come from eval_shape,
    shardings from the path rules, and the jitted init materializes each
    parameter directly on its own shard. Nothing ever exists unsharded, so a
    7B state (params + two fp32 Adam moments ≈ 70 GB) initializes on chips
    with 16 GB HBM each. Returns (state, sharding)."""
    from ..models.llama import init_params

    def mk(rng):
        params = init_params(model, rng, batch=batch, seq=seq)
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params, opt_state=optimizer.init(params)
        )

    abstract = jax.eval_shape(mk, rng)
    sharding = state_sharding(abstract, mesh)
    state = jax.jit(mk, out_shardings=sharding)(rng)
    return state, sharding


import os

# Sequence positions per lm-head/loss chunk (env-overridable for tuning
# sweeps; default chosen by measurement on v5e — see BASELINE.md).
CE_CHUNK = int(os.environ.get("TF_OPERATOR_CE_CHUNK", "512"))


def chunked_cross_entropy(hidden, kernel, targets, chunk: int = CE_CHUNK,
                          ignore_id: int = -1, bias=None):
    """Next-token CE where the lm head is applied per sequence chunk under
    `lax.map`: the [b, s, vocab] fp32 logits tensor never exists whole in
    HBM (~3 GB at b=8/s=2k/32k vocab), only [b, chunk, vocab] at a time.
    The backward recomputes each chunk's logits from the (small) hidden —
    one extra head matmul total, bought for gigabytes of peak memory.
    `bias` (fp32 [vocab], optional) supports tied-embedding heads that
    carry one (BERT's MLM head); Llama-family heads pass none."""
    b, s, d = hidden.shape
    pad = (-s) % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)), constant_values=ignore_id)
    n = (s + pad) // chunk
    hc = hidden.reshape(b, n, chunk, d).swapaxes(0, 1)  # [n, b, chunk, d]
    tc = targets.reshape(b, n, chunk).swapaxes(0, 1)

    # jax.checkpoint is LOAD-BEARING: without it, lax.map's VJP saves each
    # chunk's log-softmax residual STACKED across chunks — the full-logits
    # tensor again, silently defeating the chunking. Checkpointed, the
    # backward keeps only the (h, t) chunk inputs and recomputes logits.
    @jax.checkpoint
    def per_chunk(args):
        h, t = args
        logits = h @ kernel
        if bias is not None:
            logits = logits + bias
        return _masked_nll(logits, t, ignore_id)

    sums, counts = jax.lax.map(per_chunk, (hc, tc))
    return sums.sum() / jnp.maximum(counts.sum(), 1.0)


def loss_fn(model, params, tokens):
    """Next-token LM loss: predict tokens[:, 1:] from tokens[:, :-1].
    Any auxiliary terms a model sows into its "losses" collection (MoE
    router load-balancing) are summed in; dense models sow nothing and the
    collection comes back empty.

    Models declaring `supports_return_hidden` (the Llama family) take the
    chunked-CE path; others get the plain full-logits loss. An explicit
    capability flag, not try/except: a model accepting **kwargs would
    swallow return_hidden and hand full logits to the hidden-path matmul."""
    if getattr(model, "supports_return_hidden", False):
        hidden, mutated = model.apply(
            params, tokens[:, :-1], mutable=["losses"], return_hidden=True
        )
        if hasattr(model, "head_kernel_and_bias"):
            # Tied-embedding heads (Bert): the model knows where its head
            # lives and whether it carries a bias.
            kernel, bias = model.head_kernel_and_bias(params)
            kernel = kernel.astype(hidden.dtype)
        else:
            kernel = params["params"]["output"]["kernel"].astype(hidden.dtype)
            bias = None
        loss = chunked_cross_entropy(hidden, kernel, tokens[:, 1:], bias=bias)
    else:
        logits, mutated = model.apply(params, tokens[:, :-1], mutable=["losses"])
        loss = cross_entropy_loss(logits, tokens[:, 1:])
    aux = sum(jnp.sum(leaf) for leaf in jax.tree.leaves(mutated.get("losses", {})))
    return loss + aux


def state_sharding(state: TrainState, mesh: Mesh) -> TrainState:
    """Shardings for the whole TrainState via one path-based map: optimizer
    moments (mu/nu) have the parameter's name in their tree path, so the same
    path rules shard them identically to their parameter; scalars (step,
    counts) replicate."""
    from ..parallel.sharding import spec_for_param

    def leaf_sharding(path, leaf):
        ndim = getattr(leaf, "ndim", 0)
        if ndim == 0:
            return NamedSharding(mesh, P())
        parts = []
        for p in path:
            parts.append(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))))
        return NamedSharding(mesh, spec_for_param(
            "/".join(parts), ndim, mesh, shape=getattr(leaf, "shape", None)
        ))

    return jax.tree_util.tree_map_with_path(leaf_sharding, state)


def make_train_step_for(custom_loss_fn, optimizer, mesh: Mesh, state: TrainState,
                        sharding=None, donate_batch: bool = False):
    """Generic sharded step for ANY loss_fn(params, batch) -> scalar: jit
    over `mesh` with explicit in/out shardings, state donated so params/opt
    buffers update in place. The Llama path and the bench's BERT path both
    ride this.

    ``donate_batch=True`` additionally donates the batch argument so its
    HBM buffer is recycled instead of allocated fresh each step. Opt-in,
    not default: a donated batch array is dead after the step, so the
    caller must never reuse it — safe under the one-transfer-per-batch
    contract of ``data.DevicePrefetch`` (and the plain per-step
    device_put loops), unsafe for callers that step twice on one array."""
    if sharding is None:
        sharding = state_sharding(state, mesh)
    data = batch_sharding(mesh, with_sp=False)  # [batch, seq(+1)]

    def stepper(state, batch):
        # Scope the mesh for trace-time consumers: sharding constraints in
        # MoE dispatch (`constrain`) and the ring-attention shard_map wrap.
        from ..parallel.mesh import use_mesh

        with use_mesh(mesh):
            loss, grads = jax.value_and_grad(custom_loss_fn)(state.params, batch)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return (
                TrainState(step=state.step + 1, params=params, opt_state=opt_state),
                loss,
            )

    step = jax.jit(
        stepper,
        in_shardings=(sharding, data),
        out_shardings=(sharding, NamedSharding(mesh, P())),
        donate_argnums=(0, 1) if donate_batch else (0,),
    )
    return step, sharding


def make_train_step(model, optimizer, mesh: Mesh, state: TrainState, sharding=None,
                    donate_batch: bool = False):
    """jit the model LM step over `mesh` (see make_train_step_for)."""
    return make_train_step_for(
        functools.partial(loss_fn, model), optimizer, mesh, state, sharding,
        donate_batch=donate_batch,
    )


def place_state(state: TrainState, sharding: TrainState) -> TrainState:
    """Device-put the state onto its shardings (host -> sharded HBM)."""
    return jax.tree.map(jax.device_put, state, sharding)
