"""Training loop machinery for the JAX workloads: sharded train step,
optimizer plumbing (optax), synthetic data, checkpointing (orbax)."""

from .train_step import (
    TrainState,
    init_sharded_train_state,
    init_train_state,
    make_train_step,
)

__all__ = [
    "TrainState",
    "init_sharded_train_state",
    "init_train_state",
    "make_train_step",
]
