"""Checkpoint/resume for training workloads (orbax) — snapshot-then-persist.

The reference deliberately keeps checkpointing OUT of the operator
(SURVEY.md §5.4): restart semantics assume the framework resumes from its
own checkpoints, and the operator only contributes restart orchestration
plus stable identities. This module is the workload half of that contract:
sharded checkpoints keyed by step, so a replica recreated by the ExitCode
restart policy resumes exactly where the gang left off.

Save is split into two phases (docs/design/checkpoint_recovery.md):

- **snapshot** — a synchronous device→host copy taken at the step boundary.
  Training resumes the moment it returns; the host copy is also retained
  in memory as the shard source for peer-to-peer restore
  (runtime/shard_server.py).
- **persist** — a background write of that host copy to storage. A step is
  DURABLE only once the persist is finalized (orbax's atomic rename), and
  only then do the durability listeners fire. ``record_checkpoint`` — the
  signal the operator's checkpoint-gated elastic shrink consumes — must be
  registered as a listener, never called after ``save()`` returns: the
  return only proves the snapshot, and publishing a step whose persist is
  still in flight lets the autoscaler shrink against a checkpoint that a
  crash in the persist window erases.

States that are not fully process-addressable (multi-host sharded worlds)
cannot be host-snapshotted by one process; those saves go straight through
orbax's async machinery (training still resumes immediately) and the
durability listeners still fire only after ``wait_until_finished`` — but
there is no host snapshot to serve peers from (``host_snapshot()`` is
None and restores degrade to the storage path).

**Delta persists** (``delta_persist=True``, ``EngineOptions.delta_persist``):
between consecutive durable steps most shards are byte-identical (frozen
embeddings, momentum on untouched layers), yet a plain persist rewrites
all of them. In delta mode the persist worker keeps a content-addressed
shard store under ``<dir>/delta/`` — ``shards/<sha256>.npy`` payload files
plus one ``manifest-<step>.json`` per durable step mapping every shard
name to its checksum — and writes only the payloads whose checksum is new,
so persist bytes are O(changed shards); unchanged shards are carried
forward BY REFERENCE (the manifest names a checksum an earlier persist
already materialized). The chain is bounded: every ``delta_full_every``-th
persist is a FULL persist (rewrites every payload, ``chain_depth`` resets
to 0), and GC after each persist retains the newest ``max_to_keep``
manifests plus the newest full manifest and deletes unreferenced payload
files. The durability contract is unchanged: the manifest is written
tmp-then-rename strictly after every payload it references exists, and
``_mark_durable``/listeners fire only once the manifest rename returns —
a crash anywhere earlier leaves the previous manifest the newest durable
step, never a torn one. Restores resolve the newest manifest and verify
every payload's sha256; a missing referenced payload
(``delta-chain-broken``) or a hash mismatch (``delta-checksum-mismatch``)
degrades the WHOLE tree to the newest verifying full manifest (then to
orbax) with the cause recorded on ``last_delta_degradation`` — a
per-shard mix of steps would be torn state. Multi-host saves (no host
snapshot) fall through to the orbax path unchanged.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

import jax

log = logging.getLogger(__name__)


def geometry_mismatch(saved: Optional[dict], current: Optional[dict]) -> dict:
    """Keys whose recorded and current model geometry disagree — the
    guard against configs with identical flattened kernel shapes but
    different head grouping loading each other's checkpoints and silently
    computing differently-grouped attention (ADVICE r2). Shared by the
    storage sidecar check and the peer-restore meta check."""
    if not saved or not current:
        return {}
    return {
        k: (saved[k], current[k])
        for k in saved.keys() & current.keys()
        if saved[k] != current[k]
    }


@dataclass
class HostSnapshot:
    """One step's host-resident state copy: the peer-restore shard source.
    ``tree`` is the TrainState structure with numpy leaves; treated as
    immutable once published (the shard server may be mid-serve)."""

    step: int
    tree: Any
    model_meta: Optional[dict] = None
    # Monotonic publication stamp (diagnostics only — never compared
    # across hosts).
    taken_at: float = field(default_factory=time.monotonic)


class _DeltaBroken(Exception):
    """A delta manifest could not be fully resolved. ``cause`` is the
    named degradation ("delta-chain-broken" for a missing/unreadable
    referenced payload, "delta-checksum-mismatch" for bytes that no
    longer hash to the manifest's record) — the whole tree degrades,
    never a shard at a time."""

    def __init__(self, cause: str, detail: str) -> None:
        super().__init__(detail)
        self.cause = cause


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager bound to one TrainState
    sharding, so save/restore round-trips preserve the mesh layout —
    plus the snapshot/persist split, the durability barrier, and the
    optional delta-persist store (module doc)."""

    def __init__(
        self,
        directory: str,
        sharding: Any = None,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        model_meta: Optional[dict] = None,
        async_persist: Optional[bool] = None,
        on_durable: Optional[Callable[[int], None]] = None,
        delta_persist: bool = False,
        delta_full_every: int = 5,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.sharding = sharding
        # Model-geometry sidecar: configs with identical flattened kernel
        # shapes but different head grouping (e.g. 16x64 vs 8x128 attention)
        # load each other's checkpoints cleanly and silently compute a
        # differently-grouped attention — no shape error ever flags it.
        # Recording the geometry and validating at restore is the only
        # guard (ADVICE r2).
        self._model_meta = model_meta
        self._meta_path = os.path.join(os.path.abspath(directory), "model_meta.json")
        if async_persist is None:
            async_persist = os.environ.get(
                "TF_OPERATOR_SYNC_CHECKPOINT", ""
            ) not in ("1", "true", "yes")
        self.async_persist = bool(async_persist)
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),  # orbax requires absolute paths
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )
        # Durability plumbing. The worker thread owns the persist tail:
        # it (re)issues the orbax save for host snapshots, waits for the
        # finalize, THEN advances last_durable_step and fires listeners —
        # the only place either ever happens, so a listener can never
        # observe a step whose bytes are not committed.
        self._listeners: List[Callable[[int], None]] = []
        if on_durable is not None:
            self._listeners.append(on_durable)
        self._durable_lock = threading.Lock()
        self._last_durable: Optional[int] = None
        self._last_snapshot_step: Optional[int] = None
        self._snapshot: Optional[HostSnapshot] = None
        self._persist_queue: "queue.Queue[tuple]" = queue.Queue()
        self._persist_thread: Optional[threading.Thread] = None
        self._persist_errors = 0
        self._closed = False
        # Test seam: called in the persist worker between the snapshot
        # and the storage write — the crash-in-persist-window regressions
        # block or raise here to hold a step non-durable deterministically.
        self._persist_gate: Optional[Callable[[int], None]] = None
        # Delta-persist store (module doc). The WRITE side is flag-gated
        # (default OFF keeps every pre-existing seeded tier byte-identical
        # — no delta/ directory ever appears); the READ side keys on the
        # layout's presence so a restarted process restores a delta step
        # regardless of its own flag.
        self.delta_persist = bool(delta_persist)
        self.delta_full_every = max(1, int(delta_full_every))
        self._max_to_keep = max(1, int(max_to_keep))
        self._delta_dir = os.path.join(os.path.abspath(directory), "delta")
        self._delta_shards_dir = os.path.join(self._delta_dir, "shards")
        self._delta_persist_count = 0
        # Stats of the most recent delta/full persist this process
        # finalized: {"kind", "step", "chain_depth", "bytes_written",
        # "shards_written", "shards_skipped"} — the bench/test surface
        # behind training_checkpoint_persist_bytes_total.
        self.last_persist_info: Optional[dict] = None
        # The named cause when the most recent restore_latest() degraded
        # off a delta manifest ("delta-chain-broken" /
        # "delta-checksum-mismatch"); None on clean restores. Read by
        # train/restore.py to stamp the RestoreOutcome.
        self.last_delta_degradation: Optional[str] = None
        # Seeded-chaos seam (cluster/chaos.py RestoreFaultInjector):
        # consulted once per manifest payload read, op "delta-shard",
        # peer index 0 (storage has no peers; the index keeps the
        # fault_log entry shape uniform).
        self.restore_fault_injector = None

    # ----------------------------------------------------------- sidecar
    def _write_meta(self) -> None:
        import json

        if self._model_meta is None or os.path.exists(self._meta_path):
            return
        tmp = f"{self._meta_path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(self._meta_path), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(self._model_meta, f, sort_keys=True)
        os.replace(tmp, self._meta_path)

    def _validate_meta(self) -> None:
        import json

        if self._model_meta is None or not os.path.exists(self._meta_path):
            return
        with open(self._meta_path) as f:
            on_disk = json.load(f)
        mismatched = geometry_mismatch(on_disk, self._model_meta)
        if mismatched:
            raise ValueError(
                "checkpoint model geometry mismatch (saved vs current): "
                f"{mismatched} — refusing to mix checkpoints trained "
                "under different head/layer geometries in one directory"
            )

    # ----------------------------------------------------- delta store
    def _delta_manifest_path(self, step: int) -> str:
        return os.path.join(self._delta_dir, f"manifest-{int(step)}.json")

    def _delta_manifest_steps(self) -> List[int]:
        """Sorted steps with a (finalized) manifest on disk."""
        try:
            entries = os.listdir(self._delta_dir)
        except OSError:
            return []
        steps = []
        for entry in entries:
            if entry.startswith("manifest-") and entry.endswith(".json"):
                try:
                    steps.append(int(entry[len("manifest-"):-len(".json")]))
                except ValueError:
                    continue
        return sorted(steps)

    def _read_delta_manifest(self, step: int) -> Optional[dict]:
        import json

        try:
            with open(self._delta_manifest_path(step)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _delta_latest_step(self) -> Optional[int]:
        steps = self._delta_manifest_steps()
        return steps[-1] if steps else None

    def persisted_shard_names(self):
        """Sorted shard names the newest delta manifest references — what
        this manager's checkpoint stream PHYSICALLY holds. The
        slice-derived ownership source for ``/v1/manifest`` (PR 11
        per-slice checkpoint dirs: the slice claims what its own stream
        persisted, not a name stride). Empty tuple without a delta
        layout, which tells the shard server to fall back to striding."""
        step = self._delta_latest_step()
        if step is None:
            return ()
        manifest = self._read_delta_manifest(step)
        if not manifest:
            return ()
        return tuple(sorted(manifest.get("shards", ())))

    def delta_chain_depth(self) -> Optional[int]:
        """Chain depth of the newest manifest (0 = full persist), the
        ``training_checkpoint_delta_chain_depth`` gauge feed; None
        without a delta layout."""
        step = self._delta_latest_step()
        if step is None:
            return None
        manifest = self._read_delta_manifest(step)
        if not manifest:
            return None
        return int(manifest.get("chain_depth", 0))

    @staticmethod
    def _write_file_atomic(path: str, data: bytes) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def _persist_delta(self, step: int, tree: Any) -> None:
        """One delta-mode persist, on the worker thread (or inline in
        sync mode). Ordering IS the durability contract: every payload
        the manifest references is renamed into place before the
        manifest itself is, so a crash at any point leaves the previous
        manifest the newest durable step — never a torn one. The caller
        fires _mark_durable after this returns."""
        import json

        from ..runtime.shard_server import (
            encode_shard,
            flatten_tree,
            shard_checksum,
        )

        os.makedirs(self._delta_shards_dir, exist_ok=True)
        flat = flatten_tree(tree)
        payloads = {name: encode_shard(leaf) for name, leaf in flat.items()}
        checksums = {name: shard_checksum(p) for name, p in payloads.items()}
        prev_step = self._delta_latest_step()
        prev = (self._read_delta_manifest(prev_step)
                if prev_step is not None else None)
        self._delta_persist_count += 1
        # Chain bound: the first persist of a lineage (or of a restarted
        # process that inherited one at the bound) and every
        # delta_full_every-th persist rewrite EVERYTHING.
        full = (
            prev is None
            or (self._delta_persist_count - 1) % self.delta_full_every == 0
            or int(prev.get("chain_depth", 0)) + 1 >= self.delta_full_every
        )
        prev_sums = ({} if full or not prev
                     else {n: e["checksum"]
                           for n, e in prev.get("shards", {}).items()})
        written = skipped = bytes_written = 0
        for name in sorted(flat):
            payload = payloads[name]
            if prev_sums.get(name) == checksums[name]:
                # Unchanged since the last durable step: carried forward
                # by reference — the payload file already exists.
                skipped += 1
                continue
            path = os.path.join(
                self._delta_shards_dir, f"{checksums[name]}.npy")
            if full or not os.path.exists(path):
                self._write_file_atomic(path, payload)
            written += 1
            bytes_written += len(payload)
        chain_depth = 0 if full else int(prev.get("chain_depth", 0)) + 1
        manifest = {
            "step": int(step),
            "kind": "full" if full else "delta",
            "chain_depth": chain_depth,
            "model_meta": self._model_meta,
            "shards": {
                name: {"checksum": checksums[name],
                       "bytes": len(payloads[name])}
                for name in sorted(flat)
            },
        }
        manifest_bytes = json.dumps(manifest, sort_keys=True).encode()
        self._write_file_atomic(
            self._delta_manifest_path(step), manifest_bytes)
        self._delta_gc()
        self.last_persist_info = {
            "kind": manifest["kind"],
            "step": int(step),
            "chain_depth": chain_depth,
            "bytes_written": bytes_written + len(manifest_bytes),
            "shards_written": written,
            "shards_skipped": skipped,
        }
        try:
            from ..metrics import METRICS

            METRICS.observe_checkpoint_persist_bytes(
                manifest["kind"], bytes_written + len(manifest_bytes),
                skipped)
            METRICS.set_delta_chain_depth(chain_depth)
        except Exception:  # noqa: BLE001 — telemetry never gates durability
            pass

    def _delta_gc(self) -> None:
        """Retention: the newest max_to_keep manifests, PLUS the newest
        full manifest if none of those is full (the degradation target
        must survive), then every payload file no retained manifest
        references is deleted."""
        steps = self._delta_manifest_steps()
        if not steps:
            return
        manifests = {s: self._read_delta_manifest(s) for s in steps}
        keep = set(steps[-self._max_to_keep:])
        if not any((manifests[s] or {}).get("kind") == "full"
                   for s in keep):
            fulls = [s for s in steps
                     if (manifests[s] or {}).get("kind") == "full"]
            if fulls:
                keep.add(fulls[-1])
        referenced = set()
        for s in keep:
            for entry in (manifests[s] or {}).get("shards", {}).values():
                referenced.add(entry["checksum"])
        for s in steps:
            if s not in keep:
                try:
                    os.remove(self._delta_manifest_path(s))
                except OSError:
                    pass
        try:
            for entry in os.listdir(self._delta_shards_dir):
                if entry.endswith(".npy") and \
                        entry[:-len(".npy")] not in referenced:
                    os.remove(os.path.join(self._delta_shards_dir, entry))
        except OSError:
            pass

    def _resolve_delta(self, state, step: int):
        """Read + sha256-verify + reassemble one manifest's full tree.
        Raises :class:`_DeltaBroken` on ANY shortfall — the caller owns
        degradation to an older full manifest, never a partial mix."""
        import numpy as np

        from ..runtime.shard_server import decode_shard, shard_checksum

        manifest = self._read_delta_manifest(step)
        if not manifest or "shards" not in manifest:
            raise _DeltaBroken(
                "delta-chain-broken",
                f"manifest for step {step} unreadable")
        injector = self.restore_fault_injector
        shards = {}
        for name in sorted(manifest["shards"]):
            entry = manifest["shards"][name]
            kind = (injector.fault_for("delta-shard", 0)
                    if injector is not None else None)
            if kind == "delta-missing-shard":
                raise _DeltaBroken(
                    "delta-chain-broken",
                    f"injected: payload for {name} missing from the store")
            path = os.path.join(
                self._delta_shards_dir, f"{entry['checksum']}.npy")
            try:
                with open(path, "rb") as f:
                    payload = f.read()
            except OSError as err:
                raise _DeltaBroken(
                    "delta-chain-broken",
                    f"shard {name} payload {entry['checksum']} missing "
                    f"from the store ({err})") from err
            if kind == "delta-corrupt-shard":
                payload = payload[: max(0, len(payload) // 2)]
            if shard_checksum(payload) != entry["checksum"]:
                raise _DeltaBroken(
                    "delta-checksum-mismatch",
                    f"shard {name} failed sha256 verification against "
                    f"the step-{step} manifest")
            shards[name] = decode_shard(payload)
        abstract = self.abstract_state(state)
        flat, treedef = jax.tree_util.tree_flatten_with_path(abstract)
        leaves = []
        for key_path, leaf in flat:
            name = jax.tree_util.keystr(key_path)
            if name not in shards:
                raise _DeltaBroken(
                    "delta-chain-broken",
                    f"manifest for step {step} lacks shard {name}")
            value = np.asarray(shards[name]).astype(leaf.dtype)
            sharding = getattr(leaf, "sharding", None)
            leaves.append(
                jax.device_put(value, sharding) if sharding is not None
                else jax.numpy.asarray(value)
            )
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # ------------------------------------------------------ durability
    def add_durability_listener(self, cb: Callable[[int], None]) -> None:
        """Register cb(step), fired once per step AFTER its persist is
        finalized on storage — the only correct place to publish the
        checkpoint-step heartbeat rider (``record_checkpoint``)."""
        self._listeners.append(cb)

    def last_durable_step(self) -> Optional[int]:
        """Newest step this manager has FINALIZED on storage in this
        process's lifetime (None before the first persist completes —
        distinct from latest_step(), which also sees pre-existing
        checkpoints in the directory)."""
        with self._durable_lock:
            return self._last_durable

    def _mark_durable(self, step: int, persist_seconds: float) -> None:
        with self._durable_lock:
            if self._last_durable is None or step > self._last_durable:
                self._last_durable = step
        try:
            from ..metrics import METRICS

            METRICS.observe_checkpoint_persist(persist_seconds)
        except Exception:  # noqa: BLE001 — telemetry never gates durability
            pass
        for cb in list(self._listeners):
            try:
                cb(step)
            except Exception:  # noqa: BLE001 — a broken listener must not
                # wedge the persist worker (later steps still need it).
                log.exception("checkpoint durability listener failed")

    def _persist_loop(self) -> None:
        while True:
            item = self._persist_queue.get()
            try:
                if item[0] == "stop":
                    return
                kind, step, tree, t0 = item
                try:
                    if self._persist_gate is not None:
                        self._persist_gate(step)
                    if kind == "save":
                        # Host-snapshot path: the write itself happens
                        # here, off the training thread. force=True — the
                        # should_save decision was taken at snapshot time.
                        self._mgr.save(
                            step,
                            args=self._ocp.args.StandardSave(tree),
                            force=True,
                        )
                    if kind == "delta":
                        # Delta-mode host-snapshot path: changed payloads
                        # then the manifest (its rename IS the finalize).
                        self._persist_delta(step, tree)
                    else:
                        # Orbax paths: durable only once orbax finalizes.
                        self._mgr.wait_until_finished()
                except Exception:  # noqa: BLE001
                    self._persist_errors += 1
                    log.exception(
                        "checkpoint persist for step %s failed — the step "
                        "is NOT durable and will never be published", step
                    )
                    continue
                self._mark_durable(step, time.perf_counter() - t0)
            finally:
                self._persist_queue.task_done()

    def _ensure_worker(self) -> None:
        if self._persist_thread is None:
            self._persist_thread = threading.Thread(
                target=self._persist_loop, name="ckpt-persist", daemon=True
            )
            self._persist_thread.start()

    # -------------------------------------------------------- snapshot
    @staticmethod
    def _to_host(state) -> Optional[Any]:
        """Device→host copy of a fully process-addressable state; None when
        any leaf is sharded beyond this process (multi-host worlds — no
        single host can serve the full tree)."""
        import numpy as np

        leaves = jax.tree_util.tree_leaves(state)
        for leaf in leaves:
            if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
                return None
        return jax.tree.map(lambda leaf: np.asarray(jax.device_get(leaf)), state)

    def host_snapshot(self) -> Optional[HostSnapshot]:
        """The newest host-resident snapshot (the peer-restore shard
        source), or None when no fully-addressable save happened yet. May
        be ahead of last_durable_step(): a snapshot is servable the moment
        it exists — the restore arbitration compares steps, not
        durability."""
        return self._snapshot

    # ------------------------------------------------------------ save
    def save(self, state, force: bool = False) -> bool:
        """Snapshot now, persist in the background. Returns True iff the
        step was accepted (snapshot taken + persist scheduled); the step
        is durable only when the durability listeners fire. A step that is
        already on disk is a no-op (a final flush after a periodic save
        lands on the same step)."""
        step = int(jax.device_get(state.step))
        if self._mgr.latest_step() == step or self._last_snapshot_step == step \
                or (self.delta_persist and self._delta_latest_step() == step):
            return False
        if not force and not self._mgr.should_save(step):
            return False
        # Save-only runs reusing a directory must not mix geometries under
        # one sidecar: validate against any existing record before writing.
        self._validate_meta()
        t0 = time.perf_counter()
        host_tree = self._to_host(state)
        self._last_snapshot_step = step
        if host_tree is not None:
            self._snapshot = HostSnapshot(
                step=step, tree=host_tree, model_meta=self._model_meta
            )
            persist_kind = "delta" if self.delta_persist else "save"
            if self.async_persist:
                self._ensure_worker()
                self._persist_queue.put((persist_kind, step, host_tree, t0))
            elif self.delta_persist:
                self._persist_delta(step, host_tree)
                self._mark_durable(step, time.perf_counter() - t0)
            else:
                self._mgr.save(
                    step, args=self._ocp.args.StandardSave(host_tree),
                    force=True,
                )
                self._mgr.wait_until_finished()
                self._mark_durable(step, time.perf_counter() - t0)
        else:
            # Multi-host sharded state: every process contributes its own
            # shards through orbax's async machinery (returns after ITS
            # device→host snapshot), and the worker turns the finalize
            # into the durability edge.
            self._mgr.save(
                step, args=self._ocp.args.StandardSave(state), force=True
            )
            if self.async_persist:
                self._ensure_worker()
                self._persist_queue.put(("finalize", step, None, t0))
            else:
                self._mgr.wait_until_finished()
                self._mark_durable(step, time.perf_counter() - t0)
        self._write_meta()
        return True

    # --------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        orbax_step = self._mgr.latest_step()
        delta_step = self._delta_latest_step()
        if delta_step is None:
            return orbax_step
        if orbax_step is None:
            return delta_step
        return max(orbax_step, delta_step)

    def abstract_state(self, state):
        """`state`'s structure as ShapeDtypeStructs carrying the target
        shardings — what StandardRestore (and the peer-restore assembly)
        place restored values onto."""

        def as_abstract(leaf, shard):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=shard)

        if self.sharding is not None:
            return jax.tree.map(as_abstract, state, self.sharding)
        return jax.tree.map(
            lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
            if hasattr(leaf, "sharding")
            else jax.ShapeDtypeStruct(leaf.shape, leaf.dtype),
            state,
        )

    def restore_latest(self, state) -> Tuple[Any, Optional[int]]:
        """Restore the newest checkpoint into `state`'s structure/shardings;
        returns (state, step) — (input unchanged, None) when no checkpoint
        exists yet (first boot of the job). This is the STORAGE leg of the
        restore ladder; train/restore.py composes it with the peer path.

        Delta layouts resolve first whenever their newest manifest is at
        least as fresh as orbax (keyed on the layout's PRESENCE, not this
        manager's flag — a flag-off restart must still restore what a
        flag-on predecessor persisted). A broken resolution degrades the
        whole tree: newest verifying FULL manifest, then orbax — with the
        first named cause kept on ``last_delta_degradation``."""
        self.last_delta_degradation = None
        orbax_step = self._mgr.latest_step()
        delta_step = self._delta_latest_step()
        if delta_step is not None and (
                orbax_step is None or delta_step >= orbax_step):
            self._validate_meta()
            try:
                return self._resolve_delta(state, delta_step), delta_step
            except _DeltaBroken as err:
                self.last_delta_degradation = err.cause
                log.warning(
                    "delta restore of step %s degraded (%s: %s); falling "
                    "back to the newest full manifest", delta_step,
                    err.cause, err)
            fulls = [
                s for s in self._delta_manifest_steps()
                if s != delta_step
                and (self._read_delta_manifest(s) or {}).get("kind") == "full"
            ]
            for s in reversed(fulls):
                try:
                    return self._resolve_delta(state, s), s
                except _DeltaBroken as err:
                    log.warning(
                        "full manifest at step %s also broken (%s); "
                        "continuing down", s, err.cause)
        if orbax_step is None:
            return state, None
        self._validate_meta()
        restored = self._mgr.restore(
            orbax_step,
            args=self._ocp.args.StandardRestore(self.abstract_state(state))
        )
        return restored, orbax_step

    # -------------------------------------------------------- shutdown
    def wait(self) -> None:
        """Drain: every scheduled persist is finalized (and its listeners
        fired) when this returns."""
        if self._persist_thread is not None:
            self._persist_queue.join()
        self._mgr.wait_until_finished()

    def close(self) -> None:
        """Shutdown hygiene: drain the persist queue, stop the worker, and
        close orbax — a completing (or failing) job must never exit with
        an in-flight async write, or the newest checkpoint it believes it
        took is a torn tmp dir. Idempotent; safe on half-constructed
        managers (__exit__ runs on any error path)."""
        if self._closed:
            return
        self._closed = True
        if self._persist_thread is not None:
            self._persist_queue.join()
            self._persist_queue.put(("stop",))
            self._persist_thread.join(timeout=60.0)
            self._persist_thread = None
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
