"""Checkpoint/resume for training workloads (orbax) — snapshot-then-persist.

The reference deliberately keeps checkpointing OUT of the operator
(SURVEY.md §5.4): restart semantics assume the framework resumes from its
own checkpoints, and the operator only contributes restart orchestration
plus stable identities. This module is the workload half of that contract:
sharded checkpoints keyed by step, so a replica recreated by the ExitCode
restart policy resumes exactly where the gang left off.

Save is split into two phases (docs/design/checkpoint_recovery.md):

- **snapshot** — a synchronous device→host copy taken at the step boundary.
  Training resumes the moment it returns; the host copy is also retained
  in memory as the shard source for peer-to-peer restore
  (runtime/shard_server.py).
- **persist** — a background write of that host copy to storage. A step is
  DURABLE only once the persist is finalized (orbax's atomic rename), and
  only then do the durability listeners fire. ``record_checkpoint`` — the
  signal the operator's checkpoint-gated elastic shrink consumes — must be
  registered as a listener, never called after ``save()`` returns: the
  return only proves the snapshot, and publishing a step whose persist is
  still in flight lets the autoscaler shrink against a checkpoint that a
  crash in the persist window erases.

States that are not fully process-addressable (multi-host sharded worlds)
cannot be host-snapshotted by one process; those saves go straight through
orbax's async machinery (training still resumes immediately) and the
durability listeners still fire only after ``wait_until_finished`` — but
there is no host snapshot to serve peers from (``host_snapshot()`` is
None and restores degrade to the storage path).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

import jax

log = logging.getLogger(__name__)


def geometry_mismatch(saved: Optional[dict], current: Optional[dict]) -> dict:
    """Keys whose recorded and current model geometry disagree — the
    guard against configs with identical flattened kernel shapes but
    different head grouping loading each other's checkpoints and silently
    computing differently-grouped attention (ADVICE r2). Shared by the
    storage sidecar check and the peer-restore meta check."""
    if not saved or not current:
        return {}
    return {
        k: (saved[k], current[k])
        for k in saved.keys() & current.keys()
        if saved[k] != current[k]
    }


@dataclass
class HostSnapshot:
    """One step's host-resident state copy: the peer-restore shard source.
    ``tree`` is the TrainState structure with numpy leaves; treated as
    immutable once published (the shard server may be mid-serve)."""

    step: int
    tree: Any
    model_meta: Optional[dict] = None
    # Monotonic publication stamp (diagnostics only — never compared
    # across hosts).
    taken_at: float = field(default_factory=time.monotonic)


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager bound to one TrainState
    sharding, so save/restore round-trips preserve the mesh layout —
    plus the snapshot/persist split and the durability barrier."""

    def __init__(
        self,
        directory: str,
        sharding: Any = None,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        model_meta: Optional[dict] = None,
        async_persist: Optional[bool] = None,
        on_durable: Optional[Callable[[int], None]] = None,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.sharding = sharding
        # Model-geometry sidecar: configs with identical flattened kernel
        # shapes but different head grouping (e.g. 16x64 vs 8x128 attention)
        # load each other's checkpoints cleanly and silently compute a
        # differently-grouped attention — no shape error ever flags it.
        # Recording the geometry and validating at restore is the only
        # guard (ADVICE r2).
        self._model_meta = model_meta
        self._meta_path = os.path.join(os.path.abspath(directory), "model_meta.json")
        if async_persist is None:
            async_persist = os.environ.get(
                "TF_OPERATOR_SYNC_CHECKPOINT", ""
            ) not in ("1", "true", "yes")
        self.async_persist = bool(async_persist)
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),  # orbax requires absolute paths
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )
        # Durability plumbing. The worker thread owns the persist tail:
        # it (re)issues the orbax save for host snapshots, waits for the
        # finalize, THEN advances last_durable_step and fires listeners —
        # the only place either ever happens, so a listener can never
        # observe a step whose bytes are not committed.
        self._listeners: List[Callable[[int], None]] = []
        if on_durable is not None:
            self._listeners.append(on_durable)
        self._durable_lock = threading.Lock()
        self._last_durable: Optional[int] = None
        self._last_snapshot_step: Optional[int] = None
        self._snapshot: Optional[HostSnapshot] = None
        self._persist_queue: "queue.Queue[tuple]" = queue.Queue()
        self._persist_thread: Optional[threading.Thread] = None
        self._persist_errors = 0
        self._closed = False
        # Test seam: called in the persist worker between the snapshot
        # and the storage write — the crash-in-persist-window regressions
        # block or raise here to hold a step non-durable deterministically.
        self._persist_gate: Optional[Callable[[int], None]] = None

    # ----------------------------------------------------------- sidecar
    def _write_meta(self) -> None:
        import json

        if self._model_meta is None or os.path.exists(self._meta_path):
            return
        tmp = f"{self._meta_path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(self._meta_path), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(self._model_meta, f, sort_keys=True)
        os.replace(tmp, self._meta_path)

    def _validate_meta(self) -> None:
        import json

        if self._model_meta is None or not os.path.exists(self._meta_path):
            return
        with open(self._meta_path) as f:
            on_disk = json.load(f)
        mismatched = geometry_mismatch(on_disk, self._model_meta)
        if mismatched:
            raise ValueError(
                "checkpoint model geometry mismatch (saved vs current): "
                f"{mismatched} — refusing to mix checkpoints trained "
                "under different head/layer geometries in one directory"
            )

    # ------------------------------------------------------ durability
    def add_durability_listener(self, cb: Callable[[int], None]) -> None:
        """Register cb(step), fired once per step AFTER its persist is
        finalized on storage — the only correct place to publish the
        checkpoint-step heartbeat rider (``record_checkpoint``)."""
        self._listeners.append(cb)

    def last_durable_step(self) -> Optional[int]:
        """Newest step this manager has FINALIZED on storage in this
        process's lifetime (None before the first persist completes —
        distinct from latest_step(), which also sees pre-existing
        checkpoints in the directory)."""
        with self._durable_lock:
            return self._last_durable

    def _mark_durable(self, step: int, persist_seconds: float) -> None:
        with self._durable_lock:
            if self._last_durable is None or step > self._last_durable:
                self._last_durable = step
        try:
            from ..metrics import METRICS

            METRICS.observe_checkpoint_persist(persist_seconds)
        except Exception:  # noqa: BLE001 — telemetry never gates durability
            pass
        for cb in list(self._listeners):
            try:
                cb(step)
            except Exception:  # noqa: BLE001 — a broken listener must not
                # wedge the persist worker (later steps still need it).
                log.exception("checkpoint durability listener failed")

    def _persist_loop(self) -> None:
        while True:
            item = self._persist_queue.get()
            try:
                if item[0] == "stop":
                    return
                kind, step, tree, t0 = item
                try:
                    if self._persist_gate is not None:
                        self._persist_gate(step)
                    if kind == "save":
                        # Host-snapshot path: the write itself happens
                        # here, off the training thread. force=True — the
                        # should_save decision was taken at snapshot time.
                        self._mgr.save(
                            step,
                            args=self._ocp.args.StandardSave(tree),
                            force=True,
                        )
                    # Both paths: durable only once orbax finalizes.
                    self._mgr.wait_until_finished()
                except Exception:  # noqa: BLE001
                    self._persist_errors += 1
                    log.exception(
                        "checkpoint persist for step %s failed — the step "
                        "is NOT durable and will never be published", step
                    )
                    continue
                self._mark_durable(step, time.perf_counter() - t0)
            finally:
                self._persist_queue.task_done()

    def _ensure_worker(self) -> None:
        if self._persist_thread is None:
            self._persist_thread = threading.Thread(
                target=self._persist_loop, name="ckpt-persist", daemon=True
            )
            self._persist_thread.start()

    # -------------------------------------------------------- snapshot
    @staticmethod
    def _to_host(state) -> Optional[Any]:
        """Device→host copy of a fully process-addressable state; None when
        any leaf is sharded beyond this process (multi-host worlds — no
        single host can serve the full tree)."""
        import numpy as np

        leaves = jax.tree_util.tree_leaves(state)
        for leaf in leaves:
            if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
                return None
        return jax.tree.map(lambda leaf: np.asarray(jax.device_get(leaf)), state)

    def host_snapshot(self) -> Optional[HostSnapshot]:
        """The newest host-resident snapshot (the peer-restore shard
        source), or None when no fully-addressable save happened yet. May
        be ahead of last_durable_step(): a snapshot is servable the moment
        it exists — the restore arbitration compares steps, not
        durability."""
        return self._snapshot

    # ------------------------------------------------------------ save
    def save(self, state, force: bool = False) -> bool:
        """Snapshot now, persist in the background. Returns True iff the
        step was accepted (snapshot taken + persist scheduled); the step
        is durable only when the durability listeners fire. A step that is
        already on disk is a no-op (a final flush after a periodic save
        lands on the same step)."""
        step = int(jax.device_get(state.step))
        if self._mgr.latest_step() == step or self._last_snapshot_step == step:
            return False
        if not force and not self._mgr.should_save(step):
            return False
        # Save-only runs reusing a directory must not mix geometries under
        # one sidecar: validate against any existing record before writing.
        self._validate_meta()
        t0 = time.perf_counter()
        host_tree = self._to_host(state)
        self._last_snapshot_step = step
        if host_tree is not None:
            self._snapshot = HostSnapshot(
                step=step, tree=host_tree, model_meta=self._model_meta
            )
            if self.async_persist:
                self._ensure_worker()
                self._persist_queue.put(("save", step, host_tree, t0))
            else:
                self._mgr.save(
                    step, args=self._ocp.args.StandardSave(host_tree),
                    force=True,
                )
                self._mgr.wait_until_finished()
                self._mark_durable(step, time.perf_counter() - t0)
        else:
            # Multi-host sharded state: every process contributes its own
            # shards through orbax's async machinery (returns after ITS
            # device→host snapshot), and the worker turns the finalize
            # into the durability edge.
            self._mgr.save(
                step, args=self._ocp.args.StandardSave(state), force=True
            )
            if self.async_persist:
                self._ensure_worker()
                self._persist_queue.put(("finalize", step, None, t0))
            else:
                self._mgr.wait_until_finished()
                self._mark_durable(step, time.perf_counter() - t0)
        self._write_meta()
        return True

    # --------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def abstract_state(self, state):
        """`state`'s structure as ShapeDtypeStructs carrying the target
        shardings — what StandardRestore (and the peer-restore assembly)
        place restored values onto."""

        def as_abstract(leaf, shard):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=shard)

        if self.sharding is not None:
            return jax.tree.map(as_abstract, state, self.sharding)
        return jax.tree.map(
            lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
            if hasattr(leaf, "sharding")
            else jax.ShapeDtypeStruct(leaf.shape, leaf.dtype),
            state,
        )

    def restore_latest(self, state) -> Tuple[Any, Optional[int]]:
        """Restore the newest checkpoint into `state`'s structure/shardings;
        returns (state, step) — (input unchanged, None) when no checkpoint
        exists yet (first boot of the job). This is the STORAGE leg of the
        restore ladder; train/restore.py composes it with the peer path."""
        step = self._mgr.latest_step()
        if step is None:
            return state, None
        self._validate_meta()
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(self.abstract_state(state))
        )
        return restored, step

    # -------------------------------------------------------- shutdown
    def wait(self) -> None:
        """Drain: every scheduled persist is finalized (and its listeners
        fired) when this returns."""
        if self._persist_thread is not None:
            self._persist_queue.join()
        self._mgr.wait_until_finished()

    def close(self) -> None:
        """Shutdown hygiene: drain the persist queue, stop the worker, and
        close orbax — a completing (or failing) job must never exit with
        an in-flight async write, or the newest checkpoint it believes it
        took is a torn tmp dir. Idempotent; safe on half-constructed
        managers (__exit__ runs on any error path)."""
        if self._closed:
            return
        self._closed = True
        if self._persist_thread is not None:
            self._persist_queue.join()
            self._persist_queue.put(("stop",))
            self._persist_thread.join(timeout=60.0)
            self._persist_thread = None
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
