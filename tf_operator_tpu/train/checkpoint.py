"""Checkpoint/resume for training workloads (orbax).

The reference deliberately keeps checkpointing OUT of the operator
(SURVEY.md §5.4): restart semantics assume the framework resumes from its
own checkpoints, and the operator only contributes restart orchestration
plus stable identities. This module is the workload half of that contract:
sharded async orbax checkpoints keyed by step, so a replica recreated by
the ExitCode restart policy resumes exactly where the gang left off.

TPU-first: saves are async (training continues while the previous state
streams to storage) and restores are sharding-aware (each host reads only
its own shards — no host ever materializes the full 7B state).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager bound to one TrainState
    sharding, so save/restore round-trips preserve the mesh layout."""

    def __init__(
        self,
        directory: str,
        sharding: Any = None,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        model_meta: Optional[dict] = None,
    ):
        import os

        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.sharding = sharding
        # Model-geometry sidecar: configs with identical flattened kernel
        # shapes but different head grouping (e.g. 16x64 vs 8x128 attention)
        # load each other's checkpoints cleanly and silently compute a
        # differently-grouped attention — no shape error ever flags it.
        # Recording the geometry and validating at restore is the only
        # guard (ADVICE r2).
        self._model_meta = model_meta
        self._meta_path = os.path.join(os.path.abspath(directory), "model_meta.json")
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),  # orbax requires absolute paths
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    def _write_meta(self) -> None:
        import json
        import os

        if self._model_meta is None or os.path.exists(self._meta_path):
            return
        tmp = f"{self._meta_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self._model_meta, f, sort_keys=True)
        os.replace(tmp, self._meta_path)

    def _validate_meta(self) -> None:
        import json
        import os

        if self._model_meta is None or not os.path.exists(self._meta_path):
            return
        with open(self._meta_path) as f:
            on_disk = json.load(f)
        mismatched = {
            k: (on_disk[k], self._model_meta[k])
            for k in on_disk.keys() & self._model_meta.keys()
            if on_disk[k] != self._model_meta[k]
        }
        if mismatched:
            raise ValueError(
                "checkpoint model geometry mismatch (saved vs current): "
                f"{mismatched} — refusing to mix checkpoints trained "
                "under different head/layer geometries in one directory"
            )

    def save(self, state, force: bool = False) -> bool:
        """Async save at the state's own step counter. A step that is
        already on disk is a no-op (a final flush after a periodic save
        lands on the same step)."""
        step = int(jax.device_get(state.step))
        if self._mgr.latest_step() == step:
            return False
        # Save-only runs reusing a directory must not mix geometries under
        # one sidecar: validate against any existing record before writing.
        self._validate_meta()
        saved = self._mgr.save(
            step, args=self._ocp.args.StandardSave(state), force=force
        )
        if saved:
            self._write_meta()
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, state) -> Tuple[Any, Optional[int]]:
        """Restore the newest checkpoint into `state`'s structure/shardings;
        returns (state, step) — (input unchanged, None) when no checkpoint
        exists yet (first boot of the job)."""
        step = self._mgr.latest_step()
        if step is None:
            return state, None
        self._validate_meta()

        def as_abstract(leaf, shard):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=shard)

        if self.sharding is not None:
            abstract = jax.tree.map(as_abstract, state, self.sharding)
        else:
            abstract = jax.tree.map(
                lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
                if hasattr(leaf, "sharding")
                else jax.ShapeDtypeStruct(leaf.shape, leaf.dtype),
                state,
            )
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(abstract)
        )
        return restored, step

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
