import sys

from .gen import main

if __name__ == "__main__":
    sys.exit(main())
