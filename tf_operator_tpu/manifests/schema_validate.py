"""Structural-schema validation (the server-side half of the CRD contract).

Validates an object against the openAPIV3Schema subset `manifests/gen.py`
emits — type checks on object/array/string/integer/number/boolean,
`required` fields, recursion through properties/items/additionalProperties.
Unknown fields follow apiextensions semantics: allowed (they would be
pruned or preserved server-side), never a validation error.

Used by the stub apiserver so a bad-field CR is rejected at create/update
exactly as a real apiserver with the reference's flattened schema would
reject it (manifests/base/crds/kubeflow.org_tfjobs.yaml)."""

from __future__ import annotations

from typing import Any, Dict, List


class SchemaError(ValueError):
    """Object does not conform to the structural schema."""


def _type_ok(expected: str, value: Any) -> bool:
    if expected == "object":
        return isinstance(value, dict)
    if expected == "array":
        return isinstance(value, (list, tuple))
    if expected == "string":
        return isinstance(value, str)
    if expected == "boolean":
        return isinstance(value, bool)
    if expected == "integer":
        if isinstance(value, bool):
            return False
        if isinstance(value, int):
            return True
        # JSON decoders may surface whole numbers as floats; go-openapi
        # treats whole float64s as integers, so the stub must too.
        return isinstance(value, float) and value.is_integer()
    if expected == "number":
        return (
            isinstance(value, (int, float)) and not isinstance(value, bool)
        )
    return True  # unknown declared type: accept


def validate_schema(schema: Dict[str, Any], obj: Any, path: str = "") -> None:
    """Raise SchemaError at the first violation, naming the field path."""
    if obj is None:
        return  # null = unset; requiredness is enforced by the parent
    expected = schema.get("type")
    if expected and not _type_ok(expected, obj):
        raise SchemaError(
            f"{path or '<root>'}: expected {expected}, "
            f"got {type(obj).__name__}: {obj!r}"
        )
    if isinstance(obj, dict):
        for req in schema.get("required", ()):
            if obj.get(req) is None:
                raise SchemaError(f"{path or '<root>'}: missing required field {req!r}")
        props = schema.get("properties") or {}
        extra = schema.get("additionalProperties")
        for key, val in obj.items():
            if key in props:
                validate_schema(props[key], val, f"{path}.{key}" if path else key)
            elif isinstance(extra, dict) and extra:
                validate_schema(extra, val, f"{path}.{key}" if path else key)
            # unknown field: prune/preserve semantics — never an error
    elif isinstance(obj, (list, tuple)):
        items = schema.get("items")
        if isinstance(items, dict) and items:
            for i, val in enumerate(obj):
                validate_schema(items, val, f"{path}[{i}]")


_CRD_SCHEMAS: Dict[str, Dict[str, Any]] = {}


def crd_schema_for(kind: str) -> Dict[str, Any]:
    """The generated openAPIV3Schema for a job kind (cached)."""
    if not _CRD_SCHEMAS:
        from . import gen

        # Build complete, then swap in atomically: a concurrent reader must
        # never observe a partially-populated cache (ThreadingHTTPServer
        # validates different kinds from different threads).
        built = {
            module.KIND: gen.generate_crd(module)["spec"]["versions"][0][
                "schema"
            ]["openAPIV3Schema"]
            for module in gen._KIND_MODULES
        }
        _CRD_SCHEMAS.update(built)
    try:
        return _CRD_SCHEMAS[kind]
    except KeyError:
        raise SchemaError(f"no CRD schema for kind {kind!r}")


def validate_job_dict(job_dict: dict) -> None:
    """Validate a CR dict against its kind's generated CRD schema, with
    status-subresource semantics: a main-resource write never validates (or
    persists) .status — the apiserver strips it before validation, so a
    re-applied exported CR carrying RFC3339 condition timestamps must not
    422 here when a real apiserver would accept it."""
    kind = job_dict.get("kind", "")
    body = {k: v for k, v in job_dict.items() if k != "status"}
    validate_schema(crd_schema_for(kind), body)
