"""CRD + deployment manifest generation.

The reference ships controller-gen output (manifests/base/crds/*.yaml,
~6.9k lines per kind) plus kustomize bases for the Deployment/Service/RBAC
(manifests/base/*.yaml, SURVEY.md §2.8). Here the openAPIV3Schema is derived
directly from the API dataclasses, so the schema can never drift from the
code; the embedded PodTemplateSpec is declared with
``x-kubernetes-preserve-unknown-fields`` instead of inlining the entire
core/v1 schema (the one deliberate divergence — the reference's 6.9k-line
flattened pod schema adds no validation the apiserver doesn't already do).
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, List, Optional, Union

from ..api import jaxjob, mxjob, pytorchjob, tfjob, xgboostjob
from ..api.k8s import PodTemplateSpec, _to_camel

_KIND_MODULES = (tfjob, pytorchjob, mxjob, xgboostjob, jaxjob)

_PRIMITIVES = {
    str: {"type": "string"},
    int: {"type": "integer"},
    float: {"type": "number"},
    bool: {"type": "boolean"},
}


def _schema_for_type(tp: Any, preserve: bool = False) -> Dict[str, Any]:
    origin = typing.get_origin(tp)
    args = typing.get_args(tp)
    if origin is Union:  # Optional[T] and friends
        non_none = [a for a in args if a is not type(None)]
        if len(non_none) == 1:
            return _schema_for_type(non_none[0], preserve)
        return {"x-kubernetes-preserve-unknown-fields": True}
    if origin in (dict, Dict):
        value_schema = _schema_for_type(args[1], preserve) if len(args) == 2 else {}
        return {"type": "object", "additionalProperties": value_schema}
    if origin in (list, List):
        item_schema = _schema_for_type(args[0], preserve) if args else {}
        return {"type": "array", "items": item_schema}
    if tp in _PRIMITIVES:
        return dict(_PRIMITIVES[tp])
    if tp is Any or tp is object:
        return {"x-kubernetes-preserve-unknown-fields": True}
    if dataclasses.is_dataclass(tp):
        if tp is PodTemplateSpec:
            # The embedded pod template gets the full structural schema of
            # the consumed subset (reference granularity: the flattened
            # containers/env/resources/volumes block of
            # manifests/base/crds/kubeflow.org_tfjobs.yaml) — a typo'd type
            # is rejected at kubectl-apply time. Every object node below
            # carries x-kubernetes-preserve-unknown-fields so VALID core/v1
            # fields we don't model are preserved, not pruned.
            preserve = True
        return dataclass_schema(tp, preserve=preserve)
    return {"x-kubernetes-preserve-unknown-fields": True}


def dataclass_schema(cls: type, preserve: bool = False) -> Dict[str, Any]:
    """openAPI v3 structural schema for a dataclass tree.

    `preserve` marks this object (and its object descendants) with
    x-kubernetes-preserve-unknown-fields: known fields are still
    type-validated, unknown ones survive pruning. Dataclasses may declare
    `__schema_required__` (camelCase names) for required fields."""
    hints = typing.get_type_hints(cls)
    properties = {}
    for f in dataclasses.fields(cls):
        key = f.metadata.get("json", _to_camel(f.name))
        properties[key] = _schema_for_type(hints.get(f.name, Any), preserve)
    out: Dict[str, Any] = {"type": "object", "properties": properties}
    required = list(getattr(cls, "__schema_required__", ()))
    if required:
        out["required"] = required
    if preserve:
        out["x-kubernetes-preserve-unknown-fields"] = True
    return out


def generate_crd(module) -> Dict[str, Any]:
    """CustomResourceDefinition manifest for one job kind module."""
    spec_cls = getattr(module, f"{module.KIND}Spec")
    from ..api.common import JobStatus

    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{module.PLURAL}.{module.GROUP}"},
        "spec": {
            "group": module.GROUP,
            "names": {
                "kind": module.KIND,
                "plural": module.PLURAL,
                "singular": module.SINGULAR,
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": module.VERSION,
                    "served": True,
                    "storage": True,
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "apiVersion": {"type": "string"},
                                "kind": {"type": "string"},
                                "metadata": {"type": "object"},
                                "spec": dataclass_schema(spec_cls),
                                "status": dataclass_schema(JobStatus),
                            },
                        }
                    },
                    "subresources": {"status": {}},
                    "additionalPrinterColumns": [
                        {
                            "name": "State",
                            "type": "string",
                            "jsonPath": ".status.conditions[-1:].type",
                        },
                        {
                            "name": "Age",
                            "type": "date",
                            "jsonPath": ".metadata.creationTimestamp",
                        },
                    ],
                }
            ],
        },
    }


def operator_manifests(namespace: str = "kubeflow") -> List[Dict[str, Any]]:
    # The Namespace object leads the list: a fresh cluster has no
    # "kubeflow" namespace, and every other object here targets it.
    """Deployment + Service + RBAC for the operator process (reference
    manifests/base/{deployment,service,cluster-role,service-account}.yaml)."""
    labels = {"control-plane": "tf-operator-tpu"}
    return [
        {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {"name": namespace},
        },
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": "tf-operator-tpu", "namespace": namespace},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": "tf-operator-tpu-role"},
            "rules": [
                {
                    "apiGroups": ["kubeflow.org"],
                    "resources": [
                        f"{m.PLURAL}" for m in _KIND_MODULES
                    ] + [f"{m.PLURAL}/status" for m in _KIND_MODULES],
                    "verbs": ["create", "delete", "get", "list", "patch", "update", "watch"],
                },
                {
                    "apiGroups": [""],
                    "resources": ["pods", "services", "endpoints", "events"],
                    "verbs": ["create", "delete", "get", "list", "patch", "update", "watch"],
                },
                {
                    # Gang scheduling: pod-slice gangs materialize as PodGroups
                    # (volcano analog; reference cluster-role.yaml podgroups rule).
                    "apiGroups": ["scheduling.volcano.sh"],
                    "resources": ["podgroups"],
                    "verbs": ["create", "delete", "get", "list", "update", "watch"],
                },
                {
                    # Leader election: replicas arbitrate through a
                    # coordination.k8s.io Lease (core/leaderelection.py) —
                    # the modern analog of the reference's EndpointsLock
                    # (cmd/tf-operator.v1/app/server.go:168-196).
                    "apiGroups": ["coordination.k8s.io"],
                    "resources": ["leases"],
                    "verbs": ["create", "get", "update"],
                },
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": "tf-operator-tpu-rolebinding"},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "tf-operator-tpu-role",
            },
            "subjects": [
                {"kind": "ServiceAccount", "name": "tf-operator-tpu", "namespace": namespace}
            ],
        },
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": "tf-operator-tpu", "namespace": namespace, "labels": labels},
            "spec": {
                # Two replicas is now safe AND useful: the Lease-backed
                # election guarantees exactly one reconciles while the
                # standby gives fast failover (round-2; r1 pinned 1 replica
                # because the in-process lock had no cross-pod safety).
                "replicas": 2,
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {
                        "serviceAccountName": "tf-operator-tpu",
                        "containers": [
                            {
                                "name": "operator",
                                "image": "tf-operator-tpu:latest",
                                "command": ["python", "-m", "tf_operator_tpu",
                                            "--kube", "--leader-elect"],
                                "env": [
                                    {
                                        # Lease namespace + holder identity
                                        # (downward API).
                                        "name": "POD_NAMESPACE",
                                        "valueFrom": {"fieldRef": {
                                            "fieldPath": "metadata.namespace"}},
                                    },
                                ],
                                "ports": [
                                    {"containerPort": 8443, "name": "metrics"},
                                    {"containerPort": 8081, "name": "health"},
                                ],
                                "livenessProbe": {
                                    "httpGet": {"path": "/healthz", "port": 8081},
                                    "initialDelaySeconds": 15,
                                    "periodSeconds": 20,
                                },
                                "readinessProbe": {
                                    "httpGet": {"path": "/readyz", "port": 8081},
                                    "initialDelaySeconds": 5,
                                    "periodSeconds": 10,
                                },
                                "resources": {
                                    "limits": {"cpu": "500m", "memory": "128Mi"},
                                    "requests": {"cpu": "100m", "memory": "64Mi"},
                                },
                                "securityContext": {"allowPrivilegeEscalation": False},
                            }
                        ],
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": "tf-operator-tpu-metrics",
                "namespace": namespace,
                "labels": labels,
                "annotations": {
                    "prometheus.io/scrape": "true",
                    "prometheus.io/port": "8443",
                    "prometheus.io/path": "/metrics",
                },
            },
            "spec": {
                "selector": labels,
                "ports": [{"name": "metrics", "port": 8443, "targetPort": 8443}],
            },
        },
    ]


def generate_all() -> Dict[str, List[Dict[str, Any]]]:
    """All manifests: filename stem -> list of documents."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for module in _KIND_MODULES:
        out[f"crds/{module.GROUP}_{module.PLURAL}"] = [generate_crd(module)]
    out["operator"] = operator_manifests()
    return out


def write_manifests(outdir: str) -> List[str]:
    import os

    import yaml

    written = []
    for stem, docs in generate_all().items():
        path = os.path.join(outdir, f"{stem}.yaml")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            yaml.safe_dump_all(docs, fh, sort_keys=False)
        written.append(path)
    return written


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description="Generate CRD + operator manifests.")
    parser.add_argument("--outdir", default="manifests")
    args = parser.parse_args(argv)
    for path in write_manifests(args.outdir):
        print(path)
    return 0
