"""Deployment packaging (L6): CRD + operator manifests, generated from the
API dataclasses. Reference: manifests/base/** (controller-gen output +
kustomize); here generation is first-party (`python -m
tf_operator_tpu.manifests`)."""

from .gen import generate_all, generate_crd, operator_manifests, write_manifests

__all__ = ["generate_crd", "generate_all", "operator_manifests", "write_manifests"]
