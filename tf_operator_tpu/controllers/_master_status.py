"""Shared master-keyed status state machine.

PyTorch, XGBoost and MXNet all key job conditions on one "completion"
replica (Master / Master / Scheduler): it running -> Running, it fully
succeeded -> Succeeded; any failure -> Restarting (ExitCode policy) or
Failed. Reference: pytorchjob_controller.go:317-399,
xgboostjob_controller.go:330-405, mxjob_controller.go:340-420 (three
near-identical copies the reference maintains separately; folded here once).
"""

from __future__ import annotations

from typing import Dict

from ..api import common as capi
from ..api.common import JobStatus, ReplicaSpec
from ..api.k8s import Event
from ..core import constants
from ..core.control import record_event_best_effort


def update_master_based_status(
    controller,
    job,
    replicas: Dict[str, ReplicaSpec],
    job_status: JobStatus,
    master_type: str,
) -> None:
    kind = controller.kind
    now = controller.clock()
    restarting = getattr(job_status, "_restarting_this_sync", False)

    if job_status.start_time is None:
        job_status.start_time = now

    for rtype in controller.replica_order(replicas):
        spec = replicas[rtype]
        status = job_status.replica_statuses.get(rtype)
        if status is None:
            continue
        succeeded = status.succeeded
        expected = (spec.replicas or 0) - succeeded
        running = status.active
        failed = status.failed

        if rtype == master_type:
            if running > 0 and not restarting:
                capi.update_job_conditions(
                    job_status,
                    capi.JOB_RUNNING,
                    constants.job_reason(kind, constants.REASON_RUNNING),
                    f"{kind} {job.key()} is running.",
                    now=now,
                )
            if expected == 0:
                msg = f"{kind} {job.key()} is successfully completed."
                if job_status.completion_time is None:
                    job_status.completion_time = now
                capi.update_job_conditions(
                    job_status,
                    capi.JOB_SUCCEEDED,
                    constants.job_reason(kind, constants.REASON_SUCCEEDED),
                    msg,
                    now=now,
                )
                record_event_best_effort(
                    controller.cluster,
                    Event(
                        type="Normal",
                        reason=constants.job_reason(kind, constants.REASON_SUCCEEDED),
                        message=msg,
                        involved_object=f"{job.kind}/{job.key()}",
                    )
                )
                return

        if failed > 0:
            # Suppress Failed only when THIS sync initiated a retryable
            # restart (the engine deleted the pod and set Restarting). A
            # stale Restarting condition from a previous sync must not
            # suppress: a recreated pod failing with a permanent exit code
            # has failed>0 with restarting=False and must fail the job —
            # otherwise it wedges non-terminal forever.
            if restarting:
                continue
            msg = f"{kind} {job.key()} is failed because {failed} {rtype} replica(s) failed."
            if job_status.completion_time is None:
                job_status.completion_time = now
            capi.update_job_conditions(
                job_status,
                capi.JOB_FAILED,
                constants.job_reason(kind, constants.REASON_FAILED),
                msg,
                now=now,
            )
            record_event_best_effort(
                controller.cluster,
                Event(
                    type="Normal",
                    reason=constants.job_reason(kind, constants.REASON_FAILED),
                    message=msg,
                    involved_object=f"{job.kind}/{job.key()}",
                )
            )
            return