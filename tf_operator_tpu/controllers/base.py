"""Base framework controller: wires hooks + engine + cluster watches.

The reference equivalent is each framework's Reconciler embedding
common.JobController and implementing ControllerInterface
(tfjob_controller.go:75-204). Here the shared wiring lives once.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from ..api import KINDS
from ..api.common import JobObject
from ..api.defaulting import ValidationError
from ..api.k8s import Event
from ..cluster.base import ADDED, DELETED, Cluster, Conflict, NotFound
from ..core import constants
from ..core.control import (
    RealPodControl,
    RealServiceControl,
    TokenBucket,
    record_event_best_effort,
)
from ..core.expectations import ControllerExpectations
from ..core.job_controller import EngineOptions, FrameworkHooks, JobController
from ..core.workqueue import WorkQueue

_log = logging.getLogger(__name__)


class FrameworkController(FrameworkHooks):
    """One per job kind. Subclasses set kind/container/port constants and
    implement set_cluster_spec / update_job_status / is_master_role.

    Kinds whose CRD carries `spec.tpu` declare which replica types are the
    slice's host pods via `tpu_host_types` (rank order; empty = kind has no
    TPU extension): the gang hooks then provision per-slice all-or-nothing
    PodGroups through controllers/_tpu.py, and set_cluster_spec can inject
    the libtpu identity with self._inject_tpu. JAXJob keeps its own gang
    override (top-level numSlices drives MEGASCALE semantics)."""

    # Replica types that are TPU slice hosts, in rank order. () = none.
    tpu_host_types: tuple = ()

    def gang_group_name(self, job, rtype: str, index: int) -> str:
        if self.tpu_host_types:
            from . import _tpu

            name = _tpu.tpu_gang_group_name(job, self.tpu_host_types, rtype, index)
            if name is not None:
                return name
        return super().gang_group_name(job, rtype, index)

    def gang_groups(self, job, replicas, run_policy):
        if self.tpu_host_types:
            from . import _tpu

            groups = _tpu.tpu_gang_groups(job, replicas, run_policy, self.tpu_host_types)
            if groups is not None:
                return groups
        return super().gang_groups(job, replicas, run_policy)

    def _inject_tpu(self, job, template, replicas, rtype: str, index: int,
                    extra=None) -> None:
        """libtpu identity env + slice provisioning for a host pod; no-op
        without spec.tpu or for CPU replica types."""
        if not self.tpu_host_types:
            return
        from . import _tpu

        _tpu.inject_tpu_env(
            job, template, replicas, self.tpu_host_types, rtype, index,
            self.default_container_name, extra=extra,
        )

    def __init__(
        self,
        cluster: Cluster,
        queue: Optional[WorkQueue] = None,
        options: Optional[EngineOptions] = None,
        clock=time.time,
        metrics=None,
        namespace: str = "",
        limiter: Optional[TokenBucket] = None,
        tracer=None,
        watch_cache=None,
        owns=None,
        admission=None,
    ):
        opts = options or EngineOptions()
        if metrics is None:
            from ..metrics import METRICS

            metrics = METRICS
        self.metrics = metrics
        if tracer is None:
            from ..core.tracing import TRACER

            tracer = TRACER
        self.tracer = tracer
        # Request accounting sits directly over the backend (inside the
        # throttle: a throttled write is still exactly one apiserver
        # request) — every cluster call the controller or engine issues is
        # counted into apiserver_requests_total and attributed to the
        # active job trace. Pure 1:1 pass-through, so fault seams
        # underneath see an unchanged call sequence.
        from ..cluster.accounting import AccountingCluster

        cluster = AccountingCluster(cluster, metrics=metrics, tracer=tracer)
        # ONE client budget per operator process, enforced at the cluster
        # boundary so EVERY write (pods, services, events, status) pays it
        # — reference rest-client semantics. The manager passes a shared
        # bucket; standalone construction builds one from the options.
        if limiter is None and opts.qps > 0:
            limiter = TokenBucket(opts.qps, opts.burst)
        if limiter is not None and limiter.qps > 0:
            from ..cluster.throttled import ThrottledCluster

            cluster = ThrottledCluster(cluster, limiter)
        # Shared watch cache (cluster/watchcache.py), outermost on
        # purpose: a cache-served list/get never reaches the accounting
        # or throttle layers — zero apiserver requests, exactly like an
        # informer read. The manager passes one SharedWatchCache so all
        # framework controllers fan over a single store; standalone
        # construction (tests, benches driving one controller directly)
        # stays uncached unless the caller passes one — the backend's
        # supports_watch_cache capability gates it either way.
        if watch_cache is not None and getattr(
            watch_cache.backend, "supports_watch_cache", False
        ):
            from ..cluster.watchcache import WatchCacheCluster

            cluster = WatchCacheCluster(cluster, watch_cache, self.kind)
        self.cluster = cluster
        # `queue or WorkQueue()` would DROP an injected queue: WorkQueue
        # defines __len__, so an empty (= freshly constructed) queue is
        # falsy and a caller's fake-clock queue is silently replaced.
        self.queue = WorkQueue() if queue is None else queue
        # Namespace scoping (legacy --namespace, options.go:36): empty = all.
        self.namespace = namespace
        # Shard-ownership scoping (core/sharding.py): `owns(ns, name)`
        # answers "does this replica hold the job's shard?". Applied at
        # every enqueue like the namespace scope — an unowned key never
        # enters the queue, so the post-pop gate's hand-back (which
        # re-enqueues THROUGH this filter) cannot spin on keys another
        # replica is reconciling. None (the single-replica default) owns
        # everything: byte-identical to the pre-sharding behavior.
        self.owns = owns
        self.clock = clock
        # Last observed queue wait of THIS worker thread (item, seconds):
        # stashed by the on_wait hook at pop time, consumed by sync() to
        # record the trace's queue.wait span and parent the sync span to
        # it. Thread-local — each pool worker pops its own items.
        self._wait_tls = threading.local()
        self.expectations = ControllerExpectations(
            on_timeout=self._on_expectation_timeout
        )
        # key -> uid of the last job seen at that key, so the sync-path
        # NotFound cleanup can prune UID-keyed terminal-metrics entries even
        # when the DELETED watch event was missed. Bounded by live jobs:
        # pruned in _forget. Lock: _note_uid's read-compare-write runs on
        # every sync WORKER (plus the watch thread via _on_job_event); an
        # unsynchronized interleave across two keys could lose a
        # forget_terminal prune. The lock never wraps cluster I/O.
        self._known_uids: Dict[str, str] = {}
        self._uid_lock = threading.Lock()
        self.engine = JobController(
            hooks=self,
            cluster=self.cluster,
            pod_control=RealPodControl(self.cluster),
            service_control=RealServiceControl(self.cluster),
            expectations=self.expectations,
            options=options,
            requeue=lambda key, after: self.queue.add_after(key, after),
            clock=clock,
            on_job_restarting=self._record_restart,
            on_gang_restart=self._record_gang_restart,
            on_heartbeat_age=self._record_heartbeat_age,
            on_workload_throughput=self._record_workload_throughput,
            on_durable_checkpoint=self._record_durable_checkpoint,
            on_restore_observed=self._record_restore,
            on_force_delete=self._record_force_delete,
            on_fanout_batch=self._record_fanout_batch,
            on_fanout_abort=self._record_fanout_abort,
            on_status_coalesced=self._record_status_coalesced,
            on_status_flush=self._record_status_flush,
            tracer=tracer,
            # Gang admission arbiter (core/admission.py): ONE shared
            # instance per operator, passed by the manager when
            # --enable-gang-admission is on; None (the default) keeps the
            # engine's admission gate a single None-check.
            admission=admission,
        )
        # Queue-wait observer (enqueue -> worker pop), fed straight into
        # the queue_wait histogram; injected custom queues without the
        # hook simply go unobserved.
        if hasattr(self.queue, "on_wait"):
            self.queue.on_wait = self._observe_queue_wait
        self._watch()

    # ---------------------------------------------------------------- glue
    def _watch(self) -> None:
        """Job + dependent (pod/service) watches feeding the workqueue — the
        reference's SetupWithManager watch wiring + expectation-maintaining
        predicates (tfjob_controller.go:163-204, common/util/reconciler.go)."""
        self.cluster.watch(self.kind, self._on_job_event)
        self.cluster.watch("pods", self._on_dependent_event("pods"))
        self.cluster.watch("services", self._on_dependent_event("services"))

    def _in_scope(self, namespace: str, name: str) -> bool:
        """Namespace + shard-ownership scoping, single-sourced for every
        enqueue path (watch events, resync, the post-pop hand-back)."""
        if self.namespace and namespace != self.namespace:
            return False
        return self.owns is None or self.owns(namespace, name)

    def _enqueue(self, namespace: str, name: str) -> None:
        if not self._in_scope(namespace, name):
            return
        self.queue.add(f"{self.kind}:{namespace}/{name}")
        # Depth sampled on ADD as well as on pop (_observe_queue_wait):
        # when every worker is wedged in a long sync, pops stop — exactly
        # the moment a growing backlog must not freeze the gauge at its
        # last popped value.
        self._sample_queue_depth()

    def _enqueue_after(self, namespace: str, name: str, delay: float) -> None:
        """Scoped enqueue with a delay (the periodic resync's jitter path);
        delay<=0 degrades to the immediate _enqueue."""
        if delay <= 0:
            self._enqueue(namespace, name)
            return
        if not self._in_scope(namespace, name):
            return
        self.queue.add_after(f"{self.kind}:{namespace}/{name}", delay)

    def _on_job_event(self, event_type: str, job_dict: dict) -> None:
        meta = job_dict.get("metadata", {})
        namespace = meta.get("namespace", "default")
        name = meta.get("name", "")
        if self.namespace and namespace != self.namespace:
            # Out of scope entirely — a scoped informer would never deliver
            # this event, so neither metrics nor the queue may see it.
            return
        # Shard scoping: every replica's watch sees every event, but only
        # the shard owner counts it — otherwise a fleet of N replicas
        # inflates the created/deleted counters N-fold in aggregation.
        # Deliberate trade-off: an event landing while its shard is
        # mid-migration (owner dead, lease not yet stolen; or draining)
        # is counted by NO replica — undercounting during a failover
        # window is accepted over N-fold steady-state inflation; the
        # claim resync re-covers the WORK either way.
        owned = self.owns is None or self.owns(namespace, name)
        if event_type == ADDED and owned:
            self.metrics.created_inc(namespace, self.kind)
        if event_type == DELETED:
            if owned:
                self.metrics.deleted_inc(namespace, self.kind)
            # The job is gone and is never enqueued again: drop its
            # in-memory bookkeeping HERE — the sync-path NotFound cleanup
            # only runs if some later event enqueues the dead key.
            # Unconditionally: stale per-key state from a PREVIOUS
            # ownership stint must not outlive the job either (forgetting
            # an unowned key is a no-op).
            self._forget(f"{namespace}/{name}", uid=meta.get("uid", ""))
            return
        if meta.get("uid") and owned:
            self._note_uid(f"{namespace}/{name}", meta["uid"])
        self._enqueue(namespace, name)

    def _on_dependent_event(self, dependent_kind: str):
        def handler(event_type: str, obj) -> None:
            if self.namespace and obj.metadata.namespace != self.namespace:
                return
            ref = obj.metadata.controller_ref()
            labels = obj.metadata.labels
            if labels.get(constants.LABEL_GROUP_NAME) != constants.GROUP_NAME:
                return
            if ref is not None and ref.kind != self.kind:
                return
            job_name = labels.get(constants.LABEL_JOB_NAME)
            if not job_name:
                return
            key = f"{obj.metadata.namespace}/{job_name}"
            if event_type == ADDED:
                self.expectations.creation_observed(key, dependent_kind)
            elif event_type == DELETED:
                self.expectations.deletion_observed(key, dependent_kind)
            self._enqueue(obj.metadata.namespace, job_name)

        return handler

    def _note_uid(self, key: str, uid: str) -> None:
        """Remember the uid living at a key; a DIFFERENT uid appearing there
        means the old job was deleted and the name reused — prune the old
        uid's terminal-metrics entries now, since the NotFound sync that
        would have done it can no longer learn the old uid."""
        with self._uid_lock:
            old = self._known_uids.get(key)
            self._known_uids[key] = uid
        if old and old != uid:
            self.metrics.forget_terminal(self.kind, old)

    def _forget(self, key: str, uid: str = "") -> None:
        """Drop every piece of per-job in-memory bookkeeping (expectations,
        the engine's gang-sweep cache, the metrics terminal-dedup entries) —
        one helper so the DELETED-event and NotFound-sync cleanup paths
        cannot drift."""
        self.expectations.delete_expectations(key, "pods")
        self.expectations.delete_expectations(key, "services")
        self.engine.forget_job(key)
        namespace, _, name = key.partition("/")
        self.metrics.clear_heartbeat_age(namespace, self.kind, name)
        self.metrics.clear_workload_tokens_per_sec(namespace, self.kind, name)
        with self._uid_lock:
            uid = uid or self._known_uids.get(key, "")
            self._known_uids.pop(key, None)
        if uid:
            self.metrics.forget_terminal(self.kind, uid)

    def forget_shard(self, shard: int, shard_of) -> None:
        """Shard released (rebalance, resize migration, lost lease):
        drop the per-key in-memory state of every job that just moved
        away — expectations, the engine's gang/heartbeat/status-writer
        caches, the heartbeat-age gauge, the known-uid map. Without
        this, a long-lived replica in a 10k-job fleet accretes state for
        the union of everything it EVER owned, healed only when each job
        is finally deleted. The metrics terminal-dedup entries are
        deliberately KEPT: the DELETED watch event prunes them by uid
        regardless of ownership, and dropping them here would re-count a
        re-claimed job's terminal transition."""
        with self._uid_lock:
            keys = list(self._known_uids)
        for key in keys:
            namespace, _, name = key.partition("/")
            if shard_of(namespace, name) != shard:
                continue
            self.expectations.delete_expectations(key, "pods")
            self.expectations.delete_expectations(key, "services")
            self.engine.forget_job(key)
            self.metrics.clear_heartbeat_age(namespace, self.kind, name)
            self.metrics.clear_workload_tokens_per_sec(namespace, self.kind, name)
            with self._uid_lock:
                self._known_uids.pop(key, None)

    def _record_restart(self, job: JobObject, rtype: str, cause: str) -> None:
        self.metrics.restarted_inc(job.namespace, self.kind)
        self.metrics.restarted_by_cause_inc(job.namespace, self.kind, cause)

    def _record_gang_restart(self, job: JobObject, scope: str,
                             slice_index, cause: str) -> None:
        """One counted gang restart, scope-labeled (slice|world); slice-
        scoped restarts additionally land in the per-slice-index counter
        the flapping alert watches."""
        self.metrics.gang_restart_inc(job.namespace, self.kind, scope, cause)
        if scope == "slice" and slice_index is not None:
            self.metrics.slice_restart_inc(
                job.namespace, self.kind, str(slice_index)
            )

    def _record_heartbeat_age(self, job: JobObject, age: float) -> None:
        self.metrics.set_heartbeat_age(job.namespace, self.kind, job.name, age)

    def _record_workload_throughput(self, job: JobObject, tps) -> None:
        if tps is None:
            # Terminal: drop the series (a finished job has no live
            # throughput; 0.0 would trip low-throughput alerts forever).
            self.metrics.clear_workload_tokens_per_sec(
                job.namespace, self.kind, job.name
            )
            return
        self.metrics.set_workload_tokens_per_sec(
            job.namespace, self.kind, job.name, tps
        )

    def _record_durable_checkpoint(self, job: JobObject, step) -> None:
        if step is None:
            # Terminal: drop the series (the on_workload_throughput rule —
            # a finished job's last durable step is history, not a gate).
            self.metrics.clear_checkpoint_last_durable_step(
                job.namespace, self.kind, job.name
            )
            return
        self.metrics.set_checkpoint_last_durable_step(
            job.namespace, self.kind, job.name, float(step)
        )

    def _record_restore(self, job: JobObject, path: str, cause: str,
                        seconds: float,
                        bytes_moved: "int | None" = None) -> None:
        self.metrics.observe_restore(path, cause, seconds)
        if bytes_moved is not None:
            self.metrics.observe_restore_bytes(path, bytes_moved)

    def _record_force_delete(self, job: JobObject, cause: str) -> None:
        self.metrics.force_delete_inc(job.namespace, self.kind, cause)

    def close(self) -> None:
        """Release the engine's process-lifetime resources (fan-out
        pool). Called by OperatorManager.stop(); long-lived standalone
        controllers in tests may skip it (threads die with the process)."""
        self.engine.close()

    def _record_fanout_batch(self, resource: str, size: int) -> None:
        self.metrics.fanout_batch_inc(self.kind, resource)

    def _record_fanout_abort(self, resource: str) -> None:
        self.metrics.fanout_abort_inc(self.kind, resource)

    def _record_status_coalesced(self, job: JobObject) -> None:
        self.metrics.status_coalesced_inc(job.namespace, self.kind)

    def _record_status_flush(self, job: JobObject, age: float) -> None:
        self.metrics.observe_status_flush_latency(job.namespace, self.kind, age)

    def _observe_queue_wait(self, item: str, seconds: float) -> None:
        self.metrics.observe_queue_wait(self.kind, seconds)
        # Stash for the sync about to run on this same thread: the trace's
        # queue.wait span needs the job UID, which is only known once
        # sync() reads the job back.
        self._wait_tls.last = (item, seconds)
        self._sample_queue_depth()

    def _sample_queue_depth(self) -> None:
        self.metrics.set_workqueue_depth(
            self.kind, self.queue.depth()["queued"]
        )

    def _on_expectation_timeout(self, key: str, kind: str, adds: int, dels: int) -> None:
        """An expectation expired unfulfilled: the watch event we were
        waiting for never arrived and the job sat wedged for the full
        window before self-healing. Counted + evented so chaos tiers (and
        production dashboards) can see dropped-watch incidents instead of
        inferring them from latency."""
        namespace = key.partition("/")[0]
        self.metrics.expectation_timeout_inc(namespace, self.kind, kind)
        record_event_best_effort(
            self.cluster,
            Event(
                type="Warning",
                reason=constants.REASON_EXPECTATION_TIMEOUT,
                message=(
                    f"expectation for {kind} expired unfulfilled "
                    f"(outstanding creates={adds} deletes={dels}); a watch "
                    "event was lost — proceeding on a possibly-stale view"
                ),
                involved_object=f"{self.kind}/{key}",
            ),
        )

    # ------------------------------------------------------------ validate
    def parse_job(self, job_dict: dict) -> JobObject:
        """Convert + default one stored CR. Conversion boundary: ANY failure
        in here means a malformed resource — re-raised as ValidationError so
        sync() marks the job Failed instead of the blanket process_next
        except re-queueing it forever (a hot-looping job that never reports;
        the reference's unstructured-informer path exists for exactly this
        tolerance, issue #561)."""
        cls, set_defaults, _ = KINDS[self.kind]
        try:
            job = cls.parse(job_dict)
            set_defaults(job)
        except ValidationError:
            raise
        except Exception as err:
            raise ValidationError(
                f"malformed {self.kind} resource: {type(err).__name__}: {err}"
            ) from err
        return job

    def validate_job(self, job: JobObject) -> None:
        _, _, validate = KINDS[self.kind]
        try:
            validate(job.spec)
        except ValidationError:
            raise
        except Exception as err:
            # Same conversion boundary as parse_job: a validator tripping
            # over absent structure (null template, etc.) is an invalid spec.
            raise ValidationError(
                f"invalid {self.kind} spec: {type(err).__name__}: {err}"
            ) from err

    # ------------------------------------------------------------- sync
    def sync(self, namespace: str, name: str) -> None:
        """One reconcile of one job key (reference Reconcile,
        tfjob_controller.go:119-160)."""
        try:
            job_dict = self.cluster.get_job(self.kind, namespace, name)
        except NotFound:
            self._forget(f"{namespace}/{name}")
            return
        uid = (job_dict.get("metadata") or {}).get("uid")
        if uid:
            self._note_uid(f"{namespace}/{name}", uid)

        # Trace context: one sync span per reconcile, rooted in the job
        # incarnation's trace and parented to the measured workqueue wait
        # (recorded after the fact — the wait is only known at pop time,
        # the uid only now). Everything the engine does below, cluster
        # writes included (cluster/accounting.py), nests under this span.
        job_trace_key = (self.kind, namespace, name, uid or "")
        wait = getattr(self._wait_tls, "last", None)
        self._wait_tls.last = None
        wait_span = None
        if wait is not None and wait[0] == f"{self.kind}:{namespace}/{name}":
            wait_span = self.tracer.record_span(
                "queue.wait", job=job_trace_key, duration=wait[1],
            )
        with self.tracer.span("sync", job=job_trace_key, parent=wait_span):
            self._sync_traced(namespace, name, job_dict, uid)

    def _sync_traced(self, namespace: str, name: str, job_dict: dict,
                     uid) -> None:
        try:
            job = self.parse_job(job_dict)
            self.validate_job(job)
        except ValidationError as err:
            # Invalid spec: mark Failed on the stored object, don't crash
            # (reference's unstructured-informer tolerance, issue #561).
            self._fail_invalid(job_dict, str(err))
            return

        key = job.key()
        if not (
            self.expectations.satisfied(key, "pods")
            and self.expectations.satisfied(key, "services")
        ):
            # Cache not settled. A watch event normally re-enqueues; also
            # schedule a fallback resync so a dropped event cannot wedge the
            # job past the expectation expiry window. The stuck-terminating
            # escalation must still run HERE: the wedged pod is exactly
            # what keeps the deletion expectation unfulfilled, so an
            # escalation only inside reconcile_job (which this gate blocks)
            # could first fire after the 5-minute expectation expiry.
            self.tracer.event("expectations.pending")
            self.engine.escalate_stuck_terminating(job)
            self.queue.add_after(f"{self.kind}:{key}", 30.0)
            return

        old_conds = {
            c.get("type"): c
            for c in (job_dict.get("status") or {}).get("conditions") or []
            if c.get("status") == "True"
        }
        t0 = time.monotonic()
        self.engine.reconcile_job(job)
        elapsed = time.monotonic() - t0
        # Reference logs per-sync latency ("Finished syncing tfjob %q (%v)",
        # controller.go:306); here it also feeds a histogram.
        self.metrics.observe_reconcile(namespace, self.kind, elapsed)
        _log.debug("Finished syncing %s %r (%.1fms)", self.kind, key, elapsed * 1000)
        self._roll_terminal_metrics(job)
        self._observe_transition_latency(job, old_conds)

    def _fail_invalid(self, job_dict: dict, message: str) -> None:
        from ..api import common as capi

        meta = job_dict.get("metadata", {})
        status = job_dict.get("status") or {}
        job_status = capi.JobStatus(**{})
        conditions = status.get("conditions") or []
        already = any(
            c.get("type") == capi.JOB_FAILED and c.get("status") == capi.CONDITION_TRUE
            for c in conditions
        )
        if already:
            return
        capi.update_job_conditions(
            job_status,
            capi.JOB_FAILED,
            constants.job_reason(self.kind, constants.REASON_FAILED),
            message,
            now=self.clock(),
        )
        from ..api.k8s import to_dict

        new_status = dict(status)
        new_status["conditions"] = conditions + [to_dict(c) for c in job_status.conditions]
        try:
            self.cluster.update_job_status(
                self.kind, meta.get("namespace", "default"), meta.get("name", ""), new_status
            )
        except (NotFound, Conflict):
            # NotFound: the job vanished — nothing to mark. Conflict (a
            # concurrent status writer, or chaos-injected 409): letting it
            # escape to the blanket process_next handler hot-requeued the
            # invalid job forever — the spec cannot become valid by
            # retrying faster. The next sync (watch/resync) re-runs
            # validation and retries the write.
            pass
        record_event_best_effort(
            self.cluster,
            Event(
                type="Warning",
                reason=constants.job_reason(self.kind, constants.REASON_FAILED),
                message=message,
                involved_object=f"{self.kind}/{meta.get('namespace', 'default')}/{meta.get('name', '')}",
            )
        )

    def _observe_transition_latency(self, job: JobObject, old_conds: dict) -> None:
        """Startup p50 / restart MTTR instrumentation (SURVEY.md §7 stage 5:
        the reference has no latency metrics; BASELINE.md names job-startup
        p50 and restart MTTR as numbers this build must establish).

        Fires on the sync that transitions the job to Running: measured from
        the prior Restarting condition (restart MTTR) or from job creation
        (first startup).
        """
        from ..api import common as capi

        run = capi.get_condition(job.status, capi.JOB_RUNNING)
        if run is None or run.status != capi.CONDITION_TRUE:
            return
        if capi.JOB_RUNNING in old_conds:
            return  # already Running before this sync
        now = run.last_transition_time or self.clock()
        restarting = old_conds.get(capi.JOB_RESTARTING)
        if restarting is not None:
            t0 = restarting.get("lastTransitionTime")
            if t0 is not None:
                self.metrics.observe_restart(job.namespace, self.kind, max(0.0, now - t0))
            return
        created = old_conds.get(capi.JOB_CREATED) or {}
        t0 = created.get("lastTransitionTime") or job.metadata.creation_timestamp
        if t0 is not None:
            self.metrics.observe_startup(job.namespace, self.kind, max(0.0, now - t0))

    def _roll_terminal_metrics(self, job: JobObject) -> None:
        from ..api import common as capi

        # Count each terminal transition once: reconcile_job set the condition
        # this sync iff last_transition moved; cheap approximation — guard via
        # metrics' dedup of (kind, key, condition).
        if capi.is_succeeded(job.status):
            self.metrics.successful_inc_once(job.namespace, self.kind, job.metadata.uid)
        elif capi.is_failed(job.status):
            self.metrics.failed_inc_once(job.namespace, self.kind, job.metadata.uid)

    # ------------------------------------------------------------ run loop
    def process_next(self, timeout: float = 0.1, gate=None) -> bool:
        """Drain one item; the reference's processNextWorkItem
        (controller.go:230-286). Safe for N concurrent workers: the
        queue's processing/dirty sets guarantee no two workers ever hold
        the same item, so per-job state stays single-threaded while
        different jobs sync in parallel.

        `gate` (the manager's leadership flag, or the per-key shard-
        ownership check — it receives the popped item) is re-checked
        AFTER the pop: a worker blocked in queue.get() when leadership
        flips would otherwise sync an item popped seconds into its
        standby — the checked-then-blocked race that lets a demoted
        operator write beside the new leader. A gated-out item is handed
        back unsynced THROUGH the enqueue scope filter: under global
        election the key re-queues for when leadership returns; under
        sharding a key whose shard moved away is dropped here — the new
        owner's claim resync re-enqueues it on ITS queue, while re-adding
        locally would spin pop/gate/re-add forever."""
        item = self.queue.get(timeout=timeout)
        if item is None:
            return False
        if gate is not None and not gate(item):
            self.queue.done(item)
            namespace, _, name = item.partition(":")[2].partition("/")
            self._enqueue(namespace, name)
            return False
        # Busy-worker gauge (client-go workqueue "busy workers" parity):
        # bracketed around the sync so saturation — every worker inside a
        # reconcile while the queue grows — is directly observable.
        self.metrics.busy_workers_inc(self.kind)
        try:
            kind, _, key = item.partition(":")
            if kind != self.kind:
                return True
            namespace, _, name = key.partition("/")
            self.sync(namespace, name)
            self.queue.forget(item)
        except Exception as err:
            # The requeue itself stays (the rate-limited queue is the
            # recovery mechanism), but the failure must be VISIBLE: a
            # counter chaos tiers and dashboards can watch for
            # error-requeue storms, plus a log line naming the exception —
            # previously this swallowed every sync failure silently. The
            # namespace label keeps a storm attributable when N workers
            # surface interleaved failures from different tenants.
            namespace = item.partition(":")[2].partition("/")[0]
            self.metrics.sync_error_inc(namespace, self.kind, type(err).__name__)
            _log.warning(
                "sync of %s failed (%s: %s); rate-limited requeue",
                item, type(err).__name__, err, exc_info=True,
            )
            self.queue.add_rate_limited(item)
        finally:
            self.metrics.busy_workers_dec(self.kind)
            self.queue.done(item)
        return True

    def run_until_idle(self, max_iterations: int = 10_000) -> None:
        """Synchronously drain the queue (test/e2e harness helper)."""
        for _ in range(max_iterations):
            if self.queue.empty_and_idle():
                return
            self.process_next(timeout=0.01)
