"""Framework controllers + registry.

Mirrors pkg/controller.v1 in the reference: one controller per job kind,
registered in a kind -> factory map (register_controller.go:37-50).
"""

from typing import Callable, Dict

# kind -> factory(cluster, **kwargs) -> FrameworkController; populated by
# each controller module at import time via `register`.
SUPPORTED_CONTROLLERS: Dict[str, Callable] = {}


def register(kind: str):
    def wrap(factory):
        SUPPORTED_CONTROLLERS[kind] = factory
        return factory

    return wrap


def enabled_kinds(names=None):
    """reference EnabledSchemes.FillAll/Set (register_controller.go:52-77)."""
    if not names:
        return list(SUPPORTED_CONTROLLERS)
    unknown = [n for n in names if n not in SUPPORTED_CONTROLLERS]
    if unknown:
        raise ValueError(f"unsupported kind(s) {unknown}; supported: {list(SUPPORTED_CONTROLLERS)}")
    return list(names)


def _load_all():
    from . import jax, mxnet, pytorch, tensorflow, xgboost  # noqa: F401


_load_all()
