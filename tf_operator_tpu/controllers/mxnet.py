"""MXJob controller.

Reference parity: pkg/controller.v1/mxnet/mxjob_controller.go — DMLC env
injection (mxnet.go SetPodEnv incl. BytePS worker ids and TVM tuner labels)
and scheduler-keyed status for train mode (UpdateJobStatus :340-420).
"""

from __future__ import annotations

from typing import Dict, List

from ..api import mxjob as mxapi
from ..api.common import JobStatus, ReplicaSpec
from ..bootstrap import dmlc
from . import register
from ._master_status import update_master_based_status
from .base import FrameworkController


@register(mxapi.KIND)
class MXController(FrameworkController):
    kind = mxapi.KIND
    default_container_name = mxapi.DEFAULT_CONTAINER_NAME
    default_port_name = mxapi.DEFAULT_PORT_NAME
    default_port = mxapi.DEFAULT_PORT
    # Worker pods are the TPU slice hosts; Scheduler/Server stay CPU pods.
    tpu_host_types = (mxapi.REPLICA_TYPE_WORKER,)

    def set_cluster_spec(self, job, template, rtype: str, index: int) -> None:
        env = dmlc.gen_env(job, rtype, index)
        for container in template.spec.containers:
            for name, value in env.items():
                if container.get_env(name) is None:
                    container.set_env(name, value)
        self._inject_tpu(job, template, job.spec.mx_replica_specs, rtype, index)

    def _completion_key(self, replicas: Dict[str, ReplicaSpec]) -> str:
        """Train mode completes on the Scheduler; TVM tune mode on the
        TunerTracker; fall back to Worker."""
        for rt in (
            mxapi.REPLICA_TYPE_SCHEDULER,
            mxapi.REPLICA_TYPE_TUNER_TRACKER,
            mxapi.REPLICA_TYPE_WORKER,
        ):
            if rt in replicas:
                return rt
        return next(iter(replicas), mxapi.REPLICA_TYPE_WORKER)

    def is_master_role(self, replicas: Dict[str, ReplicaSpec], rtype: str, index: int) -> bool:
        """reference mxjob_controller.go:449-452 (scheduler is master)"""
        return rtype == mxapi.REPLICA_TYPE_SCHEDULER

    def replica_order(self, replicas: Dict[str, ReplicaSpec]) -> List[str]:
        order = [
            mxapi.REPLICA_TYPE_SCHEDULER,
            mxapi.REPLICA_TYPE_TUNER_TRACKER,
            mxapi.REPLICA_TYPE_SERVER,
            mxapi.REPLICA_TYPE_TUNER_SERVER,
            mxapi.REPLICA_TYPE_WORKER,
            mxapi.REPLICA_TYPE_TUNER,
        ]
        return [rt for rt in order if rt in replicas] + [
            rt for rt in sorted(replicas) if rt not in order
        ]

    def update_job_status(
        self, job, replicas: Dict[str, ReplicaSpec], job_status: JobStatus, pods
    ) -> None:
        update_master_based_status(
            self, job, replicas, job_status, self._completion_key(replicas)
        )
