"""JAXJob controller — the TPU-native path (no reference counterpart;
SURVEY.md §7 stages 2 and 5).

Provisions TPU pod-slices as all-or-nothing gangs: each worker pod requests
its slice share of chips (google.com/tpu) and carries GKE TPU node selectors;
pods of one slice form one gang (minMember = hosts per slice), so a
multislice job's free slice can start while another queues. Env injection is
the JAX/libtpu rendezvous contract (bootstrap/jaxdist.py).

Status: SPMD jobs live and die together — Succeeded when ALL workers
succeed; Running while any runs; retryable exits (preemption/maintenance,
128+) restart via the engine's ExitCode handling.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

from ..api import common as capi
from ..api import jaxjob as jaxapi
from ..api.common import JobStatus, ReplicaSpec
from ..api.k8s import Event
from ..bootstrap import jaxdist
from ..core import constants
from ..core.control import record_event_best_effort
from . import register
from .base import FrameworkController

# The slice-provisioning mechanics (GKE selectors, chip resources, naming)
# are shared with the TPU-extended GPU-era kinds — controllers/_tpu.py.
from ._tpu import TPU_RESOURCE, gke_accelerator_name  # noqa: F401 (re-export)
from . import _tpu


@register(jaxapi.KIND)
class JAXController(FrameworkController):
    kind = jaxapi.KIND
    default_container_name = jaxapi.DEFAULT_CONTAINER_NAME
    default_port_name = jaxapi.DEFAULT_PORT_NAME
    default_port = jaxapi.DEFAULT_PORT

    # ------------------------------------------------------------ pod spec
    def set_cluster_spec(self, job, template, rtype: str, index: int) -> None:
        env = jaxdist.gen_env(job, rtype, index)
        for container in template.spec.containers:
            for name, value in env.items():
                if container.get_env(name) is None:
                    container.set_env(name, value)
        # World stamp: lets stale_world_pods detect pods whose injected env
        # predates a resize (elastic slice membership — coordinated re-init).
        template.metadata.labels[constants.LABEL_WORLD_GENERATION] = (
            jaxdist.world_generation(job)
        )
        # Slice stamp on every WORKER pod (not just spec.tpu ones, which
        # attach_tpu_to_template already stamps identically): the
        # slice-scoped failure-domain machinery, chaos slice selectors,
        # and dashboards all key on it — a CPU e2e multislice world must
        # carry the same per-slice identity a real pod-slice does.
        if rtype == jaxapi.REPLICA_TYPE_WORKER:
            per_slice = jaxdist.hosts_per_slice(job)
            template.metadata.labels[constants.LABEL_SLICE_INDEX] = str(
                min(index // max(1, per_slice), max(1, job.spec.num_slices) - 1)
            )
        self._attach_tpu_resources(job, template, rtype, index)

    def restart_peers_on_failure(self, rtype: str) -> bool:
        """SPMD gang restart (GKE multislice / JobSet semantics): a
        jax.distributed world cannot re-admit a single restarted process —
        the coordinator's membership is fixed at initialize() — so a
        retryable worker failure restarts every worker in one batched sync
        and the world re-rendezvouses from the shared checkpoint. The
        GPU-era reference restarts only the failed replica
        (tfjob_controller.go:717-736), which is right for PS worlds and
        wrong for SPMD ones."""
        return rtype == jaxapi.REPLICA_TYPE_WORKER

    def stale_world_pods(self, job, replicas, pods) -> List:
        """Elastic resize: any pod stamped with a different world generation
        must be recreated — SPMD membership is global, so the whole job
        restarts as one gang and resumes from its checkpoint (the operator's
        obligation is stable identity + batched recreation; persistence is
        the workload's, via orbax — SURVEY.md §5.4).

        Restart applies to EVERY JAXJob, elastic or not — k8s convergence
        semantics: editing the spec of a running workload changes the
        workload (a StatefulSet template edit rolls its pods the same way).
        The alternative (leaving old pods on the old env while scale-ups or
        crash-recreations get the new one) yields a mixed-world gang that
        hangs at rendezvous — a silent waste of the slice, strictly worse
        than the visible restart. `spec.elastic` is the contract for
        *intentional* resize: it bounds numSlices in validation and gates
        the SDK scale() verb; fat-fingered patches are caught client-side
        (SDK pre-validation) and by CRD schema, not by the controller
        ignoring desired state."""
        current = jaxdist.world_generation(job)
        # A pod with no stamp (created by an older operator) is stale too:
        # its world is unknowable beside freshly-stamped peers. Pods already
        # terminating are skipped so async-deleting backends don't re-delete
        # and re-emit Restarting every sync until deletions land.
        job.status.world_generation = current
        return [
            p
            for p in pods
            if p.metadata.deletion_timestamp is None
            and p.metadata.labels.get(constants.LABEL_WORLD_GENERATION) != current
        ]

    def _attach_tpu_resources(self, job, template, rtype: str, index: int) -> None:
        tpu = job.spec.tpu
        if tpu is None or rtype != jaxapi.REPLICA_TYPE_WORKER:
            # Out-of-world replicas (Evaluator) never claim slice chips: the
            # slice is exactly worker-shaped, and an extra chip ask would
            # make every gang reservation unschedulable.
            return
        per_slice = jaxdist.hosts_per_slice(job)
        _tpu.attach_tpu_to_template(
            tpu, template, index // per_slice, self.default_container_name
        )

    def slice_topology(self, job, replicas):
        """Slice-indexed restart domains (core/job_controller.py
        SliceTopology): one domain per DCN-connected slice, so a
        retryable loss in slice s tears down slice s's pods only — the
        surviving slices' per-slice ICI meshes are untouched and the
        recreated slice re-rendezvouses through the stable worker-0
        coordinator service. Single-slice jobs return None: the flat
        whole-world restart path, byte-identical to before."""
        num_slices = max(1, job.spec.num_slices)
        if num_slices <= 1:
            return None
        from ..core.job_controller import SliceTopology

        return SliceTopology(
            num_slices=num_slices,
            hosts_per_slice=jaxdist.hosts_per_slice(job),
            min_slices=job.spec.min_slices,
        )

    # ---------------------------------------------------------------- gang
    def gang_group_name(self, job, rtype: str, index: int) -> str:
        per_slice = jaxdist.hosts_per_slice(job)
        if rtype != jaxapi.REPLICA_TYPE_WORKER:
            # Auxiliary pods spread round-robin across the slice gangs,
            # matching gang_groups' ceil-division accounting of their
            # replica counts.
            num_slices = max(1, job.spec.num_slices)
            return f"{job.name}-slice-{index % num_slices}"
        return f"{job.name}-slice-{index // per_slice}"

    def gang_groups(self, job, replicas: Dict[str, ReplicaSpec], run_policy) -> List[dict]:
        """One gang per slice: minMember = hosts per slice (a partial slice
        is useless; an independent slice is not)."""
        from ..core.job_controller import (
            aggregate_min_resources,
            gang_owner_ref,
            job_selector,
        )

        per_slice = jaxdist.hosts_per_slice(job)
        num_slices = max(1, job.spec.num_slices)
        sp = run_policy.scheduling_policy
        # Per-slice capacity: one slice's share of the worker topology (the
        # scheduler must be able to reserve a whole slice, not the whole
        # multislice job, for a free slice to start independently). Only the
        # Worker type is slice-shaped (per_slice hosts each); auxiliary
        # types (Evaluator) land round-robin across slices
        # (gang_group_name: index % num_slices), so slice s's EXACT share
        # is ceil((replicas - s) / num_slices) — a flat ceil for every
        # slice would reserve auxiliary capacity in gangs that will never
        # receive an auxiliary pod, wedging them on tight clusters.
        def slice_min_resources(s: int) -> dict:
            if sp is not None and sp.min_resources:
                return dict(sp.min_resources)
            slice_replicas = {
                rtype: dataclasses.replace(
                    spec,
                    replicas=(
                        per_slice if rtype == jaxapi.REPLICA_TYPE_WORKER
                        else max(0, -(-((spec.replicas or 0) - s) // num_slices))
                    ),
                )
                for rtype, spec in replicas.items()
            }
            resources = aggregate_min_resources(slice_replicas)
            # The per-pod chip ask is injected at pod-creation time (mutate
            # hook), so the template aggregation misses it — add the slice's
            # chips explicitly: hosts/slice x chips/host.
            from ..api import tpu as tpuapi

            chips = tpuapi.per_host_chips(job.spec.tpu) if job.spec.tpu else None
            if chips:
                resources.setdefault(TPU_RESOURCE, str(per_slice * chips))
            return resources

        groups = []
        for s in range(num_slices):
            min_resources = slice_min_resources(s)
            groups.append(
                {
                    "apiVersion": "scheduling.volcano.sh/v1beta1",
                    "kind": "PodGroup",
                    "metadata": {
                        "name": f"{job.name}-slice-{s}",
                        "namespace": job.namespace,
                        "labels": job_selector(job),
                        "ownerReferences": [gang_owner_ref(job)],
                    },
                    "spec": {
                        "minMember": per_slice,
                        "minResources": min_resources,
                        "queue": sp.queue if sp else "",
                        "priorityClassName": sp.priority_class if sp else "",
                    },
                }
            )
        return groups

    # -------------------------------------------------------------- status
    def is_master_role(self, replicas: Dict[str, ReplicaSpec], rtype: str, index: int) -> bool:
        """Worker-0 hosts the jax.distributed coordinator."""
        return rtype == jaxapi.REPLICA_TYPE_WORKER and index == 0

    def update_job_status(
        self, job, replicas: Dict[str, ReplicaSpec], job_status: JobStatus, pods
    ) -> None:
        now = self.clock()
        restarting = getattr(job_status, "_restarting_this_sync", False)
        if job_status.start_time is None:
            job_status.start_time = now

        spec = replicas.get(jaxapi.REPLICA_TYPE_WORKER)
        status = job_status.replica_statuses.get(jaxapi.REPLICA_TYPE_WORKER)
        if spec is None or status is None:
            return
        expected = (spec.replicas or 0) - status.succeeded

        # Permanent failures are checked BEFORE the success branch: when the
        # last worker's Succeeded and an evaluator's permanent Failed land
        # in the same sync, Failed must win — the documented contract is
        # that an evaluator's permanent failure fails the job. Suppress only
        # for the sync that initiated a retryable restart; a stale
        # Restarting condition must not mask a permanent failure of the
        # recreated pod (it would wedge the job forever). Evaluator
        # failures count too (reference semantics: any replica type's
        # permanent failure fails the job, tfjob_controller.go) — but
        # evaluators never gate success below: the SPMD world completing is
        # the job completing.
        failed_by_type = {
            rt: st.failed
            for rt, st in job_status.replica_statuses.items()
            if st.failed > 0
        }
        if failed_by_type and not restarting:
            detail = ", ".join(
                f"{n} {rt}" for rt, n in sorted(failed_by_type.items())
            )
            msg = (
                f"JAXJob {job.key()} has failed because {detail} "
                "replica(s) failed."
            )
            if job_status.completion_time is None:
                job_status.completion_time = now
            capi.update_job_conditions(
                job_status,
                capi.JOB_FAILED,
                constants.job_reason(self.kind, constants.REASON_FAILED),
                msg,
                now=now,
            )
            record_event_best_effort(
                self.cluster,
                Event(
                    type="Normal",
                    reason=constants.job_reason(self.kind, constants.REASON_FAILED),
                    message=msg,
                    involved_object=f"{job.kind}/{job.key()}",
                )
            )
            return

        if expected == 0:
            # SPMD: every process ran the same program to completion.
            msg = f"JAXJob {job.key()} successfully completed."
            if job_status.completion_time is None:
                job_status.completion_time = now
            capi.update_job_conditions(
                job_status,
                capi.JOB_SUCCEEDED,
                constants.job_reason(self.kind, constants.REASON_SUCCEEDED),
                msg,
                now=now,
            )
            record_event_best_effort(
                self.cluster,
                Event(
                    type="Normal",
                    reason=constants.job_reason(self.kind, constants.REASON_SUCCEEDED),
                    message=msg,
                    involved_object=f"{job.kind}/{job.key()}",
                )
            )
            return

        if status.active > 0 and not restarting:
            capi.update_job_conditions(
                job_status,
                capi.JOB_RUNNING,
                constants.job_reason(self.kind, constants.REASON_RUNNING),
                f"JAXJob {job.key()} is running.",
                now=now,
            )
