"""Shared TPU pod-slice provisioning — used by every kind with `spec.tpu`.

One implementation of: GKE node selectors + chip resources on host pods,
slice-membership labels, libtpu identity env (TPU_WORKER_ID /
TPU_WORKER_HOSTNAMES), per-slice all-or-nothing gangs, and the per-kind
accelerator env (TPUStrategy-compatible for TF, PJRT/XLA for PyTorch).

JAXController grew this first (controllers/jax.py); the north star extends
the same provisioning to TFJob/PyTorchJob/MXJob (reference env-injection
anchor: tensorflow.go:97-173), so the mechanics live here once.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from ..api import tpu as tpuapi
from ..api.common import ReplicaSpec
from ..bootstrap.tf_config import replica_service_host
from ..core import constants

# GKE TPU node-selector label keys.
NODE_SELECTOR_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
NODE_SELECTOR_TOPOLOGY = "cloud.google.com/gke-tpu-topology"
TPU_RESOURCE = "google.com/tpu"

# Marketing/GKE accelerator naming: v5e is "tpu-v5-lite-podslice".
_GKE_ACCELERATOR_NAMES = {
    "v4": "tpu-v4-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "v6e": "tpu-v6e-slice",
}


def gke_accelerator_name(accelerator_type: str) -> str:
    family = accelerator_type.split("-")[0]
    return _GKE_ACCELERATOR_NAMES.get(family, family)


def tpu_of(job) -> Optional[tpuapi.TPUSpec]:
    return getattr(job.spec, "tpu", None)


def host_rank(
    replicas: Dict[str, ReplicaSpec],
    host_types: Sequence[str],
    rtype: str,
    index: int,
) -> Optional[int]:
    """Global TPU-host ordinal of (rtype, index) across the job's host
    replica groups in declared rank order (e.g. Master before Worker for
    PyTorch — rank 0 is the master host); None for non-host types (PS,
    Chief, Evaluator, Scheduler, Server — CPU pods)."""
    if rtype not in host_types:
        return None
    rank = 0
    for t in host_types:
        if t == rtype:
            return rank + index
        spec = replicas.get(t)
        rank += (spec.replicas or 0) if spec else 0
    return None


def host_service_names(
    job, replicas: Dict[str, ReplicaSpec], host_types: Sequence[str]
) -> List[str]:
    """Headless-service DNS names of every TPU host pod, in rank order —
    the TPU_WORKER_HOSTNAMES libtpu uses to form the ICI mesh."""
    names = []
    for t in host_types:
        spec = replicas.get(t)
        for i in range(spec.replicas or 0 if spec else 0):
            names.append(replica_service_host(job.name, job.namespace, t.lower(), i))
    return names


def attach_tpu_to_template(
    tpu: tpuapi.TPUSpec, template, slice_index: int, container_name: str
) -> None:
    """Node selectors, slice label, annotations, and the per-pod chip ask.
    Values already present (user-set) are never overwritten."""
    template.metadata.labels[constants.LABEL_SLICE_INDEX] = str(slice_index)
    template.metadata.annotations[constants.ANNOTATION_TPU_ACCELERATOR] = (
        tpu.accelerator_type
    )
    if tpu.topology:
        template.metadata.annotations[constants.ANNOTATION_TPU_TOPOLOGY] = tpu.topology
    if tpu.accelerator_type:
        template.spec.node_selector.setdefault(
            NODE_SELECTOR_ACCELERATOR, gke_accelerator_name(tpu.accelerator_type)
        )
    if tpu.topology:
        template.spec.node_selector.setdefault(NODE_SELECTOR_TOPOLOGY, tpu.topology)
    chips = tpuapi.per_host_chips(tpu)
    if chips:
        for container in template.spec.containers:
            if container.name == container_name:
                limits = container.resources.setdefault("limits", {})
                limits.setdefault(TPU_RESOURCE, str(chips))
                requests = container.resources.setdefault("requests", {})
                requests.setdefault(TPU_RESOURCE, str(chips))


def libtpu_identity_env(
    tpu: tpuapi.TPUSpec, rank: int, hostnames: List[str], hosts_per_slice: int
) -> Dict[str, str]:
    """The libtpu host-identity contract, slice-local: worker id within the
    slice plus the slice's member hostnames (libtpu forms the ICI mesh from
    them). Shared by JAX, TF (TPUStrategy reads the same libtpu layer), and
    torch_xla (PJRT on TPU)."""
    per_slice = max(1, hosts_per_slice)
    slice_index = rank // per_slice
    env = {
        "TPU_WORKER_ID": str(rank % per_slice),
        "TPU_WORKER_HOSTNAMES": ",".join(
            hostnames[slice_index * per_slice:(slice_index + 1) * per_slice]
        ),
    }
    if tpu.accelerator_type:
        env["TPU_ACCELERATOR_TYPE"] = tpu.accelerator_type
    if tpu.topology:
        env["TPU_TOPOLOGY"] = tpu.topology
    chips = tpuapi.per_host_chips(tpu)
    if chips:
        # Per-chip launchers (torch_xla xmp.spawn) size their local fan-out
        # from this rather than probing the runtime pre-fork.
        env["TPU_CHIPS_PER_HOST"] = str(chips)
    return env


def inject_tpu_env(
    job,
    template,
    replicas: Dict[str, ReplicaSpec],
    host_types: Sequence[str],
    rtype: str,
    index: int,
    container_name: str,
    extra: Optional[Dict[str, str]] = None,
) -> None:
    """Inject the libtpu identity (+ per-kind `extra`) into a host pod's
    containers and attach the slice provisioning (selectors/chips); no-op
    for CPU replica types or jobs without spec.tpu."""
    tpu = tpu_of(job)
    if tpu is None:
        return
    rank = host_rank(replicas, host_types, rtype, index)
    if rank is None:
        return
    hostnames = host_service_names(job, replicas, host_types)
    hosts = tpuapi.hosts_for(tpu) or max(
        1, len(hostnames) // max(1, tpu.num_slices)
    )
    env = libtpu_identity_env(tpu, rank, hostnames, hosts)
    if extra:
        env.update(extra)
    for container in template.spec.containers:
        for name, value in env.items():
            if container.get_env(name) is None:
                container.set_env(name, value)
    attach_tpu_to_template(tpu, template, rank // max(1, hosts), container_name)


def tpu_gang_groups(
    job,
    replicas: Dict[str, ReplicaSpec],
    run_policy,
    host_types: Sequence[str],
) -> Optional[List[dict]]:
    """Per-slice all-or-nothing PodGroups for a job with spec.tpu — the
    TFJob/PyTorchJob/MXJob analog of JAXController.gang_groups: minMember =
    this slice's host count (+ the job's CPU pods, which ride with slice 0),
    minResources includes the slice's chips (injected per-pod at creation,
    so template aggregation alone misses them). Returns None when the job
    has no spec.tpu (caller falls back to the generic single gang)."""
    from ..core.job_controller import (
        aggregate_min_resources,
        gang_owner_ref,
        job_selector,
    )

    tpu = tpu_of(job)
    if tpu is None:
        return None
    num_slices = max(1, tpu.num_slices)
    total_hosts = sum(
        (replicas.get(t).replicas or 0) if replicas.get(t) else 0
        for t in host_types
    )
    per_slice = tpuapi.hosts_for(tpu) or max(1, total_hosts // num_slices)
    chips = tpuapi.per_host_chips(tpu)
    sp = run_policy.scheduling_policy

    # Host ranks are assigned by host_rank() in host_types order; slice s
    # owns ranks [s*per_slice, (s+1)*per_slice). Per-type membership in a
    # slice is therefore the overlap of the type's rank range with the
    # slice's — keeping gang membership (tpu_gang_group_name) and gang
    # accounting (minMember/minResources) consistent by construction.
    type_ranges = {}
    offset = 0
    for t in host_types:
        spec = replicas.get(t)
        n = (spec.replicas or 0) if spec else 0
        type_ranges[t] = (offset, offset + n)
        offset += n

    groups = []
    for s in range(num_slices):
        lo, hi = s * per_slice, (s + 1) * per_slice
        slice_replicas = {}
        for rtype, spec in replicas.items():
            if rtype in host_types:
                t_lo, t_hi = type_ranges[rtype]
                n = max(0, min(hi, t_hi) - max(lo, t_lo))
            else:
                # CPU pods (PS, Chief, Evaluator, ...) gang with slice 0:
                # the job cannot run without them, and a multislice job's
                # later slices must not each re-reserve them.
                n = (spec.replicas or 0) if s == 0 else 0
            slice_replicas[rtype] = dataclasses.replace(spec, replicas=n)
        min_member = sum(r.replicas or 0 for r in slice_replicas.values())
        min_resources = (
            dict(sp.min_resources) if sp is not None and sp.min_resources
            else aggregate_min_resources(slice_replicas)
        )
        if (sp is None or not sp.min_resources) and chips:
            slice_hosts = sum(
                slice_replicas[t].replicas or 0
                for t in host_types
                if t in slice_replicas
            )
            if slice_hosts:
                min_resources.setdefault(TPU_RESOURCE, str(slice_hosts * chips))
        name = job.name if num_slices == 1 else f"{job.name}-slice-{s}"
        groups.append({
            "apiVersion": "scheduling.volcano.sh/v1beta1",
            "kind": "PodGroup",
            "metadata": {
                "name": name,
                "namespace": job.namespace,
                "labels": job_selector(job),
                "ownerReferences": [gang_owner_ref(job)],
            },
            "spec": {
                "minMember": min_member,
                "minResources": min_resources,
                "queue": sp.queue if sp else "",
                "priorityClassName": sp.priority_class if sp else "",
            },
        })
    return groups


def tpu_gang_group_name(job, host_types, rtype: str, index: int) -> Optional[str]:
    """Which slice gang a pod belongs to (None = no spec.tpu, use the
    generic job gang). CPU pods and slice-0 hosts share the base group."""
    tpu = tpu_of(job)
    if tpu is None:
        return None
    replicas = job.replica_specs()
    num_slices = max(1, tpu.num_slices)
    if num_slices == 1:
        return job.name
    rank = host_rank(replicas, host_types, rtype, index)
    if rank is None:
        return f"{job.name}-slice-0"
    total_hosts = sum(
        (replicas.get(t).replicas or 0) if replicas.get(t) else 0
        for t in host_types
    )
    per_slice = tpuapi.hosts_for(tpu) or max(1, total_hosts // num_slices)
    return f"{job.name}-slice-{min(rank // per_slice, num_slices - 1)}"
