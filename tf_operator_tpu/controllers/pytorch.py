"""PyTorchJob controller.

Reference parity: pkg/controller.v1/pytorch/pytorchjob_controller.go —
c10d env injection (pytorch.go SetPodEnv) and master-based status
(UpdateJobStatus :317-399). Uses the engine's generic ReconcilePods (the
reference's PyTorch controller does not override it either).

Divergence (deliberate): a permanent exit code under ExitCode restart policy
fails the job instead of leaving a stale Restarting condition (upstream sets
Restarting for any failure under ExitCode, even unretryable ones).
"""

from __future__ import annotations

from typing import Dict, List

from ..api import pytorchjob as ptapi
from ..api.common import JobStatus, ReplicaSpec
from ..bootstrap import c10d
from . import register
from ._master_status import update_master_based_status
from .base import FrameworkController


@register(ptapi.KIND)
class PyTorchController(FrameworkController):
    kind = ptapi.KIND
    default_container_name = ptapi.DEFAULT_CONTAINER_NAME
    default_port_name = ptapi.DEFAULT_PORT_NAME
    default_port = ptapi.DEFAULT_PORT
    # Master + Workers together are the slice's host pods (master = rank 0
    # host — PJRT/XLA on TPU has no CPU-only coordinator role).
    tpu_host_types = (ptapi.REPLICA_TYPE_MASTER, ptapi.REPLICA_TYPE_WORKER)

    def set_cluster_spec(self, job, template, rtype: str, index: int) -> None:
        env = c10d.gen_env(job, rtype, index)
        for container in template.spec.containers:
            for name, value in env.items():
                if container.get_env(name) is None:
                    container.set_env(name, value)
        # spec.tpu: every host pod also gets the libtpu identity plus the
        # torch_xla PJRT contract (PJRT_DEVICE=TPU) and slice provisioning.
        self._inject_tpu(
            job, template, job.spec.pytorch_replica_specs, rtype, index,
            extra={"PJRT_DEVICE": "TPU"},
        )

    def is_master_role(self, replicas: Dict[str, ReplicaSpec], rtype: str, index: int) -> bool:
        return rtype == ptapi.REPLICA_TYPE_MASTER

    def replica_order(self, replicas: Dict[str, ReplicaSpec]) -> List[str]:
        order = [ptapi.REPLICA_TYPE_MASTER, ptapi.REPLICA_TYPE_WORKER]
        return [rt for rt in order if rt in replicas] + [
            rt for rt in sorted(replicas) if rt not in order
        ]

    def update_job_status(
        self, job, replicas: Dict[str, ReplicaSpec], job_status: JobStatus, pods
    ) -> None:
        update_master_based_status(self, job, replicas, job_status, ptapi.REPLICA_TYPE_MASTER)
