"""TFJob controller.

Reference parity: pkg/controller.v1/tensorflow/tfjob_controller.go —
TF_CONFIG injection (SetClusterSpec :542-575), master-role selection
(:588-595), and the TF status state machine (UpdateJobStatus :353-510):
chief/master presence drives completion, otherwise worker-0 (or all workers
under SuccessPolicyAllWorkers), Restarting suppresses Failed.
"""

from __future__ import annotations

from typing import Dict, List

from ..api import common as capi
from ..api import tfjob as tfapi
from ..api.common import JobStatus, ReplicaSpec
from ..api.k8s import POD_SUCCEEDED, Event
from ..bootstrap import tf_config
from ..core import constants
from ..core.control import record_event_best_effort
from ..core.job_controller import (
    filter_pods_for_replica_type,
    get_container_exit_code,
    get_pod_slices,
)
from . import register
from .base import FrameworkController


def contain_chief_or_master_spec(replicas: Dict[str, ReplicaSpec]) -> bool:
    return any(tfapi.is_chief_or_master(rt) for rt in replicas)


@register(tfapi.KIND)
class TFController(FrameworkController):
    kind = tfapi.KIND
    default_container_name = tfapi.DEFAULT_CONTAINER_NAME
    default_port_name = tfapi.DEFAULT_PORT_NAME
    default_port = tfapi.DEFAULT_PORT
    # Worker pods are the TPU slice hosts; Chief/Master/Evaluator stay CPU
    # coordinators (PS is rejected with spec.tpu at validation).
    tpu_host_types = (tfapi.REPLICA_TYPE_WORKER,)

    # ----------------------------------------------------------- env spec
    def set_cluster_spec(self, job, template, rtype: str, index: int) -> None:
        """Inject TF_CONFIG into every container of the template
        (reference SetClusterSpec tfjob_controller.go:542-575). Single-process
        jobs get none (isDistributed, pod.go:296-319). With spec.tpu, worker
        pods additionally get the libtpu identity env (TPUStrategy reads the
        same libtpu layer JAX does) and the slice provisioning."""
        if tf_config.is_distributed(job):
            config = tf_config.gen_tf_config(job, rtype, index)
            for container in template.spec.containers:
                if container.get_env("TF_CONFIG") is None:
                    container.set_env("TF_CONFIG", config)
        self._inject_tpu(job, template, job.spec.tf_replica_specs, rtype, index)

    # -------------------------------------------------------- master role
    def is_master_role(self, replicas: Dict[str, ReplicaSpec], rtype: str, index: int) -> bool:
        """Chief/Master replica if declared, else worker-0
        (reference IsMasterRole tfjob_controller.go:588-595)."""
        if contain_chief_or_master_spec(replicas):
            return tfapi.is_chief_or_master(rtype)
        return rtype == tfapi.REPLICA_TYPE_WORKER and index == 0

    def replica_order(self, replicas: Dict[str, ReplicaSpec]) -> List[str]:
        """Fixed precedence order (reference tfjob_controller.go:385-391)."""
        order = [
            tfapi.REPLICA_TYPE_CHIEF,
            tfapi.REPLICA_TYPE_EVAL,
            tfapi.REPLICA_TYPE_MASTER,
            tfapi.REPLICA_TYPE_PS,
            tfapi.REPLICA_TYPE_WORKER,
        ]
        return [rt for rt in order if rt in replicas] + [
            rt for rt in sorted(replicas) if rt not in order
        ]

    # ------------------------------------------------------------- status
    def _is_worker0_completed(self, job, replicas: Dict[str, ReplicaSpec], pods) -> bool:
        """True iff the worker-0 pod succeeded with exit code 0 (reference
        IsWorker0Completed tfjob_controller.go:599-640); vacuously true with
        no worker group."""
        if tfapi.REPLICA_TYPE_WORKER not in replicas:
            return True
        pods = filter_pods_for_replica_type(pods, tfapi.REPLICA_TYPE_WORKER)
        slices = get_pod_slices(
            pods, replicas[tfapi.REPLICA_TYPE_WORKER].replicas or 0
        )
        for index, pod_slice in enumerate(slices):
            if index == 0 and len(pod_slice) == 1:
                pod = pod_slice[0]
                exit_code = get_container_exit_code(pod, self.default_container_name)
                if exit_code == 0 and pod.status.phase == POD_SUCCEEDED:
                    return True
        return False

    def update_job_status(
        self, job, replicas: Dict[str, ReplicaSpec], job_status: JobStatus, pods
    ) -> None:
        """The TF condition state machine (reference UpdateJobStatus
        tfjob_controller.go:353-510)."""
        now = self.clock()
        worker0_completed = self._is_worker0_completed(job, replicas, pods)
        # A retryable restart was initiated this sync: don't set Running (it
        # would clobber the Restarting condition the failed>0 guard needs).
        restarting = getattr(job_status, "_restarting_this_sync", False)

        if job_status.start_time is None:
            job_status.start_time = now

        has_chief = contain_chief_or_master_spec(replicas)
        for rtype in self.replica_order(replicas):
            spec = replicas[rtype]
            status = job_status.replica_statuses.get(rtype)
            if status is None:
                continue
            succeeded = status.succeeded
            expected = (spec.replicas or 0) - succeeded
            running = status.active
            failed = status.failed

            if has_chief:
                if tfapi.is_chief_or_master(rtype):
                    if running > 0 and not restarting:
                        capi.update_job_conditions(
                            job_status,
                            capi.JOB_RUNNING,
                            constants.job_reason(self.kind, constants.REASON_RUNNING),
                            f"TFJob {job.key()} is running.",
                            now=now,
                        )
                    if expected == 0:
                        self._mark_succeeded(job, job_status, now)
            elif rtype == tfapi.REPLICA_TYPE_WORKER:
                # Succeed when all workers finish, or when worker-0 finishes
                # under the default success policy (reference :440-470).
                all_workers_done = expected == 0
                if all_workers_done or (
                    worker0_completed
                    and job.spec.success_policy != tfapi.SUCCESS_POLICY_ALL_WORKERS
                ):
                    self._mark_succeeded(job, job_status, now)
                elif running > 0 and not restarting:
                    capi.update_job_conditions(
                        job_status,
                        capi.JOB_RUNNING,
                        constants.job_reason(self.kind, constants.REASON_RUNNING),
                        f"TFJob {job.key()} is running.",
                        now=now,
                    )

            if failed > 0:
                if restarting:
                    # Restarting wins over Failed for the sync that initiated
                    # it (reference :473-501 checks the stale condition, but
                    # that wedges a job whose recreated pod fails with a
                    # permanent code before being seen Running; this-sync
                    # scoping keeps the reference behavior without the hang).
                    pass
                else:
                    msg = (
                        f"TFJob {job.key()} has failed because {failed} {rtype} "
                        "replica(s) failed."
                    )
                    if job_status.completion_time is None:
                        job_status.completion_time = now
                    capi.update_job_conditions(
                        job_status,
                        capi.JOB_FAILED,
                        constants.job_reason(self.kind, constants.REASON_FAILED),
                        msg,
                        now=now,
                    )
                    record_event_best_effort(
                        self.cluster,
                        Event(
                            type="Normal",
                            reason=constants.job_reason(self.kind, constants.REASON_FAILED),
                            message=msg,
                            involved_object=f"{job.kind}/{job.key()}",
                        )
                    )

    def _mark_succeeded(self, job, job_status: JobStatus, now: float) -> None:
        msg = f"TFJob {job.key()} successfully completed."
        if job_status.completion_time is None:
            job_status.completion_time = now
        capi.update_job_conditions(
            job_status,
            capi.JOB_SUCCEEDED,
            constants.job_reason(self.kind, constants.REASON_SUCCEEDED),
            msg,
            now=now,
        )
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=constants.job_reason(self.kind, constants.REASON_SUCCEEDED),
                message=msg,
                involved_object=f"{job.kind}/{job.key()}",
            )
        )
