"""XGBoostJob controller.

Reference parity: pkg/controller.v1/xgboost/xgboostjob_controller.go —
Rabit/LightGBM env injection (xgboost.go SetPodEnv) and master-based status
(UpdateJobStatus :330-405).
"""

from __future__ import annotations

from typing import Dict, List

from ..api import xgboostjob as xgbapi
from ..api.common import JobStatus, ReplicaSpec
from ..bootstrap import rabit
from . import register
from ._master_status import update_master_based_status
from .base import FrameworkController


@register(xgbapi.KIND)
class XGBoostController(FrameworkController):
    kind = xgbapi.KIND
    default_container_name = xgbapi.DEFAULT_CONTAINER_NAME
    default_port_name = xgbapi.DEFAULT_PORT_NAME
    default_port = xgbapi.DEFAULT_PORT

    def set_cluster_spec(self, job, template, rtype: str, index: int) -> None:
        env = rabit.gen_env(job, rtype, index)
        for container in template.spec.containers:
            for name, value in env.items():
                if container.get_env(name) is None:
                    container.set_env(name, value)

    def is_master_role(self, replicas: Dict[str, ReplicaSpec], rtype: str, index: int) -> bool:
        """reference xgboostjob_controller.go:446-449"""
        return rtype == xgbapi.REPLICA_TYPE_MASTER

    def replica_order(self, replicas: Dict[str, ReplicaSpec]) -> List[str]:
        order = [xgbapi.REPLICA_TYPE_MASTER, xgbapi.REPLICA_TYPE_WORKER]
        return [rt for rt in order if rt in replicas] + [
            rt for rt in sorted(replicas) if rt not in order
        ]

    def update_job_status(
        self, job, replicas: Dict[str, ReplicaSpec], job_status: JobStatus, pods
    ) -> None:
        update_master_based_status(self, job, replicas, job_status, xgbapi.REPLICA_TYPE_MASTER)
