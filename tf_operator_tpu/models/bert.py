"""BERT encoder (Flax) — the BASELINE.md "BERT-base PyTorchJob PJRT/XLA"
config, built natively instead of routed through torch-XLA.

The reference runs BERT as a PyTorchJob user container over c10d
(pytorch.go:27-82 env contract). TPU-natively the same workload is this
Flax encoder trained under `pjit`; the PyTorchJob controller remains for
genuine torch containers, but the framework's own path needs no bridge.

TPU-first choices mirror the Llama flagship: bf16 params/activations,
fp32 softmax via the shared attention op (Pallas flash kernel on TPU),
remat per layer, static shapes (pad/truncate to `max_len`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import flash_attention, xla_attention


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_len: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.bfloat16
    remat: bool = True
    # Checkpoint policy under remat (same vocabulary as models/llama.py):
    # nn.remat's default saves NOTHING (maximum recompute); "dots" keeps
    # the matmul outputs so the backward replays only elementwise/norm
    # work — measured on v5e it is pure win at bert-base's activation
    # footprint.
    remat_policy: str = "dots"
    # "pallas" = the non-causal flash kernel on TPU (measured +4 MFU
    # points over the einsum-softmax path at bert-base/seq 512 — the
    # [b, h, s, s] fp32 score tensor never round-trips HBM); the code
    # auto-falls back to the XLA path off-TPU and whenever a padding
    # mask is present (the flash kernel has no mask input).
    attention_impl: str = "pallas"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def param_count(self) -> int:
        d, f = self.dim, self.ffn_dim
        embed = (self.vocab_size + self.max_len + self.type_vocab_size) * d + 2 * d
        per_layer = 4 * d * d + 4 * d + 2 * d * f + d + f + 4 * d
        return int(embed + self.n_layers * per_layer)

    def flops_per_token(self, seq: Optional[int] = None) -> float:
        p = self.param_count()
        attn = 12 * self.n_layers * self.dim * (seq or self.max_len)
        return 6 * p + attn


CONFIGS = {
    "bert-base": BertConfig(),
    "bert-large": BertConfig(dim=1024, n_layers=24, n_heads=16, ffn_dim=4096),
    "bert-tiny": BertConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, ffn_dim=128, max_len=128,
        remat=False,
    ),
}


class SelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask):
        cfg = self.config
        b, s, _ = x.shape
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.n_heads, cfg.head_dim),
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name=name,
        )
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        if cfg.attention_impl == "pallas" and attention_mask is None:
            out = flash_attention(q, k, v, causal=False)
        else:
            # Additive padding mask folded into the shared fp32-softmax path.
            bias = None
            if attention_mask is not None:
                bias = jnp.where(attention_mask[:, None, None, :], 0.0, -1e9)
            out = xla_attention(q, k, v, causal=False, bias=bias)
        out = out.reshape(b, s, cfg.dim)
        return nn.Dense(cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="out")(out)


class Layer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask):
        from ..parallel.sharding import DATA_AXES, constrain

        cfg = self.config
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.norm_eps, dtype=jnp.float32, param_dtype=jnp.float32, name=name
        )
        # Residual-stream boundary annotations, mirroring models/llama.py
        # Block: pin [b, s, d] to the canonical batch layout at layer entry
        # and between the attention and FFN sublayers (no-op without a
        # scoped mesh — the bench's make_train_step_for provides one).
        x = constrain(x, DATA_AXES, None, None)
        # Post-LN, the original BERT arrangement.
        attn = SelfAttention(cfg, name="attention")(x, attention_mask)
        x = ln("ln_attn")((x + attn).astype(jnp.float32)).astype(cfg.dtype)
        x = constrain(x, DATA_AXES, None, None)
        h = nn.Dense(cfg.ffn_dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="ffn_in")(x)
        h = nn.gelu(h)
        # ffn-dim activation stays tp-sharded between the two FFN matmuls
        # (same pin as the Llama MLP).
        h = constrain(h, DATA_AXES, None, "tp")
        h = nn.Dense(cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="ffn_out")(h)
        return constrain(
            ln("ln_ffn")((x + h).astype(jnp.float32)).astype(cfg.dtype),
            DATA_AXES, None, None,
        )


def _remat_policy(cfg: BertConfig):
    return {
        "nothing": jax.checkpoint_policies.nothing_saveable,
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }[cfg.remat_policy]


class Bert(nn.Module):
    """Encoder + tied-embedding MLM head; returns vocab logits (fp32).

    ``return_hidden=True`` yields the post-mlm_ln hidden states instead
    (bf16, [b, s, d]) for the memory-chunked MLM loss: the full
    [b, s, vocab] fp32 logits tensor (~0.5 GB at bs 8 / seq 512 / 30k
    vocab) then never exists whole in HBM — same contract as the Llama
    family (train_step.loss_fn / chunked_cross_entropy)."""

    # Capability flag for train_step.loss_fn and the bench harness.
    supports_return_hidden = True

    config: BertConfig = BertConfig()

    def head_kernel_and_bias(self, params):
        """(kernel [d, vocab] in activation dtype, bias fp32 [vocab]) of
        the tied MLM head, for the chunked-loss path."""
        kernel = params["params"]["tok_embed"]["embedding"].astype(
            self.config.dtype).T
        return kernel, params["params"]["mlm_bias"]

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 return_hidden: bool = False):
        cfg = self.config
        b, s = input_ids.shape
        tok = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="tok_embed")
        pos = nn.Embed(cfg.max_len, cfg.dim, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="pos_embed")
        typ = nn.Embed(cfg.type_vocab_size, cfg.dim, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="type_embed")
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = tok(input_ids) + pos(jnp.arange(s)[None, :]) + typ(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                         param_dtype=jnp.float32, name="ln_embed")(
            x.astype(jnp.float32)
        ).astype(cfg.dtype)

        layer_cls = Layer
        if cfg.remat:
            layer_cls = nn.remat(
                Layer, static_argnums=(), prevent_cse=False,
                policy=_remat_policy(cfg),
            )
        for i in range(cfg.n_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(x, attention_mask)

        # MLM head with tied input embedding.
        x = nn.Dense(cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="mlm_transform")(x)
        x = nn.gelu(x)
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=jnp.float32,
                         param_dtype=jnp.float32, name="mlm_ln")(
            x.astype(jnp.float32)
        )
        bias = self.param("mlm_bias", nn.initializers.zeros, (cfg.vocab_size,), jnp.float32)
        if return_hidden:
            return x.astype(cfg.dtype)
        # bf16 operands, fp32 accumulation: a genuinely fp32 x @ embedding
        # einsum runs the MXU at a fraction of its bf16 rate and was
        # measured costing bert-base several MFU points; fp32 accumulate
        # keeps the softmax numerics.
        logits = jnp.einsum(
            "bsd,vd->bsv", x.astype(cfg.dtype), tok.embedding,
            preferred_element_type=jnp.float32,
        )
        return logits + bias


def make_model(name_or_config="bert-base") -> Bert:
    if isinstance(name_or_config, str):
        return Bert(CONFIGS[name_or_config])
    return Bert(name_or_config)


def init_params(model: Bert, rng, batch: int = 1, seq: Optional[int] = None):
    seq = seq or model.config.max_len
    ids = jnp.zeros((batch, seq), jnp.int32)
    return model.init(rng, ids)["params"]
