"""MNIST ConvNet (Flax) — parity with the reference's dist-mnist workload.

The reference's canonical e2e example is examples/tensorflow/dist-mnist/
dist_mnist.py (between-graph PS/Worker training, SyncReplicasOptimizer,
dist_mnist.py:98-143). This is its TPU-native counterpart: the same
two-conv/two-dense topology, trained data-parallel with `pjit` over the
mesh that `tpu_init` builds — the BASELINE.md "MNIST single-worker TFJob →
functional" row.

Runs on anything (CPU dev box → one TPU chip → a slice); images are NHWC
fp32 in, compute in bf16, logits fp32.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class MnistConfig:
    num_classes: int = 10
    hidden: int = 128
    dtype: Any = jnp.bfloat16


class MnistCNN(nn.Module):
    """conv5x5x32 → pool → conv5x5x64 → pool → dense → logits, the
    dist_mnist.py topology (dist_mnist.py:148-186)."""

    config: MnistConfig = MnistConfig()

    @nn.compact
    def __call__(self, images):
        cfg = self.config
        x = images.astype(cfg.dtype)
        if x.ndim == 3:
            x = x[..., None]  # [b, 28, 28] -> NHWC
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=cfg.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=cfg.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(cfg.hidden, dtype=cfg.dtype)(x)
        x = nn.relu(x)
        logits = nn.Dense(cfg.num_classes, dtype=jnp.float32)(x)
        return logits.astype(jnp.float32)


def make_model(config: Optional[MnistConfig] = None) -> MnistCNN:
    return MnistCNN(config or MnistConfig())


def init_params(model: MnistCNN, rng, batch: int = 1):
    images = jnp.zeros((batch, 28, 28, 1), jnp.float32)
    return model.init(rng, images)["params"]


def loss_and_accuracy(model: MnistCNN, params, images, labels):
    logits = model.apply({"params": params}, images)
    one_hot = jax.nn.one_hot(labels, logits.shape[-1])
    loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), axis=-1))
    accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
    return loss, accuracy


class SyntheticMnist:
    """Deterministic synthetic digits: class-dependent blobs, learnable in a
    few steps — stands in for the real download in hermetic environments
    (the reference's e2e substitutes a controllable test-server the same
    way, SURVEY.md §4 T3)."""

    def __init__(self, batch: int, seed: int = 0):
        self.batch = batch
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        return self

    def __next__(self):
        labels = self._rng.integers(0, 10, size=(self.batch,))
        images = self._rng.normal(0.1, 0.25, size=(self.batch, 28, 28, 1))
        # Signal: a bright row per class.
        for i, lab in enumerate(labels):
            images[i, 2 + 2 * lab : 4 + 2 * lab, :, 0] += 1.5
        return images.astype(np.float32), labels.astype(np.int32)
