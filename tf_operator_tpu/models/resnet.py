"""ResNet (Flax) — the BASELINE.md "ResNet-50 TPUStrategy" config.

The reference drives ResNet through TF's TPUStrategy inside user
containers; TPU-natively the same job is a JAXJob running this model
data-parallel under `pjit`. TPU-first choices:

- NHWC layout (XLA:TPU's native conv layout) with bf16 compute.
- BatchNorm statistics in fp32; `axis_name="batch"` cross-replica sync is
  the caller's choice (pass use_running_average for eval).
- All convs stride through `nn.Conv` so XLA fuses conv+BN+relu chains.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    num_filters: int = 64
    bottleneck: bool = True
    dtype: Any = jnp.bfloat16
    # Cross-replica BatchNorm axis (sync-BN). Only valid under
    # pmap/shard_map with this axis bound; plain pjit data-parallel keeps
    # per-shard stats (None), which is the usual large-batch choice.
    sync_bn_axis: Any = None


CONFIGS = {
    "resnet18": ResNetConfig(stage_sizes=(2, 2, 2, 2), bottleneck=False),
    "resnet50": ResNetConfig(),
    "resnet101": ResNetConfig(stage_sizes=(3, 4, 23, 3)),
    # CI/dev-sized: two tiny stages, 8 classes.
    "resnet-tiny": ResNetConfig(
        stage_sizes=(1, 1), num_classes=8, num_filters=8, bottleneck=False
    ),
}


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig = ResNetConfig()

    @nn.compact
    def __call__(self, images, train: bool = True):
        cfg = self.config
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,  # BN stats/params stay fp32
            axis_name=cfg.sync_bn_axis if train else None,
        )
        block = BottleneckBlock if cfg.bottleneck else BasicBlock

        x = images.astype(cfg.dtype)
        x = conv(cfg.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(cfg.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block(cfg.num_filters * 2**i, strides, conv, norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(cfg.num_classes, dtype=jnp.float32)(x)
        return logits.astype(jnp.float32)


def make_model(name_or_config="resnet50") -> ResNet:
    if isinstance(name_or_config, str):
        return ResNet(CONFIGS[name_or_config])
    return ResNet(name_or_config)


def init_variables(model: ResNet, rng, batch: int = 1, image_size: int = 224):
    """Returns the full variable dict: {'params', 'batch_stats'}."""
    images = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    return model.init(rng, images, train=False)
