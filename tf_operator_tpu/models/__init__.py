"""Model zoo for the example/benchmark workloads.

The reference ships training *scripts* as examples (examples/tensorflow/
dist-mnist, examples/pytorch/mnist, …) because the operator launches user
containers. This package is their TPU-native equivalent: Flax models used by
the JAXJob examples and the benchmark harness — `llama` (the flagship,
BASELINE.md Llama-2-7B target), `mnist` (MLP/CNN parity with dist-mnist),
`resnet` and `bert` (the ResNet-50 / BERT-base BASELINE configs).
"""

from . import bert, llama, mnist, resnet

__all__ = ["bert", "llama", "mnist", "resnet"]
